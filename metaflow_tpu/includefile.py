"""IncludeFile: a file-as-parameter, stored once in the flow datastore.

Reference behavior: metaflow/includefile.py (IncludeFile:234) with the
versioned uploader protocol (UploaderV1:386, UploaderV2:478). Design here:

  - the parameter ARTIFACT is a small versioned DESCRIPTOR
    ({"type": "tpuflow-include/v1", "key": <sha>, ...}), never the file
    content — persisting a run never re-serializes the payload, and the
    content-vs-path question is answered by an explicit type marker, not
    a heuristic;
  - upload streams the file into the content-addressed store in 1 MiB
    chunks (chunked SHA-256 + file-to-file copy / GCS put_file), so a
    multi-GB include runs at bounded RSS; repeat uploads dedup by hash;
  - reads are lazy: user code gets an `IncludedFile` handle with
    `.text` / `.blob` (load into memory) and `.stream()` / `.save_to()`
    (bounded RSS) accessors;
  - resume and event-triggered runs replay the DESCRIPTOR, so the
    original path never needs to exist again and the content is not
    re-uploaded.
"""

import os

from . import knobs
from .exception import TpuFlowException
from .parameters import Parameter

# refuse absurd includes before reading anything: artifacts are the
# inter-task data channel, not a bulk-data path (use the datastore or
# gsop directly for datasets)
MAX_SIZE_MB_ENV = "TPUFLOW_INCLUDEFILE_MAX_MB"
DEFAULT_MAX_SIZE_MB = 10 * 1024


class IncludedFile(object):
    """Lazy handle to a file stored once in the flow's datastore.

    Pickles (and JSON-encodes, via `.descriptor`) as the small descriptor;
    content loads only when an accessor is called."""

    TYPE = "tpuflow-include/v1"
    # pre-descriptor runs stored the file CONTENT as the parameter
    # artifact; resume wraps those in this marker (by PROVENANCE — the
    # value came from an IncludeFile parameter's artifact — never by
    # sniffing the string)
    INLINE_TYPE = "tpuflow-include-inline/v1"

    @classmethod
    def legacy_inline_descriptor(cls, value):
        """Wrap a legacy content-artifact (str/bytes) for replay."""
        import base64

        if isinstance(value, bytes):
            return {"type": cls.INLINE_TYPE, "b64": True,
                    "content": base64.b64encode(value).decode("ascii")}
        return {"type": cls.INLINE_TYPE, "b64": False, "content": value}

    def __init__(self, descriptor):
        self._d = dict(descriptor)

    # ---- identity ----

    @property
    def descriptor(self):
        return dict(self._d)

    @property
    def key(self):
        return self._d["key"]

    @property
    def size(self):
        return int(self._d.get("size") or 0)

    @property
    def is_text(self):
        return bool(self._d.get("is_text", True))

    @property
    def encoding(self):
        return self._d.get("encoding") or "utf-8"

    def __reduce__(self):
        return (IncludedFile, (self._d,))

    def __repr__(self):
        return "IncludedFile(key=%s, size=%d, %s)" % (
            self.key[:12], self.size,
            "text" if self.is_text else "binary",
        )

    # NOTE: deliberately no __len__ — an included EMPTY file must still be
    # truthy so `if self.param:` distinguishes "provided empty file" from
    # "parameter absent"; use .size for the byte count.

    # ---- content access ----

    def _datastore(self):
        from .datastore import STORAGE_BACKENDS, FlowDataStore

        ds_type = self._d.get("ds_type", "local")
        backend = STORAGE_BACKENDS.get(ds_type)
        if backend is None:
            raise TpuFlowException(
                "IncludedFile stored in unknown datastore type %r" % ds_type
            )
        return FlowDataStore(
            self._d["flow_name"], backend, ds_root=self._d.get("ds_root")
        )

    def stream(self, chunk_size=1 << 20, flow_datastore=None):
        """Yield the content in chunks at bounded RSS."""
        fds = flow_datastore or self._datastore()
        with fds.open_data_stream(self.key) as f:
            while True:
                chunk = f.read(chunk_size)
                if not chunk:
                    return
                yield chunk

    def save_to(self, path, flow_datastore=None):
        """Download the content to `path` at bounded RSS."""
        with open(path, "wb") as out:
            for chunk in self.stream(flow_datastore=flow_datastore):
                out.write(chunk)
        return path

    @property
    def blob(self):
        """The raw bytes (loads the whole payload into memory)."""
        return b"".join(self.stream())

    @property
    def text(self):
        """The decoded text (loads the whole payload into memory)."""
        return self.blob.decode(self.encoding)


class IncludeFile(Parameter):
    IS_INCLUDE_FILE = True

    def __init__(self, name, required=False, is_text=True, encoding="utf-8",
                 default=None, help=None):
        super().__init__(name, required=required, default=default, help=help)
        self.is_text = is_text
        self.encoding = encoding

    def convert(self, value):
        """Datastore-less conversion: only already-uploaded forms pass
        through (descriptor dict or IncludedFile); the upload itself needs
        `include()` with a datastore."""
        if value is None or isinstance(value, IncludedFile):
            return value
        if isinstance(value, dict) and value.get("type") == IncludedFile.TYPE:
            return IncludedFile(value)
        raise TpuFlowException(
            "IncludeFile *%s* got %r without a datastore to upload into — "
            "this is a framework bug (task parameter init must call "
            "include())." % (self.name, type(value).__name__)
        )

    def include(self, value, flow_datastore):
        """Resolve a parameter value into an IncludedFile.

        Explicit encoding, no content heuristics: a dict bearing the
        descriptor type marker is an already-uploaded file (resume /
        trigger replay); a string is ALWAYS a filesystem path, which must
        exist; anything else is an error."""
        if value is None or isinstance(value, IncludedFile):
            return value
        if isinstance(value, dict):
            if value.get("type") == IncludedFile.INLINE_TYPE:
                return self._include_legacy_inline(value, flow_datastore)
            if value.get("type") != IncludedFile.TYPE:
                raise TpuFlowException(
                    "IncludeFile *%s*: unrecognized descriptor %r"
                    % (self.name, value.get("type"))
                )
            # descriptor replay (resume/trigger) re-references the payload:
            # refresh its gc registry timestamp so the blob outlives the
            # NEW run, not just the original upload's retention window
            if value.get("key"):
                try:
                    flow_datastore._register_data_keys([value["key"]])
                except Exception:
                    pass  # a read-only datastore view must still resolve
            return IncludedFile(value)
        path = os.path.expanduser(str(value))
        if not os.path.isfile(path):
            raise TpuFlowException(
                "IncludeFile *%s*: file '%s' does not exist." % (self.name,
                                                                 path)
            )
        size = os.path.getsize(path)
        max_mb = knobs.get_int(MAX_SIZE_MB_ENV)
        if size > max_mb << 20:
            raise TpuFlowException(
                "IncludeFile *%s*: '%s' is %.1f MB, over the %d MB limit "
                "(%s) — artifacts are the inter-task control channel; "
                "ship bulk data through the datastore/gsop directly."
                % (self.name, path, size / 1048576.0, max_mb,
                   MAX_SIZE_MB_ENV)
            )
        _uri, key = flow_datastore.save_file(path)
        return self._descriptor_for(key, size, flow_datastore)

    def _descriptor_for(self, key, size, flow_datastore):
        return IncludedFile({
            "type": IncludedFile.TYPE,
            "key": key,
            "size": size,
            "is_text": self.is_text,
            "encoding": self.encoding,
            "ds_type": flow_datastore.ds_type,
            "ds_root": flow_datastore.ds_root,
            "flow_name": flow_datastore.flow_name,
        })

    def _include_legacy_inline(self, value, flow_datastore):
        """Replay a pre-descriptor content artifact: upload the content
        once (in memory — legacy artifacts were in-memory by definition)
        and hand back a normal lazy descriptor."""
        import base64

        content = value.get("content") or ""
        if value.get("b64"):
            data = base64.b64decode(content)
        else:
            data = content.encode(self.encoding)
        results = flow_datastore.save_data([data])
        (_uri, key) = results[0]
        return self._descriptor_for(key, len(data), flow_datastore)
