"""Unbounded-foreach marker types.

Reference behavior: metaflow/unbounded_foreach.py + flowspec.py ParallelUBF:68.
An unbounded foreach is one whose cardinality the scheduler does not expand
itself: it queues ONE control task which is responsible for the gang. On TPU
the gang is a pod slice: control = host 0 (SURVEY.md §2.9)."""

UBF_CONTROL = "ubf_control"
UBF_TASK = "ubf_task"
CONTROL_TASK_TAG = "control_task"


class UnboundedForeachInput(object):
    """Marker base class: a foreach over an instance of this class is
    scheduled as a single control task."""

    NAME = "UnboundedForeachInput"

    def __getitem__(self, item):
        # the control task "indexes" the input with None
        return self


class ParallelUBF(UnboundedForeachInput):
    """Unbounded-foreach behind `self.next(step, num_parallel=N)`."""

    def __init__(self, num_parallel):
        self.num_parallel = num_parallel

    def __getitem__(self, item):
        # the gang rank for workers; the control task passes None
        return item or 0

    def __len__(self):
        return self.num_parallel

    def __repr__(self):
        return "ParallelUBF(%d)" % self.num_parallel
