"""Sharded on-datastore corpus format: fixed-size token shard blobs plus
a JSON index manifest.

Layout (all under the flow's datastore):

    <flow>/data/<xx>/<sha256>          one raw CAS blob per shard
    <flow>/_datasets/<name>/manifest.json

Shard blobs are the little-endian bytes of a 1-D token array slice,
stored RAW (token data is incompressible; gzip would only burn CPU on
the hot read path) through the content-addressed store — so they ride
the SAME batched `save_bytes` path artifacts use (pipelined-persist
concurrency, compose heuristics) and the SAME `FileCache` read-through
on load. The CAS key IS the shard checksum: sha256 of the payload,
verified in flight by the reader (reader.py).

The manifest is the index: dtype (with explicit byte order), token
counts, per-shard keys/sizes. Its schema is pinned in
tests/schema_validate.py::DATASET_MANIFEST_SCHEMA.

Build via the CLI (`python -m metaflow_tpu dataset build ...`,
cmd/dataset.py) or `build_corpus()` directly.
"""

import hashlib
import json

import numpy as np

from ..exception import TpuFlowException

DATASET_PREFIX = "_datasets"
MANIFEST_VERSION = 1

# 4M tokens/shard: 16 MB of int32 — large enough that per-request
# overhead amortizes, small enough that a readahead window holds several
DEFAULT_SHARD_TOKENS = 4 * 1024 * 1024


class DatasetError(TpuFlowException):
    headline = "Dataset error"


def dataset_path(flow_datastore, name, *suffix):
    return flow_datastore.storage.path_join(
        flow_datastore.flow_name, DATASET_PREFIX, name, *suffix)


def _manifest_path(flow_datastore, name):
    return dataset_path(flow_datastore, name, "manifest.json")


def _check_name(name):
    if not name or "/" in name or name.startswith("_") or name != name.strip():
        raise DatasetError(
            "invalid dataset name %r (no slashes, no leading underscore)"
            % name)


def build_corpus(flow_datastore, name, tokens,
                 shard_tokens=DEFAULT_SHARD_TOKENS, overwrite=False,
                 dtype=None):
    """Pack a 1-D token array into shard blobs + manifest; returns the
    manifest dict.

    `tokens` may be any 1-D array-like (incl. a np.memmap over a corpus
    file — shards are sliced and converted one at a time, so peak RSS is
    one shard regardless of corpus size). `dtype` recasts per shard on
    the way out (a whole-array cast would materialize the memmap);
    default preserves the input dtype. Either way the manifest pins it
    little-endian so a corpus built on any host decodes identically
    everywhere.
    """
    _check_name(name)
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise DatasetError("tokens must be 1-D, got shape %s"
                           % (tokens.shape,))
    if tokens.size == 0:
        raise DatasetError("refusing to build an empty corpus")
    shard_tokens = int(shard_tokens)
    if shard_tokens <= 0:
        raise DatasetError("shard_tokens must be positive, got %d"
                           % shard_tokens)
    if not overwrite and load_manifest(flow_datastore, name,
                                       missing_ok=True) is not None:
        raise DatasetError(
            "dataset %r already exists (pass overwrite to rebuild)" % name)

    dtype = (np.dtype(dtype) if dtype is not None
             else tokens.dtype).newbyteorder("<")
    bounds = [(start, min(start + shard_tokens, tokens.size))
              for start in range(0, tokens.size, shard_tokens)]

    def blob_iter():
        for start, stop in bounds:
            yield np.ascontiguousarray(
                tokens[start:stop], dtype=dtype).tobytes()

    # raw CAS blobs through the batched persist path; save_data also
    # registers the keys so gc's mark phase keeps the corpus live
    results = flow_datastore.save_data(blob_iter())
    shards = [
        {"key": key, "tokens": int(stop - start),
         "bytes": int((stop - start) * dtype.itemsize), "sha256": key}
        for (_uri, key), (start, stop) in zip(results, bounds)
    ]
    manifest = {
        "v": MANIFEST_VERSION,
        "name": name,
        "dtype": dtype.str,
        "total_tokens": int(tokens.size),
        "shard_tokens": shard_tokens,
        "n_shards": len(shards),
        "shards": shards,
    }
    flow_datastore.storage.save_bytes(
        [(_manifest_path(flow_datastore, name),
          json.dumps(manifest, sort_keys=True).encode("utf-8"))],
        overwrite=True,
    )
    return manifest


def append_corpus(flow_datastore, name, tokens, generation=None,
                  dtype=None):
    """Append a 1-D token array to an EXISTING corpus as new shard blobs
    plus a manifest rewrite; returns the updated manifest dict.

    This is the replay-buffer write path (metaflow_tpu/online/replay.py)
    and `tpuflow dataset build --append`. The manifest stays v1 but
    gains/bumps an integer `revision` (absent == 0 for manifests written
    before appends existed), so a writer's publish is observable:
    readers that hold the OLD manifest dict keep streaming exactly the
    token order they started with (shard entries are append-only and
    existing blobs are immutable CAS objects), while readers that reload
    the manifest see the growth and pick it up at their next epoch
    boundary.

    `generation` optionally stamps every appended shard entry with the
    weight generation that produced its tokens — the freshness key the
    online ReplayReader's max-staleness window filters on. Shards from
    the original build (or generation-less appends) count as
    generation 0.

    The append's trailing shard may be short (shard_tokens is the pack
    size, not a guarantee): StreamingTokenBatches windows are sliced
    per-shard, so appended text never straddles a shard boundary, and a
    mid-corpus short shard simply contributes fewer windows. Writers
    that must not lose tokens to partial windows (the replay path) keep
    both shard_tokens and each append a multiple of their window size.
    """
    manifest = load_manifest(flow_datastore, name)
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise DatasetError("tokens must be 1-D, got shape %s"
                           % (tokens.shape,))
    if tokens.size == 0:
        raise DatasetError("refusing to append zero tokens to %r" % name)
    want = np.dtype(manifest["dtype"])
    if dtype is not None and np.dtype(dtype).newbyteorder("<") != want:
        raise DatasetError(
            "dataset %r stores %s tokens; cannot append as %s"
            % (name, manifest["dtype"], np.dtype(dtype).str))
    shard_tokens = int(manifest["shard_tokens"])
    bounds = [(start, min(start + shard_tokens, tokens.size))
              for start in range(0, tokens.size, shard_tokens)]

    def blob_iter():
        for start, stop in bounds:
            yield np.ascontiguousarray(
                tokens[start:stop], dtype=want).tobytes()

    results = flow_datastore.save_data(blob_iter())
    for (_uri, key), (start, stop) in zip(results, bounds):
        shard = {"key": key, "tokens": int(stop - start),
                 "bytes": int((stop - start) * want.itemsize),
                 "sha256": key}
        if generation is not None:
            shard["generation"] = int(generation)
        manifest["shards"].append(shard)
    manifest["n_shards"] = len(manifest["shards"])
    manifest["total_tokens"] = int(manifest["total_tokens"]
                                   + tokens.size)
    manifest["revision"] = int(manifest.get("revision", 0)) + 1
    flow_datastore.storage.save_bytes(
        [(_manifest_path(flow_datastore, name),
          json.dumps(manifest, sort_keys=True).encode("utf-8"))],
        overwrite=True,
    )
    return manifest


def manifest_revision(manifest):
    """The append revision of a manifest dict (0 = never appended)."""
    return int(manifest.get("revision", 0))


def shard_generation(shard):
    """The weight generation stamped on a shard entry (0 = unstamped:
    original build or a generation-less append)."""
    return int(shard.get("generation", 0))


def load_manifest(flow_datastore, name, missing_ok=False):
    """The manifest dict of a built dataset, or None (missing_ok)."""
    _check_name(name)
    path = _manifest_path(flow_datastore, name)
    with flow_datastore.storage.load_bytes([path]) as loaded:
        for _p, local, _m in loaded:
            if local is None:
                break
            with open(local) as f:
                manifest = json.load(f)
            if manifest.get("v") != MANIFEST_VERSION:
                raise DatasetError(
                    "dataset %r has manifest version %r; this reader "
                    "understands v%d" % (name, manifest.get("v"),
                                         MANIFEST_VERSION))
            return manifest
    if missing_ok:
        return None
    raise DatasetError(
        "dataset %r not found in flow %s's datastore (build it with "
        "`python -m metaflow_tpu dataset build`)"
        % (name, flow_datastore.flow_name))


def list_datasets(flow_datastore):
    """Names of built datasets in this flow's datastore."""
    prefix = flow_datastore.storage.path_join(
        flow_datastore.flow_name, DATASET_PREFIX)
    return sorted(
        flow_datastore.storage.basename(p)
        for p, is_file in flow_datastore.storage.list_content([prefix])
        if not is_file
    )


def decode_shard(manifest, index, blob):
    """One shard blob → its 1-D token array (zero-copy view over the
    fetched bytes; callers slice windows out of it)."""
    shard = manifest["shards"][index]
    arr = np.frombuffer(blob, dtype=np.dtype(manifest["dtype"]),
                        count=shard["tokens"])
    if arr.size != shard["tokens"]:
        raise DatasetError(
            "shard %d of %s decoded to %d tokens, manifest says %d"
            % (index, manifest.get("name"), arr.size, shard["tokens"]))
    return arr


def verify_blob(shard, blob):
    """True iff `blob` matches the shard's manifest checksum."""
    return (len(blob) == shard["bytes"]
            and hashlib.sha256(blob).hexdigest() == shard["sha256"])
