"""Bounded-readahead parallel shard reader.

The datastore→host side of the streaming input pipeline: a thread pool
fetches shard blobs AHEAD of consumption so the loader (and through it
the device) never waits on the network in steady state — the same
keep-the-MXU-fed argument as device prefetch in training/data.py, one
level down the memory hierarchy.

  - the readahead window is measured in BYTES (TPUFLOW_DATA_READAHEAD_MB,
    default 64), not shards, so corpora with different shard sizes get
    the same memory bound;
  - every fetched blob is checksum-verified in flight against the
    manifest (the CAS key is the sha256); a mismatch retries ONCE
    bypassing the blob cache — a corrupted cache entry heals, a
    corrupted object in the store is a hard ShardCorruptionError;
  - per-blob retry/backoff on transient storage errors is inherited from
    the gsop engine underneath storage.load_bytes;
  - shard ORDER is the caller's: the loader passes each host its own
    deterministic slice of the epoch's shard order (host_slice), so every
    host of a gang reads only its 1/n of the corpus.

Telemetry (names pinned in tests/schema_validate.py):
  data.shard_fetch        timer, per fetched blob ({shard, bytes, retried})
  data.readahead_occupancy gauge, readahead-window fill fraction at each
                          consumer take ({bytes, shards, window_bytes})
  data.shard_retry        counter, checksum-mismatch refetches
"""

import collections
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout

from .. import knobs, telemetry
from ..exception import TpuFlowException
from .shards import decode_shard, verify_blob

DEFAULT_READAHEAD_MB = 64
DEFAULT_WORKERS = 8


class ShardCorruptionError(TpuFlowException):
    headline = "Corrupted dataset shard"


def readahead_bytes_from_env():
    mb = knobs.get_float("TPUFLOW_DATA_READAHEAD_MB")
    return max(1, int(mb * 1024 * 1024))


def host_slice(order, host_index, n_hosts):
    """The shards host `host_index` of `n_hosts` consumes, given the
    epoch's global shard order: a stride-slice, so host sets are disjoint
    and together cover every shard exactly once."""
    if not 0 <= int(host_index) < int(n_hosts):
        raise ValueError("host_index=%s out of range for n_hosts=%s"
                         % (host_index, n_hosts))
    return [int(s) for s in order[int(host_index)::int(n_hosts)]]


class ShardReader(object):
    """Parallel prefetching reader over one corpus manifest.

    `stream(shard_ids)` yields (shard_id, token_array) in the GIVEN
    order; up to `readahead_bytes` of further shards are in flight or
    ready at any time. `stats` accumulates fetch/retry/occupancy/wait
    figures across streams (the data bench reads them)."""

    def __init__(self, flow_datastore, manifest, max_workers=None,
                 readahead_bytes=None, verify=True):
        self._fds = flow_datastore
        self._manifest = manifest
        if max_workers is None:
            max_workers = knobs.get_int("TPUFLOW_DATA_WORKERS")
        self._max_workers = max(1, max_workers)
        self._readahead = (readahead_bytes if readahead_bytes
                           else readahead_bytes_from_env())
        self._verify = verify
        self.stats = {"fetches": 0, "retries": 0, "bytes": 0,
                      "wait_ms": 0.0, "occupancy_sum": 0.0,
                      "occupancy_samples": 0}
        # fetches/retries/bytes are bumped from pool worker threads;
        # += on a dict entry is a read-modify-write that loses updates
        # without a lock (the bench and tests read exact counts)
        self._stats_lock = threading.Lock()

    # ---------- blob fetch (worker threads) ----------

    def _fetch_from_storage(self, key):
        """Cache-bypassing fetch straight from storage (the retry path:
        the blob cache may hold the corrupted copy)."""
        cas = self._fds.ca_store
        with cas.storage.load_bytes([cas.blob_path(key)]) as loaded:
            for _path, local, _meta in loaded:
                if local is None:
                    raise KeyError(
                        "dataset shard blob %s not found in datastore"
                        % key)
                with open(local, "rb") as f:
                    return cas._unpack(f.read())

    def _fetch(self, shard_id):
        shard = self._manifest["shards"][shard_id]
        key = shard["key"]
        start = time.perf_counter()
        retried = False
        blob = None
        for _k, b in self._fds.ca_store.load_blobs([key]):
            blob = b
        if self._verify and not (blob is not None
                                 and verify_blob(shard, blob)):
            # a bad cache entry (bit rot on local disk) must not kill the
            # run: refetch once from the store itself, bypassing the cache
            retried = True
            with self._stats_lock:
                self.stats["retries"] += 1
            telemetry.counter("data.shard_retry",
                              data={"shard": int(shard_id)})
            blob = self._fetch_from_storage(key)
            if not verify_blob(shard, blob):
                raise ShardCorruptionError(
                    "shard %d of dataset %r is corrupted in the datastore "
                    "(sha256 mismatch for key %s after cache-bypass "
                    "refetch)" % (shard_id, self._manifest.get("name"),
                                  key))
            cache = self._fds.ca_store.blob_cache
            if cache is not None:  # heal the poisoned cache entry
                cache.store_key(key, blob)
        tokens = decode_shard(self._manifest, shard_id, blob)
        with self._stats_lock:
            self.stats["fetches"] += 1
            self.stats["bytes"] += len(blob)
        telemetry.emit(
            "timer", "data.shard_fetch",
            ms=(time.perf_counter() - start) * 1000, ok=True,
            data={"shard": int(shard_id), "bytes": len(blob),
                  "retried": retried})
        return tokens

    # ---------- ordered, bounded streaming (consumer side) ----------

    def stream(self, shard_ids):
        """Yield (shard_id, tokens) for `shard_ids` in order, keeping up
        to the readahead window of further shards in flight."""
        shard_ids = [int(s) for s in shard_ids]
        if not shard_ids:
            return
        from ..datastore.storage import storage_timeout_s

        sizes = [self._manifest["shards"][s]["bytes"] for s in shard_ids]
        # consumer-side deadline (TPUFLOW_STORAGE_TIMEOUT_S, 0 = none):
        # the retried network layer underneath has its own per-attempt
        # deadline, so allow the full retry budget's worth of wall clock
        # before declaring the fetch wedged
        timeout_s = storage_timeout_s()
        fetch_timeout = (timeout_s * 8) if timeout_s > 0 else None
        pending = collections.deque()  # (shard_id, size, future)
        inflight = 0
        nxt = 0
        pool = ThreadPoolExecutor(max_workers=self._max_workers)
        wedged = False
        try:
            while pending or nxt < len(shard_ids):
                # top up: always at least one in flight; beyond that,
                # submit while the byte window has room
                while nxt < len(shard_ids) and (
                        not pending
                        or inflight + sizes[nxt] <= self._readahead):
                    sid = shard_ids[nxt]
                    pending.append(
                        (sid, sizes[nxt],
                         pool.submit(self._fetch, sid)))
                    inflight += sizes[nxt]
                    nxt += 1
                occ = min(1.0, inflight / float(self._readahead))
                with self._stats_lock:
                    self.stats["occupancy_sum"] += occ
                    self.stats["occupancy_samples"] += 1
                telemetry.gauge(
                    "data.readahead_occupancy", round(occ, 4),
                    data={"bytes": inflight, "shards": len(pending),
                          "window_bytes": self._readahead})
                sid, size, fut = pending.popleft()
                t0 = time.perf_counter()
                try:
                    tokens = fut.result(timeout=fetch_timeout)
                except FuturesTimeout:
                    wedged = True
                    raise TimeoutError(
                        "shard %d fetch exceeded %.1fs — wedged transfer "
                        "(TPUFLOW_STORAGE_TIMEOUT_S)"
                        % (sid, fetch_timeout))
                with self._stats_lock:
                    self.stats["wait_ms"] += (
                        time.perf_counter() - t0) * 1000
                inflight -= size
                yield sid, tokens
        finally:
            # an abandoned generator (consumer broke out early) exits
            # through GeneratorExit here: cancel the fetches still
            # queued behind the workers — the default pool shutdown
            # would WAIT for them, stalling teardown by up to a full
            # readahead window of downloads nobody will consume — then
            # wait out only the ≤max_workers already running. UNLESS a
            # fetch wedged past its deadline: then even the running
            # workers are unjoinable and the pool is abandoned outright
            # (the TimeoutError must reach the caller, not hang here)
            for _sid, _size, fut in pending:
                fut.cancel()
            pool.shutdown(wait=not wedged, cancel_futures=wedged)

    def mean_occupancy(self):
        n = self.stats["occupancy_samples"]
        return (self.stats["occupancy_sum"] / n) if n else 0.0
