"""Deterministic shuffle orders shared by the streaming and in-memory
token loaders.

Every order here is a PURE function of (seed, epoch[, shard]) — no
iterator state, no RNG objects carried across calls — so a resume stamp
of flat ints fully determines the rest of a stream, and two independently
constructed loaders (one streaming shards from the datastore, one holding
the concatenated array in memory) walk byte-identical token sequences.

The scheme is hierarchical, the shape every at-scale input pipeline uses
(tf.data / grain / MaxText): the SHARD order is permuted per epoch, then
the windows WITHIN each shard are permuted per (epoch, shard). A global
window permutation would need random access across the whole corpus —
exactly the in-memory assumption this subsystem removes.
"""

import numpy as np

# key under which resumable loaders stamp their resume state into each
# batch dict; shard_iterator passes it through host-side (never deviced)
STATE_KEY = "data_state"


def epoch_shard_order(seed, epoch, n_shards):
    """The order shards are consumed in `epoch`. seed=None → sequential."""
    if seed is None:
        return np.arange(n_shards)
    rng = np.random.default_rng([int(seed), int(epoch)])
    return rng.permutation(n_shards)


def shard_window_order(seed, epoch, shard_index, n_windows):
    """The order windows of one shard are consumed in `epoch`. The GLOBAL
    shard index (not its position in the epoch order) keys the RNG, so a
    host reading only its slice of the shard order computes the same
    within-shard orders as a host reading everything."""
    if seed is None:
        return np.arange(n_windows)
    rng = np.random.default_rng([int(seed), int(epoch), int(shard_index)])
    return rng.permutation(n_windows)


def hierarchical_window_order(seed, epoch, n_windows, shard_windows):
    """The epoch's GLOBAL window order when a flat array of `n_windows`
    windows is viewed as shards of `shard_windows` windows each (the last
    shard may be short) — i.e. what a streaming loader over such a corpus
    yields, expressed as indices into the concatenated array. This is how
    ResumableTokenBatches(shard_windows=...) matches StreamingTokenBatches
    byte for byte."""
    shard_windows = int(shard_windows)
    if shard_windows <= 0:
        raise ValueError("shard_windows must be positive, got %d"
                         % shard_windows)
    n_shards = -(-n_windows // shard_windows)
    parts = []
    for s in epoch_shard_order(seed, epoch, n_shards):
        base = int(s) * shard_windows
        count = min(shard_windows, n_windows - base)
        parts.append(base + shard_window_order(seed, epoch, int(s), count))
    if not parts:
        return np.arange(0)
    return np.concatenate(parts)
