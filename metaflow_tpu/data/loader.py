"""StreamingTokenBatches: the ResumableTokenBatches contract over a
sharded on-datastore corpus.

Yields {'tokens': [B, seq_len+1], STATE_KEY: {...}} batches, exactly like
training/data.py::ResumableTokenBatches — but the corpus never
materializes in host memory: shards stream through the bounded-readahead
ShardReader, and each host of a gang reads only its deterministic slice
of the epoch's shard order.

Resume stamp (flat ints, stamped onto EVERY batch under STATE_KEY):

    epoch          epochs completed
    shard_cursor   position in THIS HOST's slice of the epoch shard order
    window_cursor  windows consumed within the current shard's order
    seed           shuffle seed (orders are pure functions of it)
    + geometry cross-checks: batch_size, window, n_shards, total_tokens,
      shard_tokens, host_index, n_hosts, drop_last

`restore(stamp)` positions the stream just after the batch that carried
the stamp — iteration continues with the exact next token, zero replay,
zero skip, including across shard boundaries and epoch rollovers.

Byte-identity with the in-memory loader: when shard_tokens is a multiple
of (seq_len+1), the stream equals ResumableTokenBatches over the
concatenated token array with the same seed and
shard_windows=shard_tokens//(seq_len+1) — both walk the shared
hierarchical order in ordering.py (seed=None matches plain sequential
ResumableTokenBatches too). tests/test_data.py pins this.
"""

import time

import numpy as np

from .. import knobs, telemetry
from .ordering import STATE_KEY, epoch_shard_order, shard_window_order
from .reader import ShardReader, host_slice
from .shards import DatasetError, load_manifest


class StreamingTokenBatches(object):
    def __init__(self, flow_datastore, corpus, batch_size, seq_len, *,
                 seed=None, epochs=None, drop_last=True, host_index=None,
                 n_hosts=None, readahead_bytes=None, max_workers=None,
                 reader=None, verify=True):
        """corpus: a dataset name (manifest loaded from the datastore) or
        an already-loaded manifest dict. host_index/n_hosts default to the
        gang env (MF_PARALLEL_NODE_INDEX / MF_PARALLEL_NUM_NODES) so a
        gang worker picks up its slice with no extra wiring."""
        self._manifest = (corpus if isinstance(corpus, dict)
                          else load_manifest(flow_datastore, corpus))
        self._batch_size = int(batch_size)
        self._window = int(seq_len) + 1
        self._seed = seed
        self._epochs = epochs
        self._drop_last = bool(drop_last)
        if host_index is None:
            host_index = _env_int("MF_PARALLEL_NODE_INDEX", 0)
        if n_hosts is None:
            n_hosts = _env_int("MF_PARALLEL_NUM_NODES", 1)
        self._host_index = int(host_index)
        self._n_hosts = int(n_hosts)
        if not 0 <= self._host_index < self._n_hosts:
            raise DatasetError(
                "host_index=%d out of range for n_hosts=%d"
                % (self._host_index, self._n_hosts))
        self._wins = [s["tokens"] // self._window
                      for s in self._manifest["shards"]]
        self._n_shards = len(self._wins)
        if sum(self._wins) == 0:
            raise DatasetError(
                "corpus %r holds no complete %d-token window in any shard"
                % (self._manifest.get("name"), self._window))
        # only the TRAILING shard can be short (fixed shard_tokens), so
        # any zero-window shard sits at the end; it never enters the
        # epoch order — matching hierarchical_window_order's
        # ceil(n_windows/shard_windows) shard count, so streaming and
        # in-memory orders stay identical even when the tail shard holds
        # no complete window
        self._n_order = self._n_shards
        while self._n_order and self._wins[self._n_order - 1] == 0:
            self._n_order -= 1
        self._reader = reader or ShardReader(
            flow_datastore, self._manifest, max_workers=max_workers,
            readahead_bytes=readahead_bytes, verify=verify)
        self._epoch = 0
        self._shard_cursor = 0
        self._window_cursor = 0
        # collective-sanitizer hook (spmd/sanitizer.py), env-gated so the
        # data package never pulls the spmd package (jax) in by default.
        # Only lockstep-identical geometry is journaled — never the
        # host-specific cursors (per-host slices are disjoint BY DESIGN).
        self._sanitizer = None
        if knobs.get_bool("TPUFLOW_SANITIZE"):
            from ..spmd import sanitizer

            self._sanitizer = sanitizer

    # ---------- geometry ----------

    @property
    def reader(self):
        return self._reader

    def _host_order(self, epoch):
        return host_slice(
            epoch_shard_order(self._seed, epoch, self._n_order),
            self._host_index, self._n_hosts)

    def host_windows(self, epoch=None):
        """Windows this host consumes in `epoch` (membership of the host
        slice varies with the epoch's shard order when shards are
        unequal)."""
        order = self._host_order(self._epoch if epoch is None else epoch)
        return sum(self._wins[s] for s in order)

    def batches_per_epoch(self, epoch=None):
        n = self.host_windows(epoch)
        if self._drop_last:
            return n // self._batch_size
        return -(-n // self._batch_size)

    # ---------- resume contract ----------

    def state(self):
        """Resume state BEFORE the next batch to be produced (flat ints;
        JSON- and orbax-serializable). Carries the full stream geometry,
        so restoring onto a differently-shaped stream is a hard error,
        not a silently different token sequence."""
        return {
            "epoch": int(self._epoch),
            "shard_cursor": int(self._shard_cursor),
            "window_cursor": int(self._window_cursor),
            "seed": self._seed,
            "batch_size": int(self._batch_size),
            "window": int(self._window),
            "n_shards": int(self._n_shards),
            "total_tokens": int(self._manifest["total_tokens"]),
            "shard_tokens": int(self._manifest["shard_tokens"]),
            "host_index": int(self._host_index),
            "n_hosts": int(self._n_hosts),
            "drop_last": int(self._drop_last),
        }

    def restore(self, state, reslice=False):
        """Position the stream just after the batch that carried `state`
        — iteration continues with the batch that would have come next.

        reslice=True accepts a stamp recorded under a DIFFERENT gang
        geometry (host_index/n_hosts — an elastic resize): per-host
        slices are disjoint stride slices of the epoch shard order, so a
        mid-epoch position under the old slicing has no exact equivalent
        under the new one. The stamp must therefore sit at an epoch
        boundary (start of an epoch, or the old slice fully drained);
        the new layout then re-slices that epoch deterministically and
        the GLOBAL token order stays exact. A mid-epoch stamp with a
        changed geometry is a hard error either way — align resizes to
        checkpoint-at-epoch-boundary (or use a global, non-sharded
        stream, which is resize-invariant)."""
        if state.get("seed") != self._seed:
            raise ValueError(
                "checkpointed stream seed %r != this stream's %r — "
                "restoring would produce a different shuffle order"
                % (state.get("seed"), self._seed))
        old_hosts = (int(state.get("host_index", self._host_index)),
                     int(state.get("n_hosts", self._n_hosts)))
        if reslice and old_hosts != (self._host_index, self._n_hosts):
            return self._restore_resliced(state, old_hosts)
        for key, mine in (("batch_size", self._batch_size),
                          ("window", self._window),
                          ("n_shards", self._n_shards),
                          ("total_tokens", self._manifest["total_tokens"]),
                          ("shard_tokens", self._manifest["shard_tokens"]),
                          ("host_index", self._host_index),
                          ("n_hosts", self._n_hosts),
                          ("drop_last", int(self._drop_last))):
            theirs = int(state[key])
            if theirs != int(mine):
                raise ValueError(
                    "checkpointed stream %s=%d != this stream's %d — the "
                    "cursor would address different tokens (the same "
                    "corpus, geometry and host slice are required to "
                    "resume)" % (key, theirs, int(mine)))
        epoch = int(state["epoch"])
        shard_cursor = int(state["shard_cursor"])
        window_cursor = int(state["window_cursor"])
        if epoch < 0 or (self._epochs is not None and epoch > self._epochs):
            raise ValueError(
                "checkpointed stream epoch=%d out of range [0, %s] — "
                "corrupted resume stamp" % (epoch, self._epochs))
        order = self._host_order(epoch)
        # shard_cursor == len(order) is the legal "epoch drained" stamp
        if not 0 <= shard_cursor <= len(order):
            raise ValueError(
                "checkpointed stream shard_cursor=%d out of range [0, %d] "
                "— corrupted resume stamp" % (shard_cursor, len(order)))
        if shard_cursor < len(order):
            wins = self._wins[order[shard_cursor]]
        else:
            wins = 0
        if not 0 <= window_cursor <= max(0, wins):
            raise ValueError(
                "checkpointed stream window_cursor=%d out of range [0, %d]"
                " — corrupted resume stamp" % (window_cursor, wins))
        self._epoch = epoch
        self._shard_cursor = shard_cursor
        self._window_cursor = window_cursor
        return self

    def _restore_resliced(self, state, old_hosts):
        """Epoch-boundary restore across a gang-geometry change."""
        old_index, old_n = old_hosts
        for key, mine in (("batch_size", self._batch_size),
                          ("window", self._window),
                          ("n_shards", self._n_shards),
                          ("total_tokens", self._manifest["total_tokens"]),
                          ("shard_tokens", self._manifest["shard_tokens"]),
                          ("drop_last", int(self._drop_last))):
            theirs = int(state[key])
            if theirs != int(mine):
                raise ValueError(
                    "checkpointed stream %s=%d != this stream's %d — a "
                    "resize can re-slice the SAME corpus, not a "
                    "different one" % (key, theirs, int(mine)))
        if not 0 <= old_index < old_n:
            raise ValueError(
                "checkpointed stream host_index=%d out of range for "
                "n_hosts=%d — corrupted resume stamp" % (old_index, old_n))
        epoch = int(state["epoch"])
        shard_cursor = int(state["shard_cursor"])
        window_cursor = int(state["window_cursor"])
        if epoch < 0 or (self._epochs is not None and epoch > self._epochs):
            raise ValueError(
                "checkpointed stream epoch=%d out of range [0, %s] — "
                "corrupted resume stamp" % (epoch, self._epochs))
        old_order = host_slice(
            epoch_shard_order(self._seed, epoch, self._n_order),
            old_index, old_n)
        if shard_cursor == 0 and window_cursor == 0:
            pass  # start of `epoch` — globally aligned under any slicing
        elif shard_cursor == len(old_order) and window_cursor == 0:
            epoch += 1  # old slice fully drained: next epoch's start
        else:
            raise ValueError(
                "cannot re-slice a mid-epoch stamp (epoch=%d, "
                "shard_cursor=%d/%d, window_cursor=%d) from %d host(s) "
                "onto %d: per-host slices are disjoint, so the position "
                "has no exact equivalent. Align elastic resizes to an "
                "epoch boundary, or stream a global (non-sharded) "
                "source." % (epoch, shard_cursor, len(old_order),
                             window_cursor, old_n, self._n_hosts))
        if self._epochs is not None and epoch > self._epochs:
            raise ValueError(
                "checkpointed stream epoch=%d out of range [0, %s] — "
                "corrupted resume stamp" % (epoch, self._epochs))
        self._epoch = epoch
        self._shard_cursor = 0
        self._window_cursor = 0
        return self

    # ---------- iteration ----------

    def __iter__(self):
        B, W = self._batch_size, self._window
        while self._epochs is None or self._epoch < self._epochs:
            order = self._host_order(self._epoch)
            from_start = (self._shard_cursor == 0
                          and self._window_cursor == 0)
            yielded = False
            buf = []
            t_batch = time.perf_counter()
            pos = self._shard_cursor
            stream = self._reader.stream(order[pos:])
            try:
                for sid, tokens in stream:
                    wins = self._wins[sid]
                    worder = shard_window_order(
                        self._seed, self._epoch, sid, wins)
                    j = self._window_cursor
                    while j < wins:
                        w = int(worder[j])
                        j += 1
                        # cursor advances BEFORE the yield so the stamp
                        # always points at the NEXT window — device
                        # prefetch running the iterator ahead cannot
                        # desynchronize it from consumed batches
                        if j == wins:
                            self._shard_cursor = pos + 1
                            self._window_cursor = 0
                        else:
                            self._shard_cursor = pos
                            self._window_cursor = j
                        buf.append(tokens[w * W:(w + 1) * W])
                        if len(buf) == B:
                            telemetry.emit(
                                "timer", "data.batch_wait",
                                ms=(time.perf_counter() - t_batch) * 1000,
                                ok=True)
                            batch = np.stack(buf)
                            if self._sanitizer is not None:
                                self._sanitizer.journal(
                                    "data", "batch", shape=batch,
                                    key=self._epoch)
                            yield {"tokens": batch,
                                   STATE_KEY: self.state()}
                            yielded = True
                            buf = []
                            t_batch = time.perf_counter()
                    pos += 1
                    self._shard_cursor = pos
                    self._window_cursor = 0
            finally:
                stream.close()
            if buf and not self._drop_last:
                telemetry.emit(
                    "timer", "data.batch_wait",
                    ms=(time.perf_counter() - t_batch) * 1000, ok=True)
                batch = np.stack(buf)
                if self._sanitizer is not None:
                    self._sanitizer.journal("data", "batch", shape=batch,
                                            key=self._epoch)
                yield {"tokens": batch, STATE_KEY: self.state()}
                yielded = True
            if not yielded and self._epochs is None and from_start:
                # an epoch consumed from its start produced NO batch (this
                # host's slice holds fewer than batch_size windows under
                # drop_last, or no shards at all): with epochs=None the
                # loop would spin forever, re-downloading the slice each
                # pass while next() never returns
                raise DatasetError(
                    "host %d/%d drew %d window(s) in epoch %d — not "
                    "enough for one batch of %d (drop_last=%s); an "
                    "unbounded stream would never yield. Shrink "
                    "batch_size or n_hosts, or grow the corpus."
                    % (self._host_index, self._n_hosts,
                       self.host_windows(self._epoch), self._epoch,
                       self._batch_size, self._drop_last))
            self._epoch += 1
            self._shard_cursor = 0
            self._window_cursor = 0


def _env_int(name, default):
    import os

    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default
