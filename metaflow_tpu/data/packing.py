"""Sequence packing: fill fixed-length windows from variable-length
documents, with segment-id masks.

Training on documents shorter than seq_len wastes compute on padding;
packing concatenates documents into full (seq_len+1)-token windows and
carries a per-token SEGMENT ID so the loss can refuse to predict across
document boundaries. Windows feed the existing training path directly:
`packed_batches` yields {'inputs', 'targets', 'mask', 'segment_ids'}
batches, and models/llama.py::loss_fn already consumes the
inputs/targets/mask form (the mask zeroes boundary-crossing and padding
targets). `segment_ids` ride along (host→device like any other leaf) for
attention implementations that support intra-segment masking.

Semantics:
  - documents are packed greedily in input order; a document longer than
    the remaining space in a window continues into the next window (its
    continuation restarts as segment 1 of that window);
  - segment ids are 1-based PER WINDOW; 0 marks padding (only ever in
    the final window's tail);
  - the target at position i (= token i+1 predicting from token i) is
    masked out unless tokens i and i+1 belong to the same segment —
    so the first token of every document and all padding contribute no
    loss.
"""

import numpy as np


def pack_documents(docs, seq_len, *, pad_id=0, dtype=None):
    """Pack an iterable of 1-D token docs into (tokens[W], segment_ids[W])
    windows, W = seq_len + 1. The final partial window is padded with
    pad_id / segment 0. Yields nothing for an empty doc stream."""
    W = int(seq_len) + 1
    if W < 2:
        raise ValueError("seq_len must be >= 1, got %r" % seq_len)
    cur_t, cur_s = [], []
    seg = 1
    out_dtype = dtype
    for doc in docs:
        doc = np.asarray(doc).ravel()
        if out_dtype is None:
            out_dtype = doc.dtype
        offset = 0
        while offset < doc.size:
            space = W - len(cur_t)
            take = min(space, doc.size - offset)
            cur_t.extend(doc[offset:offset + take].tolist())
            cur_s.extend([seg] * take)
            offset += take
            if len(cur_t) == W:
                yield (np.asarray(cur_t, dtype=out_dtype),
                       np.asarray(cur_s, dtype=np.int32))
                cur_t, cur_s = [], []
                # a continuing doc restarts as segment 1 of the new
                # window; a doc that ended exactly at the boundary lets
                # the NEXT doc start at segment 1 too
                seg = 1
        if cur_t:
            seg += 1
    if cur_t:
        pad = W - len(cur_t)
        cur_t.extend([pad_id] * pad)
        cur_s.extend([0] * pad)
        yield (np.asarray(cur_t, dtype=out_dtype or np.int32),
               np.asarray(cur_s, dtype=np.int32))


def segment_loss_mask(segment_ids):
    """[..., W] segment ids → [..., W-1] float32 loss mask: target i is
    live iff positions i and i+1 share a non-padding segment."""
    segment_ids = np.asarray(segment_ids)
    same = segment_ids[..., 1:] == segment_ids[..., :-1]
    live = segment_ids[..., 1:] != 0
    return (same & live).astype(np.float32)


def packed_batches(docs, batch_size, seq_len, *, pad_id=0, drop_last=False):
    """Pack docs and batch the windows: yields
    {'inputs': [B, S], 'targets': [B, S], 'mask': [B, S] float32,
     'segment_ids': [B, S+1] int32} — directly consumable by the existing
    loss path (llama.loss_fn reads inputs/targets/mask; segment_ids ride
    along for segment-aware attention)."""
    toks, segs = [], []
    for tokens, segment_ids in pack_documents(docs, seq_len, pad_id=pad_id):
        toks.append(tokens)
        segs.append(segment_ids)
        if len(toks) == batch_size:
            yield _finish(toks, segs)
            toks, segs = [], []
    if toks and not drop_last:
        yield _finish(toks, segs)


def _finish(toks, segs):
    tokens = np.stack(toks)
    segment_ids = np.stack(segs)
    return {
        "inputs": tokens[:, :-1],
        "targets": tokens[:, 1:],
        "mask": segment_loss_mask(segment_ids),
        "segment_ids": segment_ids,
    }
