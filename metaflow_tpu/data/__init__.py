"""Streaming dataset subsystem: sharded on-datastore corpora.

The training input path no longer needs the whole token corpus as one
in-memory array (the `ResumableTokenBatches(data=...)` assumption):

  - shards.py   — `tpuflow dataset build` packs raw token arrays into
                  fixed-size shard blobs (content-addressed, per-shard
                  checksums) plus a JSON index manifest, written through
                  the FlowDataStore/GCSStorage batched path.
  - reader.py   — bounded-readahead parallel reader: a worker pool
                  fetches shards ahead of consumption (readahead window
                  in bytes, in-flight checksum verify, cache-bypass
                  retry), deterministic per-host shard assignment.
  - loader.py   — StreamingTokenBatches: the exact ResumableTokenBatches
                  contract (STATE_KEY resume stamp on every batch, zero
                  replay on restore) over an on-datastore corpus.
  - packing.py  — sequence packing: fill fixed seq_len windows from
                  variable-length documents with segment-id masks.
  - ordering.py — the pure (seed, epoch) shuffle functions shared with
                  training/data.py, so streaming and in-memory loaders
                  produce byte-identical token streams.

See docs/data.md for the shard format, manifest schema, and the
resume-stamp contract.
"""

from .ordering import (
    STATE_KEY,
    epoch_shard_order,
    hierarchical_window_order,
    shard_window_order,
)
from .packing import pack_documents, packed_batches, segment_loss_mask
from .reader import ShardCorruptionError, ShardReader
from .shards import (
    DATASET_PREFIX,
    build_corpus,
    dataset_path,
    list_datasets,
    load_manifest,
)
from .loader import StreamingTokenBatches

__all__ = [
    "STATE_KEY",
    "epoch_shard_order",
    "shard_window_order",
    "hierarchical_window_order",
    "DATASET_PREFIX",
    "build_corpus",
    "dataset_path",
    "list_datasets",
    "load_manifest",
    "ShardReader",
    "ShardCorruptionError",
    "StreamingTokenBatches",
    "pack_documents",
    "packed_batches",
    "segment_loss_mask",
]
