"""Mixtral-style MoE transformer (BASELINE target: Mixtral-8x7B EP).

Same pure-pytree design as models/llama.py; the FFN is a top-2-of-N MoE
(ops/moe.py) whose expert dimension carries the 'expert' logical axis — on a
MeshSpec.moe mesh the experts are sharded across chips and dispatch becomes
an all-to-all.
"""

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.moe import moe_ffn
from ..ops.norms import rms_norm
from ..ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32_000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    n_experts: int = 8
    experts_per_tok: int = 2
    max_seq_len: int = 32_768
    rope_theta: float = 1_000_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    attention_impl: str = "auto"
    remat: bool = True
    router_aux_coef: float = 0.02
    # sparse = capacity-bucketed expert-parallel dispatch (ops/moe.py);
    # gmm = dropless grouped-matmul, single-shard experts;
    # gmm_ep = dropless composed with expert parallelism (a2a + local
    # gmm, bounded by ep_buffer_factor);
    # dense = the O(num_experts × tokens) oracle, debugging only
    moe_dispatch: str = "sparse"
    capacity_factor: float = 2.0
    ep_buffer_factor: float = None  # gmm_ep only; None = exact/dropless

    @property
    def head_dim(self):
        return self.dim // self.n_heads

    @staticmethod
    def mixtral_8x7b(**kw):
        return replace(MixtralConfig(), **kw)

    @staticmethod
    def tiny(**kw):
        return replace(
            MixtralConfig(
                vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                ffn_dim=256, n_experts=4, experts_per_tok=2, max_seq_len=256,
                dtype="float32",
            ),
            **kw,
        )


def param_dtype(cfg):
    return jnp.dtype(cfg.dtype)


def init_params(rng, cfg):
    dt = param_dtype(cfg)
    k_embed, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, fan_in, *shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    H, KV, Hd, N = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_experts
    keys = jax.random.split(k_layers, 8)

    return {
        "embed": dense(k_embed, D, cfg.vocab_size, D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dt),
            "wq": dense(keys[0], D, L, D, H * Hd),
            "wk": dense(keys[1], D, L, D, KV * Hd),
            "wv": dense(keys[2], D, L, D, KV * Hd),
            "wo": dense(keys[3], H * Hd, L, H * Hd, D),
            "ffn_norm": jnp.ones((L, D), dt),
            "router": dense(keys[4], D, L, D, N),
            "w_gate": dense(keys[5], D, L, N, D, F),
            "w_up": dense(keys[6], D, L, N, D, F),
            "w_down": dense(keys[7], F, L, N, F, D),
        },
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(k_out, D, D, cfg.vocab_size),
    }


def logical_axes(cfg):
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "ffn_norm": ("layers", "embed"),
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def _layer(cfg, cos, sin, carry, layer_params, mesh=None):
    x, aux_sum = carry
    B, S, D = x.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rms_norm(x, layer_params["attn_norm"], cfg.norm_eps)
    q = (h @ layer_params["wq"]).reshape(B, S, H, Hd)
    k = (h @ layer_params["wk"]).reshape(B, S, KV, Hd)
    v = (h @ layer_params["wv"]).reshape(B, S, KV, Hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = attention(q, k, v, causal=True, impl=cfg.attention_impl)
    x = x + attn.reshape(B, S, H * Hd) @ layer_params["wo"]

    h = rms_norm(x, layer_params["ffn_norm"], cfg.norm_eps)
    moe_out, aux = moe_ffn(
        h,
        layer_params["router"],
        layer_params["w_gate"],
        layer_params["w_up"],
        layer_params["w_down"],
        num_experts_per_tok=cfg.experts_per_tok,
        # gmm/gmm_ep are dropless: the capacity knob does not apply
        capacity_factor=(None if cfg.moe_dispatch in ("gmm", "gmm_ep")
                         else cfg.capacity_factor),
        dispatch=cfg.moe_dispatch,
        mesh=mesh,
        ep_buffer_factor=(cfg.ep_buffer_factor
                          if cfg.moe_dispatch == "gmm_ep" else None),
    )
    return (x + moe_out, aux_sum + aux), None


def forward(params, tokens, cfg, return_aux=False, mesh=None):
    dt = param_dtype(cfg)
    x = params["embed"][tokens].astype(dt)
    cos, sin = rope_frequencies(cfg.head_dim, tokens.shape[1], cfg.rope_theta,
                                dtype=dt)

    layer_fn = lambda carry, lp: _layer(cfg, cos, sin, carry, lp, mesh=mesh)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    (x, aux), _ = jax.lax.scan(
        layer_fn, (x, jnp.zeros((), jnp.float32)), params["layers"]
    )

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    if return_aux:
        return logits, aux / cfg.n_layers
    return logits


def loss_fn(params, batch, cfg, mesh=None):
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    logits, aux = forward(params, inputs, cfg, return_aux=True, mesh=mesh)
    logps = jax.nn.log_softmax(logits, axis=-1)
    token_lp = jnp.take_along_axis(logps, targets[..., None], axis=-1)[..., 0]
    ce = -jnp.mean(token_lp)
    return ce + cfg.router_aux_coef * aux


def num_params(params):
    return sum(int(x.size) for x in jax.tree.leaves(params))
