"""ResNet (v1.5) in pure JAX — the foreach fan-out fine-tune target
(BASELINE config: "JAX ResNet-50 fine-tune, one v5e chip per branch").

Convs map straight onto the MXU via lax.conv_general_dilated in NHWC; batch
norm is folded into inference mode by default for fine-tuning (train_bn=True
keeps running stats in the state dict).
"""

from dataclasses import dataclass, field, replace
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    dtype: str = "float32"

    @staticmethod
    def resnet50(**kw):
        return replace(ResNetConfig(), **kw)

    @staticmethod
    def resnet18(**kw):
        return replace(ResNetConfig(stage_sizes=(2, 2, 2, 2)), **kw)

    @staticmethod
    def tiny(**kw):
        return replace(
            ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=10), **kw
        )


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * (2.0 / fan_in) ** 0.5).astype(dtype)


def _bn_init(c, dtype):
    return {
        "scale": jnp.ones((c,), dtype),
        "bias": jnp.zeros((c,), dtype),
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init_params(rng, cfg):
    dt = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(rng, 256))
    params = {
        "stem": {
            "conv": _conv_init(next(keys), 7, 7, 3, cfg.width, dt),
            "bn": _bn_init(cfg.width, dt),
        },
        "stages": [],
        "head": None,
    }
    cin = cfg.width
    for stage, blocks in enumerate(cfg.stage_sizes):
        cout = cfg.width * (2 ** stage)
        stage_params = []
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            block = {
                "conv1": _conv_init(next(keys), 1, 1, cin, cout, dt),
                "bn1": _bn_init(cout, dt),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout, dt),
                "bn2": _bn_init(cout, dt),
                "conv3": _conv_init(next(keys), 1, 1, cout, cout * 4, dt),
                "bn3": _bn_init(cout * 4, dt),
            }
            if cin != cout * 4 or stride != 1:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout * 4, dt)
                block["proj_bn"] = _bn_init(cout * 4, dt)
            stage_params.append(block)
            cin = cout * 4
        params["stages"].append(stage_params)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, cfg.num_classes),
                                jnp.float32) * cin ** -0.5).astype(dt),
        "b": jnp.zeros((cfg.num_classes,), dt),
    }
    return params


def _conv(x, w, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x, w, (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def _bn(x, p, eps=1e-5):
    inv = lax.rsqrt(p["var"] + eps) * p["scale"].astype(jnp.float32)
    out = (x.astype(jnp.float32) - p["mean"]) * inv + p["bias"].astype(
        jnp.float32
    )
    return out.astype(x.dtype)


def _bottleneck(x, block, stride):
    residual = x
    y = jax.nn.relu(_bn(_conv(x, block["conv1"]), block["bn1"]))
    y = jax.nn.relu(_bn(_conv(y, block["conv2"], stride), block["bn2"]))
    y = _bn(_conv(y, block["conv3"]), block["bn3"])
    if "proj" in block:
        residual = _bn(_conv(x, block["proj"], stride), block["proj_bn"])
    return jax.nn.relu(y + residual)


def forward(params, images, cfg):
    """images: [B, H, W, 3] → logits [B, num_classes]."""
    x = images.astype(jnp.dtype(cfg.dtype))
    x = jax.nn.relu(_bn(_conv(x, params["stem"]["conv"], 2),
                        params["stem"]["bn"]))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_idx, stage in enumerate(params["stages"]):
        for block_idx, block in enumerate(stage):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            x = _bottleneck(x, block, stride)
    x = jnp.mean(x, axis=(1, 2))
    return (
        jnp.einsum("bc,cn->bn", x, params["head"]["w"],
                   preferred_element_type=jnp.float32)
        + params["head"]["b"].astype(jnp.float32)
    )


def loss_fn(params, batch, cfg):
    logits = forward(params, batch["images"], cfg)
    labels = batch["labels"]
    logps = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logps, labels[:, None], axis=-1))


def logical_axes(cfg):
    """ResNet params replicate under FSDP-style meshes (conv kernels are
    small); only the head shards on 'embed'/'vocab'."""
    params = init_params(jax.random.PRNGKey(0), cfg)

    def annotate(path, leaf):
        if path[-1] == "w" and leaf.ndim == 2:
            return ("embed", "vocab")
        return tuple(None for _ in range(leaf.ndim))

    def walk(node, path=()):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v, path) for v in node]
        if isinstance(node, (int, float)):
            return node
        return annotate(path, node)

    return walk(params)


def num_params(params):
    return sum(
        int(x.size) for x in jax.tree.leaves(params)
        if hasattr(x, "size")
    )
