"""Llama-family transformer, TPU-first.

Pure-JAX pytree parameters with a parallel tree of *logical axis* annotations
(metaflow_tpu.spmd.sharding) — pjit/GSPMD shards the whole model from a
rule table; no framework indirection between the math and the mesh.

Covers the BASELINE.json targets: Llama-3-8B (dense, GQA, RoPE-500k) and the
scaled-down variants used for single-chip benchmarking. The layer stack is a
lax.scan over a stacked-parameters pytree — one compiled layer body,
layer-count-independent compile time.
"""

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.norms import rms_norm
from ..ops.rope import apply_rope, rope_frequencies


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    rope_llama3_scaling: bool = True
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    attention_impl: str = "auto"
    remat: bool = True
    # remat policy: None = recompute everything; "dots" = save matmul
    # outputs (less recompute, more memory)
    remat_policy: str = None
    # cross-entropy chunk length (tokens): the [B, S, vocab] fp32 logits are
    # the single biggest activation (batch 16 × 2048 × 32k fp32 = 4.2 GB on
    # one v5e); chunking the loss over the sequence bounds that to
    # [B, chunk, vocab] fwd AND bwd (per-chunk remat). 0 = unchunked.
    loss_chunk: int = 256

    @property
    def head_dim(self):
        return self.dim // self.n_heads

    # ---- standard sizes ----

    @staticmethod
    def llama3_8b(**kw):
        return replace(LlamaConfig(), **kw)

    @staticmethod
    def llama3_1b(**kw):
        """Llama-3.2-1B-shaped."""
        return replace(
            LlamaConfig(
                dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                ffn_dim=8192,
            ),
            **kw,
        )

    @staticmethod
    def tiny(**kw):
        """Test-sized config (CPU-runnable)."""
        return replace(
            LlamaConfig(
                vocab_size=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                ffn_dim=256, max_seq_len=256, rope_llama3_scaling=False,
                dtype="float32",
            ),
            **kw,
        )

    @staticmethod
    def bench_1b(**kw):
        """~1.2B params: fits one v5e chip in bf16 with Adam state offloaded
        sharding-free; used by bench.py."""
        return replace(
            LlamaConfig(
                vocab_size=32_000, dim=2048, n_layers=16, n_heads=16,
                n_kv_heads=8, ffn_dim=5632, max_seq_len=2048,
                rope_llama3_scaling=False,
            ),
            **kw,
        )


def param_dtype(cfg):
    return jnp.dtype(cfg.dtype)


def init_params(rng, cfg):
    """Initialize the parameter pytree. Per-layer tensors are stacked on a
    leading 'layers' axis (consumed by lax.scan in forward)."""
    dt = param_dtype(cfg)
    k_embed, k_layers, k_out = jax.random.split(rng, 3)

    def norm_init(*shape):
        return jnp.ones(shape, dt)

    def dense_init(key, fan_in, *shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    keys = jax.random.split(k_layers, 7)

    params = {
        "embed": dense_init(k_embed, D, cfg.vocab_size, D),
        "layers": {
            "attn_norm": norm_init(L, D),
            "wq": dense_init(keys[0], D, L, D, H * Hd),
            "wk": dense_init(keys[1], D, L, D, KV * Hd),
            "wv": dense_init(keys[2], D, L, D, KV * Hd),
            "wo": dense_init(keys[3], H * Hd, L, H * Hd, D),
            "ffn_norm": norm_init(L, D),
            "w_gate": dense_init(keys[4], D, L, D, F),
            "w_up": dense_init(keys[5], D, L, D, F),
            "w_down": dense_init(keys[6], F, L, F, D),
        },
        "final_norm": norm_init(D),
        "lm_head": dense_init(k_out, D, D, cfg.vocab_size),
    }
    return params


def logical_axes(cfg):
    """Logical axis names for every parameter (same tree structure)."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "ffn_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def _layer(cfg, cos, sin, x, layer_params, mesh=None):
    """One transformer block; x: [B, S, D]."""
    B, S, D = x.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rms_norm(x, layer_params["attn_norm"], cfg.norm_eps)
    q = (h @ layer_params["wq"]).reshape(B, S, H, Hd)
    k = (h @ layer_params["wk"]).reshape(B, S, KV, Hd)
    v = (h @ layer_params["wv"]).reshape(B, S, KV, Hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if cfg.attention_impl in ("ring", "ulysses"):
        # context parallelism over the 'sequence' mesh axis: 'ring'
        # rotates KV blocks (ops/ring_attention.py, O(S/n) residency);
        # 'ulysses' re-shards seq->heads with all-to-alls and runs
        # full-sequence attention per head group
        # (ops/ulysses_attention.py, unsharded inner kernel)
        if mesh is None or "sequence" not in mesh.axis_names:
            raise ValueError(
                "attention_impl=%r needs a mesh with a 'sequence' axis "
                "passed to forward/loss_fn" % cfg.attention_impl
            )
        if cfg.attention_impl == "ring":
            from ..ops.ring_attention import ring_attention

            attn = ring_attention(q, k, v, mesh, causal=True)
        else:
            from ..ops.ulysses_attention import ulysses_attention

            attn = ulysses_attention(q, k, v, mesh, causal=True)
    else:
        attn = attention(q, k, v, causal=True, impl=cfg.attention_impl)
    # named for remat_policy='attn_out': saving this tensor across the layer
    # checkpoint boundary means the backward pass never re-runs the
    # attention forward (the flash custom_vjp already recomputes its own
    # blockwise internals from the saved LSE — re-running the kernel on top
    # of that is pure waste)
    from jax.ad_checkpoint import checkpoint_name

    attn = checkpoint_name(attn, "attn_out")
    x = x + attn.reshape(B, S, H * Hd) @ layer_params["wo"]

    h = rms_norm(x, layer_params["ffn_norm"], cfg.norm_eps)
    gate = jax.nn.silu(h @ layer_params["w_gate"])
    up = h @ layer_params["w_up"]
    x = x + (gate * up) @ layer_params["w_down"]
    return x


def hidden_states(params, tokens, cfg, mesh=None):
    """tokens: [B, S] int32 → final-norm hidden states [B, S, D] (model
    dtype). The lm_head projection is deliberately separate so the loss can
    chunk it (see loss_fn)."""
    dt = param_dtype(cfg)
    x = params["embed"][tokens].astype(dt)
    cos, sin = rope_frequencies(
        cfg.head_dim, tokens.shape[1], cfg.rope_theta, dtype=dt,
        llama3_scaling=cfg.rope_llama3_scaling,
    )

    layer_fn = lambda x, lp: (_layer(cfg, cos, sin, x, lp, mesh=mesh), None)
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "attn_out":
            # costs L x [B,S,D] bf16 of HBM, saves a full attention forward
            # per layer in the backward pass
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out"
            )
        layer_fn = jax.checkpoint(layer_fn, policy=policy)
    x, _ = jax.lax.scan(layer_fn, x, params["layers"])

    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def forward(params, tokens, cfg, mesh=None):
    """tokens: [B, S] int32 → logits [B, S, vocab] (float32).

    `mesh` is only needed for the sequence-parallel attention impls ('ring'/'ulysses')."""
    x = hidden_states(params, tokens, cfg, mesh=mesh)
    return jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )


def _ce_sums(x, lm_head, targets, mask):
    """Summed cross-entropy + token count for one [B, C, D] hidden chunk.
    fp32 logits live only inside this function."""
    logits = jnp.einsum(
        "bcd,dv->bcv", x, lm_head, preferred_element_type=jnp.float32
    )
    lse = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tl
    if mask is None:
        return jnp.sum(nll), jnp.asarray(nll.size, jnp.float32)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask), jnp.sum(mask)


def loss_fn(params, batch, cfg, mesh=None):
    """Next-token cross-entropy; batch: {'tokens': [B, S+1]} or
    {'inputs': [B,S], 'targets': [B,S]} (+ optional 'mask').

    When cfg.loss_chunk divides the sequence, the head projection +
    log-softmax run as a rematerialized lax.scan over sequence chunks, so
    peak activation memory is [B, chunk, vocab] fp32 instead of the full
    [B, S, vocab] in BOTH the forward and backward pass."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    mask = batch.get("mask")
    x = hidden_states(params, inputs, cfg, mesh=mesh)

    B, S, D = x.shape
    chunk = cfg.loss_chunk
    if chunk and S % chunk:
        # snap to the largest divisor of S that fits the requested bound so
        # an off-size sequence never silently reverts to full-logit memory
        chunk = next((c for c in range(min(chunk, S), 0, -1) if S % c == 0))
        if chunk < 32:
            chunk = 0  # degenerate chunking would be slower than the memory win
    if not chunk or S == chunk:
        loss_sum, count = _ce_sums(x, params["lm_head"], targets, mask)
        return loss_sum / jnp.maximum(count, 1)

    n = S // chunk
    xs = jnp.moveaxis(x.reshape(B, n, chunk, D), 1, 0)
    ts = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)
    ms = None if mask is None else jnp.moveaxis(mask.reshape(B, n, chunk), 1, 0)

    @jax.checkpoint
    def body(carry, sl):
        loss_sum, count = carry
        s, c = _ce_sums(
            sl["x"], params["lm_head"], sl["t"], sl.get("m")
        )
        return (loss_sum + s, count + c), None

    sl = {"x": xs, "t": ts}
    if ms is not None:
        sl["m"] = ms
    (loss_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), sl
    )
    return loss_sum / jnp.maximum(count, 1)


def num_params(params):
    return sum(int(x.size) for x in jax.tree.leaves(params))
