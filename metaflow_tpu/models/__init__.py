from . import llama
from . import mixtral
from . import resnet

__all__ = ["llama", "mixtral", "resnet"]
