from . import dit
from . import llama
from . import mixtral
from . import resnet

__all__ = ["dit", "llama", "mixtral", "resnet"]
