"""Diffusion transformer (DiT / SD3-style MM-DiT lite) for sharded batch
inference (BASELINE config: "Stable-Diffusion-3 batch inference over v5e-256
via unbounded foreach").

A rectified-flow latent diffusion model: patchified latents + timestep/class
conditioning through adaLN-zero transformer blocks. Same pure-pytree +
logical-axes design as the other model families; `sample()` runs the Euler
sampler under jit with static step count.
"""

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.norms import layer_norm


@dataclass(frozen=True)
class DiTConfig:
    input_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    dim: int = 1152
    n_layers: int = 28
    n_heads: int = 16
    num_classes: int = 1000
    dtype: str = "bfloat16"
    attention_impl: str = "auto"

    @property
    def num_patches(self):
        return (self.input_size // self.patch_size) ** 2

    @property
    def patch_dim(self):
        return self.patch_size * self.patch_size * self.in_channels

    @staticmethod
    def dit_xl(**kw):
        return replace(DiTConfig(), **kw)

    @staticmethod
    def tiny(**kw):
        return replace(
            DiTConfig(input_size=8, patch_size=2, in_channels=4, dim=64,
                      n_layers=2, n_heads=4, num_classes=10,
                      dtype="float32"),
            **kw,
        )


def init_params(rng, cfg):
    dt = jnp.dtype(cfg.dtype)
    keys = jax.random.split(rng, 12)

    def dense(key, fan_in, *shape):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    L, D = cfg.n_layers, cfg.dim
    return {
        "patch_embed": dense(keys[0], cfg.patch_dim, cfg.patch_dim, D),
        "pos_embed": (jax.random.normal(keys[1], (cfg.num_patches, D),
                                        jnp.float32) * 0.02).astype(dt),
        "time_mlp1": dense(keys[2], 256, 256, D),
        "time_mlp2": dense(keys[3], D, D, D),
        "label_embed": dense(keys[4], D, cfg.num_classes + 1, D),
        "layers": {
            "qkv": dense(keys[5], D, L, D, 3 * D),
            "proj": dense(keys[6], D, L, D, D),
            "mlp1": dense(keys[7], D, L, D, 4 * D),
            "mlp2": dense(keys[8], 4 * D, L, 4 * D, D),
            # adaLN-zero modulation: 6 params per block, zero-init
            "ada": jnp.zeros((L, D, 6 * D), dt),
        },
        "final_ada": jnp.zeros((D, 2 * D), dt),
        "final_proj": jnp.zeros((D, cfg.patch_dim), dt),
    }


def logical_axes(cfg):
    return {
        "patch_embed": (None, "embed"),
        "pos_embed": ("seq", "embed"),
        "time_mlp1": (None, "embed"),
        "time_mlp2": ("embed", "embed"),
        "label_embed": ("vocab", "embed"),
        "layers": {
            "qkv": ("layers", "embed", "heads"),
            "proj": ("layers", "heads", "embed"),
            "mlp1": ("layers", "embed", "mlp"),
            "mlp2": ("layers", "mlp", "embed"),
            "ada": ("layers", "embed", None),
        },
        "final_ada": ("embed", None),
        "final_proj": ("embed", None),
    }


def _timestep_embedding(t, dim=256):
    half = dim // 2
    freqs = jnp.exp(-jnp.log(10_000.0) * jnp.arange(half) / half)
    args = t[:, None].astype(jnp.float32) * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _patchify(x, cfg):
    B, H, W, C = x.shape
    p = cfg.patch_size
    x = x.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p),
                                              p * p * C)
    return x


def _unpatchify(x, cfg):
    B, N, _ = x.shape
    p = cfg.patch_size
    g = cfg.input_size // p
    x = x.reshape(B, g, g, p, p, cfg.in_channels)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.input_size,
                                              cfg.input_size,
                                              cfg.in_channels)
    return x


def _block(cfg, x, cond, lp):
    B, N, D = x.shape
    H = cfg.n_heads
    mod = cond @ lp["ada"]  # [B, 6D]
    s1, b1, g1, s2, b2, g2 = jnp.split(mod, 6, axis=-1)
    ones = jnp.ones((D,), x.dtype)

    h = layer_norm(x, ones, None) * (1 + s1[:, None]) + b1[:, None]
    qkv = (h @ lp["qkv"]).reshape(B, N, 3, H, D // H)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = attention(q, k, v, causal=False, impl=cfg.attention_impl)
    x = x + g1[:, None] * (attn.reshape(B, N, D) @ lp["proj"])

    h = layer_norm(x, ones, None) * (1 + s2[:, None]) + b2[:, None]
    h = jax.nn.gelu(h @ lp["mlp1"]) @ lp["mlp2"]
    return x + g2[:, None] * h


def forward(params, latents, t, labels, cfg):
    """Predict the velocity field. latents: [B, H, W, C]; t: [B] in [0, 1];
    labels: [B] ints (num_classes = unconditional)."""
    dt_ = jnp.dtype(cfg.dtype)
    x = _patchify(latents.astype(dt_), cfg)
    x = x @ params["patch_embed"] + params["pos_embed"][None]

    temb = _timestep_embedding(t * 1000.0).astype(dt_)
    cond = jax.nn.silu(temb @ params["time_mlp1"]) @ params["time_mlp2"]
    cond = cond + params["label_embed"][labels]
    cond = jax.nn.silu(cond)

    def layer_fn(h, lp):
        return _block(cfg, h, cond, lp), None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])

    s, b = jnp.split(cond @ params["final_ada"], 2, axis=-1)
    ones = jnp.ones((cfg.dim,), x.dtype)
    x = layer_norm(x, ones, None) * (1 + s[:, None]) + b[:, None]
    x = x @ params["final_proj"]
    return _unpatchify(x, cfg).astype(jnp.float32)


def loss_fn(params, batch, cfg):
    """Rectified-flow matching loss: x_t = (1-t)·noise + t·data,
    target velocity = data - noise.

    Thread fresh randomness per step: pass batch['rng'] (a PRNG key) or a
    changing batch['seed'] — otherwise every step reuses one noise draw."""
    data = batch["latents"]
    labels = batch["labels"]
    rng = batch.get("rng")
    if rng is None:
        rng = jax.random.PRNGKey(batch.get("seed", 0))
    k_noise, k_t = jax.random.split(rng)
    noise = jax.random.normal(k_noise, data.shape, jnp.float32)
    t = jax.random.uniform(k_t, (data.shape[0],))
    x_t = (1 - t[:, None, None, None]) * noise + t[:, None, None, None] * data
    v_pred = forward(params, x_t, t, labels, cfg)
    v_target = data - noise
    return jnp.mean((v_pred - v_target) ** 2)


def sample(params, rng, labels, cfg, num_steps=20, guidance_scale=1.0):
    """Euler sampler along the rectified flow, optionally with
    classifier-free guidance. Returns [B, H, W, C] latents."""
    B = labels.shape[0]
    x = jax.random.normal(rng, (B, cfg.input_size, cfg.input_size,
                                cfg.in_channels), jnp.float32)
    uncond = jnp.full((B,), cfg.num_classes, jnp.int32)
    dt_step = 1.0 / num_steps

    def step(i, x):
        t = jnp.full((B,), i * dt_step)
        v = forward(params, x, t, labels, cfg)
        if guidance_scale != 1.0:
            v_u = forward(params, x, t, uncond, cfg)
            v = v_u + guidance_scale * (v - v_u)
        return x + dt_step * v

    return jax.lax.fori_loop(0, num_steps, step, x)


def num_params(params):
    return sum(int(x.size) for x in jax.tree.leaves(params))
