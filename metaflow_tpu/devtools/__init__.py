"""Local full-stack development harness ("devstack").

The reference ships a docker-compose devtools stack (metaflow-dev: minio +
metadata service + UI) so flows can exercise the production code paths —
remote datastore, REST metadata — without cloud access. This is the
TPU-native equivalent, with no containers: one process hosts

  - a fake GCS server (`devtools/fake_gcs.py`, the full gs:// JSON-API
    slice gsop speaks), and
  - the reference metadata service (`metadata/service.py`, same REST
    shape as Metaflow's), backed by a directory on disk.

`python -m metaflow_tpu devstack up` starts both, writes a state file,
and prints the exports; any shell that sources them runs every flow
against the stack:

    eval "$(python -m metaflow_tpu devstack env)"
    python myflow.py run          # --datastore gs --metadata service

Reference: metaflow-dev / devtools (SURVEY.md §2.10 devtools stack).
"""

import json
import os
import signal
import tempfile


STATE_FILE = os.path.join(
    tempfile.gettempdir(), "tpuflow_devstack-%d.json" % os.getuid()
)
DEFAULT_BUCKET = "devstack"


class DevStack(object):
    """In-process composition of the fake GCS server + metadata service."""

    def __init__(self, gs_port=0, metadata_port=0, root=None,
                 bucket=DEFAULT_BUCKET):
        self.root = root or os.path.join(
            tempfile.gettempdir(), "tpuflow_devstack_data"
        )
        self.bucket = bucket
        self._gs_port = gs_port
        self._md_port = metadata_port
        self.gs_endpoint = None
        self.metadata_url = None
        self._gcs = None
        self._md = None

    def start(self):
        from ..metadata.service import MetadataService
        from .fake_gcs import FakeGCSServer

        os.makedirs(self.root, exist_ok=True)
        self._gcs = FakeGCSServer(port=self._gs_port)
        self._gcs.__enter__()
        self.gs_endpoint = self._gcs.endpoint
        self._md = MetadataService(
            os.path.join(self.root, "metadata"), port=self._md_port
        )
        self.metadata_url = self._md.start()
        return self

    def stop(self):
        if self._gcs is not None:
            self._gcs.__exit__(None, None, None)
            self._gcs = None
        if self._md is not None:
            self._md.stop()
            self._md = None

    # ------------------------------------------------------------------

    def env(self):
        """The exports a shell needs to run flows against this stack."""
        return {
            "TPUFLOW_GS_ENDPOINT": self.gs_endpoint,
            "TPUFLOW_DATASTORE_SYSROOT_GS": "gs://%s/tpuflow" % self.bucket,
            "TPUFLOW_DEFAULT_DATASTORE": "gs",
            "TPUFLOW_DEFAULT_METADATA": "service",
            "TPUFLOW_SERVICE_URL": self.metadata_url,
        }

    def write_state(self, path=STATE_FILE):
        with open(path, "w") as f:
            json.dump({"pid": os.getpid(), "env": self.env()}, f)
        return path


def read_state(path=STATE_FILE):
    """State of a running devstack, or None (missing file / dead pid)."""
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return None
    try:
        os.kill(state["pid"], 0)
    except (OSError, KeyError):
        return None
    return state


def stop_running(path=STATE_FILE):
    """SIGTERM a running devstack; returns True if one was signalled."""
    state = read_state(path)
    if state is None:
        return False
    try:
        os.kill(state["pid"], signal.SIGTERM)
    except OSError:
        return False
    try:
        os.unlink(path)
    except OSError:
        pass
    return True
