"""In-process fake GCS server: the MinIO trick without a binary.

Implements the slice of the GCS JSON API that metaflow_tpu.gsop speaks —
object get (with Range), media upload, compose, stat, list (prefix +
delimiter + paging), delete — backed by an in-memory dict. Tests point
TPUFLOW_GS_ENDPOINT at it and the ENTIRE gs:// stack (gsop, GCSStorage,
datastores, flow-level gs contexts) runs for real with no cloud access
(reference pattern: .github/workflows/metaflow.s3_tests.minio.yml).
"""

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeGCSState(object):
    def __init__(self):
        self.lock = threading.Lock()
        self.buckets = {}  # bucket -> {object_name: bytes}
        self.generations = {}  # (bucket, object_name) -> int
        self.request_count = 0
        self._gen_counter = 0

    def bucket(self, name):
        return self.buckets.setdefault(name, {})

    def bump_generation(self, bucket_name, obj):
        # caller holds self.lock
        self._gen_counter += 1
        self.generations[(bucket_name, obj)] = self._gen_counter
        return self._gen_counter

    def generation(self, bucket_name, obj):
        return self.generations.get((bucket_name, obj), 1)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state = None  # injected

    # ------------- helpers -------------

    def _send(self, status, body=b"", content_type="application/json",
              extra_headers=None):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, status, payload):
        self._send(status, json.dumps(payload).encode("utf-8"))

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length)

    def log_message(self, *args):
        pass

    # ------------- routes -------------

    def do_GET(self):
        with self.state.lock:
            self.state.request_count += 1
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))

        m = re.match(r"^/download/storage/v1/b/([^/]+)/o/([^/]+)$",
                     parsed.path)
        if m and params.get("alt") == "media":
            return self._download(m.group(1),
                                  urllib.parse.unquote(m.group(2)),
                                  params=params)

        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$", parsed.path)
        if m:
            return self._stat(m.group(1), urllib.parse.unquote(m.group(2)))

        m = re.match(r"^/storage/v1/b/([^/]+)/o$", parsed.path)
        if m:
            return self._list(m.group(1), params)

        self._json(404, {"error": "no route %s" % parsed.path})

    def do_POST(self):
        with self.state.lock:
            self.state.request_count += 1
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))

        m = re.match(r"^/upload/storage/v1/b/([^/]+)/o$", parsed.path)
        if m and params.get("uploadType") == "media":
            bucket_name = m.group(1)
            bucket = self.state.bucket(bucket_name)
            name = params["name"]
            data = self._body()
            with self.state.lock:
                bucket[name] = data
                gen = self.state.bump_generation(bucket_name, name)
            return self._json(200, {"name": name, "size": str(len(data)),
                                    "generation": str(gen)})

        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)/compose$",
                     parsed.path)
        if m:
            return self._compose(m.group(1),
                                 urllib.parse.unquote(m.group(2)))

        self._json(404, {"error": "no route %s" % parsed.path})

    def do_DELETE(self):
        with self.state.lock:
            self.state.request_count += 1
        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$",
                     urllib.parse.urlparse(self.path).path)
        if not m:
            return self._json(404, {"error": "no route"})
        bucket = self.state.bucket(m.group(1))
        name = urllib.parse.unquote(m.group(2))
        with self.state.lock:
            if name not in bucket:
                return self._json(404, {"error": "not found"})
            del bucket[name]
        self._send(204)

    # ------------- handlers -------------

    def _download(self, bucket_name, obj, params=None):
        bucket = self.state.bucket(bucket_name)
        with self.state.lock:
            data = bucket.get(obj)
            gen = self.state.generation(bucket_name, obj)
        if data is None:
            return self._json(404, {"error": "not found"})
        want_gen = (params or {}).get("generation")
        if want_gen and want_gen != str(gen):
            # GCS returns 404 for a generation that no longer exists
            return self._json(404, {"error": "generation %s gone" % want_gen})
        rng = self.headers.get("Range")
        if rng:
            m = re.match(r"bytes=(\d+)-(\d+)$", rng)
            start, end = int(m.group(1)), min(int(m.group(2)),
                                              len(data) - 1)
            return self._send(
                206, data[start:end + 1],
                content_type="application/octet-stream",
                extra_headers={
                    "Content-Range": "bytes %d-%d/%d"
                    % (start, end, len(data))
                },
            )
        self._send(200, data, content_type="application/octet-stream")

    def _stat(self, bucket_name, obj):
        bucket = self.state.bucket(bucket_name)
        with self.state.lock:
            data = bucket.get(obj)
        if data is None:
            return self._json(404, {"error": "not found"})
        with self.state.lock:
            gen = self.state.generation(bucket_name, obj)
        self._json(200, {"name": obj, "bucket": bucket_name,
                         "size": str(len(data)),
                         "generation": str(gen)})

    def _list(self, bucket_name, params):
        bucket = self.state.bucket(bucket_name)
        prefix = params.get("prefix", "")
        delimiter = params.get("delimiter")
        with self.state.lock:
            names = sorted(n for n in bucket if n.startswith(prefix))
        items, prefixes = [], set()
        for name in names:
            if delimiter:
                rest = name[len(prefix):]
                if delimiter in rest:
                    prefixes.add(
                        prefix + rest.split(delimiter)[0] + delimiter
                    )
                    continue
            with self.state.lock:
                items.append({"name": name,
                              "size": str(len(bucket[name]))})
        self._json(200, {"items": items, "prefixes": sorted(prefixes)})

    def _compose(self, bucket_name, dest):
        bucket = self.state.bucket(bucket_name)
        payload = json.loads(self._body())
        parts = []
        with self.state.lock:
            for src in payload["sourceObjects"]:
                data = bucket.get(src["name"])
                if data is None:
                    return self._json(404,
                                      {"error": "missing %s" % src["name"]})
                parts.append(data)
            bucket[dest] = b"".join(parts)
            size = len(bucket[dest])
            gen = self.state.bump_generation(bucket_name, dest)
        self._json(200, {"name": dest, "size": str(size),
                         "generation": str(gen)})


class FakeGCSServer(object):
    """Context manager: `with FakeGCSServer() as srv: ... srv.endpoint`."""

    def __init__(self, port=0):
        self.state = FakeGCSState()
        handler = type("BoundHandler", (_Handler,), {"state": self.state})
        self.server = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.endpoint = "http://127.0.0.1:%d" % self.server.server_port
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()
        return False


def main():
    """Run standalone (separate process): prints the endpoint, serves until
    killed. Benchmarks use this so client and server don't share a GIL."""
    import sys

    srv = FakeGCSServer()
    print(srv.endpoint, flush=True)
    srv._thread.start()
    try:
        srv._thread.join()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
