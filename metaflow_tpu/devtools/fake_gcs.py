"""In-process fake GCS server: the MinIO trick without a binary.

Implements the slice of the GCS JSON API that metaflow_tpu.gsop speaks —
object get (with Range), media upload, compose, stat, list (prefix +
delimiter + paging), delete — backed by an in-memory dict. Tests point
TPUFLOW_GS_ENDPOINT at it and the ENTIRE gs:// stack (gsop, GCSStorage,
datastores, flow-level gs contexts) runs for real with no cloud access
(reference pattern: .github/workflows/metaflow.s3_tests.minio.yml).
"""

import json
import os
import re
import socket
import tempfile
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeGCSState(object):
    def __init__(self):
        self.lock = threading.Lock()
        self.buckets = {}  # bucket -> {object_name: bytes}
        self.generations = {}  # (bucket, object_name) -> int
        self.request_count = 0
        self._gen_counter = 0

    def bucket(self, name):
        return self.buckets.setdefault(name, {})

    def bump_generation(self, bucket_name, obj):
        # caller holds self.lock
        self._gen_counter += 1
        self.generations[(bucket_name, obj)] = self._gen_counter
        return self._gen_counter

    def generation(self, bucket_name, obj):
        return self.generations.get((bucket_name, obj), 1)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state = None  # injected
    # injected per-request latency (seconds): models a real object
    # store's RTT so readahead/overlap machinery has latency to hide on
    # loopback — time.sleep releases the GIL, so concurrent requests
    # overlap their delays exactly like real network waits
    latency_s = 0.0

    # ------------- helpers -------------

    def _delay(self):
        if self.latency_s:
            import time

            time.sleep(self.latency_s)

    def _send(self, status, body=b"", content_type="application/json",
              extra_headers=None):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, status, payload):
        self._send(status, json.dumps(payload).encode("utf-8"))

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length)

    def log_message(self, *args):
        pass

    @staticmethod
    def _size_of(bucket, name):
        """Object size without reading the payload when the bucket can
        stat (disk mode); None when the object is missing."""
        sizer = getattr(bucket, "size", None)
        if sizer is not None:
            return sizer(name)
        data = bucket.get(name)
        return None if data is None else len(data)

    # ------------- routes -------------

    def do_GET(self):
        self._delay()
        with self.state.lock:
            self.state.request_count += 1
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))

        m = re.match(r"^/download/storage/v1/b/([^/]+)/o/([^/]+)$",
                     parsed.path)
        if m and params.get("alt") == "media":
            return self._download(m.group(1),
                                  urllib.parse.unquote(m.group(2)),
                                  params=params)

        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$", parsed.path)
        if m:
            return self._stat(m.group(1), urllib.parse.unquote(m.group(2)))

        m = re.match(r"^/storage/v1/b/([^/]+)/o$", parsed.path)
        if m:
            return self._list(m.group(1), params)

        self._json(404, {"error": "no route %s" % parsed.path})

    def do_POST(self):
        self._delay()
        with self.state.lock:
            self.state.request_count += 1
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))

        m = re.match(r"^/upload/storage/v1/b/([^/]+)/o$", parsed.path)
        if m and params.get("uploadType") == "media":
            bucket_name = m.group(1)
            bucket = self.state.bucket(bucket_name)
            name = params["name"]
            data = self._body()
            with self.state.lock:
                bucket[name] = data
                gen = self.state.bump_generation(bucket_name, name)
            return self._json(200, {"name": name, "size": str(len(data)),
                                    "generation": str(gen)})

        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)/compose$",
                     parsed.path)
        if m:
            return self._compose(m.group(1),
                                 urllib.parse.unquote(m.group(2)))

        self._json(404, {"error": "no route %s" % parsed.path})

    def do_DELETE(self):
        self._delay()
        with self.state.lock:
            self.state.request_count += 1
        m = re.match(r"^/storage/v1/b/([^/]+)/o/([^/]+)$",
                     urllib.parse.urlparse(self.path).path)
        if not m:
            return self._json(404, {"error": "no route"})
        bucket = self.state.bucket(m.group(1))
        name = urllib.parse.unquote(m.group(2))
        with self.state.lock:
            try:
                del bucket[name]
            except KeyError:
                # the lock is per-process: a concurrent cross-worker
                # delete of the same object must 404, not crash
                return self._json(404, {"error": "not found"})
        self._send(204)

    # ------------- handlers -------------

    def _download(self, bucket_name, obj, params=None):
        bucket = self.state.bucket(bucket_name)
        with self.state.lock:
            data = bucket.get(obj)
            gen = self.state.generation(bucket_name, obj)
        if data is None:
            return self._json(404, {"error": "not found"})
        want_gen = (params or {}).get("generation")
        if want_gen and want_gen != str(gen):
            # GCS returns 404 for a generation that no longer exists
            return self._json(404, {"error": "generation %s gone" % want_gen})
        rng = self.headers.get("Range")
        if rng:
            m = re.match(r"bytes=(\d+)-(\d+)$", rng)
            start, end = int(m.group(1)), min(int(m.group(2)),
                                              len(data) - 1)
            return self._send(
                206, data[start:end + 1],
                content_type="application/octet-stream",
                extra_headers={
                    "Content-Range": "bytes %d-%d/%d"
                    % (start, end, len(data))
                },
            )
        self._send(200, data, content_type="application/octet-stream")

    def _stat(self, bucket_name, obj):
        bucket = self.state.bucket(bucket_name)
        with self.state.lock:
            size = self._size_of(bucket, obj)
        if size is None:
            return self._json(404, {"error": "not found"})
        with self.state.lock:
            gen = self.state.generation(bucket_name, obj)
        self._json(200, {"name": obj, "bucket": bucket_name,
                         "size": str(size),
                         "generation": str(gen)})

    def _list(self, bucket_name, params):
        bucket = self.state.bucket(bucket_name)
        prefix = params.get("prefix", "")
        delimiter = params.get("delimiter")
        with self.state.lock:
            names = sorted(n for n in bucket if n.startswith(prefix))
        items, prefixes = [], set()
        for name in names:
            if delimiter:
                rest = name[len(prefix):]
                if delimiter in rest:
                    prefixes.add(
                        prefix + rest.split(delimiter)[0] + delimiter
                    )
                    continue
            with self.state.lock:
                size = self._size_of(bucket, name)
            if size is not None:  # deleted between snapshot and here
                items.append({"name": name, "size": str(size)})
        self._json(200, {"items": items, "prefixes": sorted(prefixes)})

    def _compose(self, bucket_name, dest):
        bucket = self.state.bucket(bucket_name)
        payload = json.loads(self._body())
        parts = []
        with self.state.lock:
            for src in payload["sourceObjects"]:
                data = bucket.get(src["name"])
                if data is None:
                    return self._json(404,
                                      {"error": "missing %s" % src["name"]})
                parts.append(data)
            bucket[dest] = b"".join(parts)
            size = len(bucket[dest])
            gen = self.state.bump_generation(bucket_name, dest)
        self._json(200, {"name": dest, "size": str(size),
                         "generation": str(gen)})


class FakeGCSServer(object):
    """Context manager: `with FakeGCSServer() as srv: ... srv.endpoint`."""

    def __init__(self, port=0, latency_ms=0.0):
        self.state = FakeGCSState()
        handler = type("BoundHandler", (_Handler,),
                       {"state": self.state,
                        "latency_s": float(latency_ms) / 1000.0})
        self.server = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.endpoint = "http://127.0.0.1:%d" % self.server.server_port
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()
        return False


class _DiskBucket(object):
    """Dict-shaped view of one bucket backed by files, so N server
    PROCESSES share state through the filesystem (atomic tmp+rename
    writes). Object names are percent-encoded into flat filenames."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.root, urllib.parse.quote(name, safe=""))

    def get(self, name, default=None):
        try:
            with open(self._path(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return default

    def __getitem__(self, name):
        data = self.get(name)
        if data is None:
            raise KeyError(name)
        return data

    def __setitem__(self, name, data):
        path = self._path(name)
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".inflight-")
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
        os.rename(tmp, path)

    def __delitem__(self, name):
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            raise KeyError(name)

    def __contains__(self, name):
        return os.path.exists(self._path(name))

    def size(self, name):
        """O(1) size via stat (the handler's list/stat paths use this
        instead of reading whole payloads); None when missing."""
        try:
            return os.stat(self._path(name)).st_size
        except OSError:
            return None

    def __iter__(self):
        for fn in os.listdir(self.root):
            if not fn.startswith(".inflight-"):
                yield urllib.parse.unquote(fn)


class FakeGCSDiskState(object):
    """Same surface as FakeGCSState, shared across worker processes via a
    directory (put it on tmpfs to keep the bench memory-speed).

    Generations must STRICTLY increase per object, but two rapid
    overwrites can land inside one filesystem timestamp quantum (the tmp
    file's mtime is set at write time and survives the rename) — so the
    issued generation is max(mtime_ns, last_issued + 1), tracked in a
    flock-guarded sidecar (named under the .inflight- prefix the listing
    already skips) and stamped back onto the object with utime."""

    def __init__(self, root):
        self.root = root
        self.lock = threading.Lock()  # per-process; renames are atomic
        self.request_count = 0

    def bucket(self, name):
        return _DiskBucket(
            os.path.join(self.root, urllib.parse.quote(name, safe=""))
        )

    def _gen_sidecar(self, bucket_name, obj):
        bucket = self.bucket(bucket_name)
        return os.path.join(
            bucket.root, ".inflight-gen-" + urllib.parse.quote(obj, safe="")
        )

    def bump_generation(self, bucket_name, obj):
        import fcntl

        path = self.bucket(bucket_name)._path(obj)
        try:
            with open(self._gen_sidecar(bucket_name, obj), "a+") as gf:
                fcntl.flock(gf, fcntl.LOCK_EX)
                gf.seek(0)
                raw = gf.read().strip()
                last = int(raw) if raw else 0
                st = os.stat(path)
                gen = max(st.st_mtime_ns, last + 1)
                if gen != st.st_mtime_ns:
                    os.utime(path, ns=(st.st_atime_ns, gen))
                gf.seek(0)
                gf.truncate()
                gf.write(str(gen))
                return gen
        except OSError:
            return 1

    def generation(self, bucket_name, obj):
        try:
            return os.stat(
                self.bucket(bucket_name)._path(obj)).st_mtime_ns
        except OSError:
            return 1


class _ReusePortHTTPServer(ThreadingHTTPServer):
    allow_reuse_address = True

    def server_bind(self):
        # set SO_REUSEPORT directly (the allow_reuse_port class attribute
        # only exists on newer socketserver versions): the kernel
        # load-balances accepts across the worker processes
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        ThreadingHTTPServer.server_bind(self)


def serve_cluster(workers, root, port=0, latency_ms=0.0):
    """Pre-fork N worker processes all bound to ONE port via SO_REUSEPORT,
    state shared through `root`. Returns (endpoint, child pids); the
    caller owns cleanup (SIGTERM the pids). This exists so gsop benchmark
    numbers measure the ENGINE, not a single-GIL test double."""
    # reserve a port with SO_REUSEPORT so children can re-bind it
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    probe.bind(("127.0.0.1", port))
    port = probe.getsockname()[1]

    pids = []
    for _ in range(workers):
        pid = os.fork()
        if pid == 0:  # child: serve forever
            code = 0
            try:
                state = FakeGCSDiskState(root)
                handler = type("BoundHandler", (_Handler,),
                               {"state": state,
                                "latency_s": float(latency_ms) / 1000.0})
                srv = _ReusePortHTTPServer(("127.0.0.1", port), handler)
                probe.close()
                srv.serve_forever()
            except BaseException:
                # a silently-dead worker would surface only as
                # connection-refused at the client — say why instead
                import traceback

                traceback.print_exc()
                code = 1
            finally:
                os._exit(code)
        pids.append(pid)
    probe.close()
    return "http://127.0.0.1:%d" % port, pids


def main():
    """Run standalone (separate process): prints the endpoint, serves until
    killed. Benchmarks use this so client and server don't share a GIL.

        python -m metaflow_tpu.devtools.fake_gcs [--workers N [--root DIR]]

    With --workers > 1, pre-forks N SO_REUSEPORT processes sharing state
    via --root (default: a fresh tmpfs-backed tempdir under /dev/shm)."""
    import signal
    import sys

    workers = 1
    root = None
    latency_ms = 0.0
    args = sys.argv[1:]
    while args:
        if args[0] == "--workers":
            workers = int(args[1])
            args = args[2:]
        elif args[0] == "--root":
            root = args[1]
            args = args[2:]
        elif args[0] == "--latency-ms":
            # injected per-request latency: benches use it to model a
            # remote object store's RTT over loopback
            latency_ms = float(args[1])
            args = args[2:]
        else:
            print("unknown arg %s" % args[0], file=sys.stderr)
            return 2

    if workers <= 1:
        srv = FakeGCSServer(latency_ms=latency_ms)
        print(srv.endpoint, flush=True)
        srv._thread.start()
        try:
            srv._thread.join()
        except KeyboardInterrupt:
            pass
        return 0

    made_root = root is None
    if root is None:
        base = "/dev/shm" if os.path.isdir("/dev/shm") else None
        root = tempfile.mkdtemp(prefix="fake-gcs-", dir=base)
    endpoint, pids = serve_cluster(workers, root, latency_ms=latency_ms)
    print(endpoint, flush=True)

    def _bye(*_):
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        if made_root:
            # tmpfs-backed object data must not outlive the server —
            # repeated bench runs would fill /dev/shm
            import shutil

            shutil.rmtree(root, ignore_errors=True)
        sys.exit(0)

    signal.signal(signal.SIGTERM, _bye)
    signal.signal(signal.SIGINT, _bye)
    for pid in pids:
        os.waitpid(pid, 0)
    return 0


if __name__ == "__main__":
    main()
