"""Deterministic fault injection: seeded kill schedules for gang steps.

Preemption testing must not depend on prod incidents: this harness turns
"rank 3 gets reclaimed at step 7, capacity shrinks to 4 hosts, comes
back 10 seconds later" into a reproducible unit test.

Kill delivery rides the EXACT production path — `notify_preemption`
drops the timestamped spot-notice marker and SIGTERMs the process, so
the PreemptionHandler, the gang teardown, the scheduler's failure
classification and the elastic supervisor's resize policy are all
exercised end to end, not mocked.

Environment contract (read by `from_env`, ticked by
`training/metrics.instrument_train_step` or an explicit
`chaos.maybe_chaos_step(step)` in the training loop):

    TPUFLOW_CHAOS=<seed>        seeded schedule: kills drawn from
                                default_rng(seed) over the horizon
    TPUFLOW_CHAOS=3:1,7:0       explicit schedule: kill rank 1 at step 3,
                                rank 0 at step 7
    TPUFLOW_CHAOS=3:1:hang      explicit fault KIND: rank 1 wedges
                                forever at step 3 (never exits — the
                                gang hang watchdog's prey)
    TPUFLOW_CHAOS=3:1:slow      bounded straggler: rank 1 sleeps
                                TPUFLOW_CHAOS_SLOW_S once at step 3,
                                then keeps training (the watchdog
                                false-positive guard)
    TPUFLOW_CHAOS_STEPS=N       seeded horizon (default 10)
    TPUFLOW_CHAOS_NKILLS=K      kills drawn from the seed (default 1)
    TPUFLOW_CHAOS_SLOW_S=T      straggler delay for :slow (default 1.0)
    TPUFLOW_CHAOS_DIR=path      once-only ledger dir (defaults to a
                                per-run dir under the system tempdir)

Each scheduled kill fires AT MOST ONCE per run: delivery claims a
ledger file with O_EXCL, so the retried (resumed) gang replaying the
same step numbers does not re-kill itself forever. The capacity side of
a scenario is scripted on the scheduler via
TPUFLOW_CAPACITY_ORACLE=scripted:... (elastic/oracle.py) — together
they make shrink/grow/repeated-kill scenarios deterministic.
"""

import os
import tempfile
import time

from .. import knobs, telemetry

CHAOS_ENV = "TPUFLOW_CHAOS"
STEPS_ENV = "TPUFLOW_CHAOS_STEPS"
NKILLS_ENV = "TPUFLOW_CHAOS_NKILLS"
DIR_ENV = "TPUFLOW_CHAOS_DIR"
SLOW_S_ENV = "TPUFLOW_CHAOS_SLOW_S"

# fault kinds an explicit schedule entry may name ("step:rank:kind")
KIND_KILL = "kill"    # spot-notice marker + SIGTERM (the default)
KIND_HANG = "hang"    # wedge forever in-step: main thread sleeps until
                      # something from outside kills the process
KIND_SLOW = "slow"    # bounded once-only straggler delay, then proceed
FAULT_KINDS = (KIND_KILL, KIND_HANG, KIND_SLOW)

# serving-fleet variant: kills are indexed by DISPATCH COUNT (the
# router's monotonically increasing request-dispatch counter), not train
# step, and the victim coordinate is a replica index, not a gang rank
FLEET_ENV = "TPUFLOW_CHAOS_FLEET"
FLEET_DISPATCHES_ENV = "TPUFLOW_CHAOS_FLEET_DISPATCHES"
FLEET_NKILLS_ENV = "TPUFLOW_CHAOS_FLEET_NKILLS"


class KillSchedule(object):
    """An immutable set of (step, rank) fault events.

    `.kills` stays a tuple of 2-tuples — the seeded replay tests and the
    fleet injector iterate it positionally — while the optional fault
    kind of each event rides beside it in `.kinds` (missing = "kill")."""

    def __init__(self, kills, kinds=None):
        self.kills = tuple(sorted({(int(s), int(r)) for s, r in kills}))
        self.kinds = {
            (int(s), int(r)): str(k)
            for (s, r), k in (kinds or {}).items()
        }

    def kind_of(self, step, rank):
        return self.kinds.get((int(step), int(rank)), KIND_KILL)

    @classmethod
    def parse(cls, spec):
        """"3:1,7:0" -> kill rank 1 at step 3, rank 0 at step 7.
        A third field names the fault kind: "3:1:hang", "5:0:slow"."""
        kills = []
        kinds = {}
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) not in (2, 3):
                raise ValueError(
                    "chaos schedule entry %r is not step:rank[:kind]"
                    % part)
            step, rank = int(fields[0]), int(fields[1])
            kills.append((step, rank))
            if len(fields) == 3:
                kind = fields[2].strip().lower()
                if kind not in FAULT_KINDS:
                    raise ValueError(
                        "unknown chaos fault kind %r (one of %s)"
                        % (kind, ", ".join(FAULT_KINDS)))
                if kind != KIND_KILL:
                    kinds[(step, rank)] = kind
        return cls(kills, kinds)

    @classmethod
    def seeded(cls, seed, n_steps, world, n_kills=1):
        """A pure function of (seed, n_steps, world, n_kills): every rank
        of the gang — and every retry attempt — computes the identical
        schedule with no coordination. Kills land in [1, n_steps-1]
        (never step 0: a gang killed before its first checkpoint has
        nothing to prove about resume)."""
        import numpy as np

        rng = np.random.default_rng([int(seed), int(n_steps), int(world)])
        hi = max(2, int(n_steps))
        steps = rng.choice(
            np.arange(1, hi), size=min(int(n_kills), hi - 1), replace=False)
        ranks = rng.integers(0, max(1, int(world)), size=len(steps))
        return cls(zip(steps.tolist(), ranks.tolist()))

    def kills_for_rank(self, rank):
        return [s for s, r in self.kills if r == int(rank)]

    def __iter__(self):
        return iter(self.kills)

    def __len__(self):
        return len(self.kills)


class ChaosInjector(object):
    """Per-process kill dispatcher: tick `on_step(step)` at each train
    step boundary; scheduled (step, my_rank) events deliver a real
    preemption notice to this process, once per run."""

    def __init__(self, schedule, rank, world, ledger_dir, notify=None):
        if notify is None:
            from ..plugins.tpu.preemption import notify_preemption

            notify = notify_preemption
        self.schedule = schedule
        self.rank = int(rank)
        self.world = int(world)
        self.ledger_dir = ledger_dir
        self._notify = notify
        self._my_steps = {
            s: schedule.kind_of(s, self.rank)
            for s in schedule.kills_for_rank(self.rank)
        }

    def _claim(self, step, kind=KIND_KILL):
        """True iff THIS call is the first delivery of (step, rank) in
        the run — O_EXCL on a ledger file arbitrates across attempts
        (and across racing processes on the same host). Kill events keep
        their historical ledger name; other kinds are kind-prefixed."""
        os.makedirs(self.ledger_dir, exist_ok=True)
        path = os.path.join(
            self.ledger_dir, "%s-%d-%d" % (kind, int(step), self.rank))
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def _hang(self, step):
        """Wedge this rank forever, exactly like a stuck collective or
        deadlocked I/O would: the thread-driven heartbeat keeps beating,
        progress stops, and nothing here ever returns. Flush first — a
        SIGKILLed process loses buffered records, and the event is the
        e2e's proof the fault fired."""
        telemetry.event(
            "chaos.hang",
            data={"step": int(step), "rank": self.rank,
                  "world": self.world})
        telemetry.flush()
        while True:
            time.sleep(3600)

    def _slow(self, step):
        """Bounded straggler: one long-but-finite delay, then the step
        proceeds. Progress resumes before any sane deadline, so the hang
        watchdog must NOT fire (the false-positive guard)."""
        delay_s = knobs.get_float(SLOW_S_ENV)
        telemetry.event(
            "chaos.slow",
            data={"step": int(step), "rank": self.rank,
                  "world": self.world, "delay_s": delay_s})
        time.sleep(delay_s)

    def on_step(self, step):
        """Deliver any scheduled fault for (step, this rank). Returns
        True when a kill notice was just delivered (the SIGTERM raise is
        typically already unwinding the stack by then); a hang never
        returns."""
        kind = self._my_steps.get(int(step))
        if kind is None:
            return False
        if not self._claim(step, kind):
            return False
        if kind == KIND_HANG:
            self._hang(step)
        if kind == KIND_SLOW:
            self._slow(step)
            return False
        telemetry.event(
            "chaos.kill",
            data={"step": int(step), "rank": self.rank,
                  "world": self.world})
        self._notify(os.getpid())
        return True


def _default_ledger_dir():
    """Per-run ledger so once-only semantics span attempts but never leak
    across runs. Falls back to a pid-keyed dir outside a task context."""
    run_id = None
    try:
        from ..current import current

        run_id = current.run_id
        flow = current.flow_name
    except Exception:
        flow = None
    if run_id:
        name = "tpuflow-chaos-%s-%s" % (flow or "flow", run_id)
    else:
        name = "tpuflow-chaos-%d" % os.getppid()
    return os.path.join(tempfile.gettempdir(), name)


def schedule_from_env(world, env=None):
    """The configured KillSchedule, or None when chaos is off."""
    env = env if env is not None else os.environ
    spec = (knobs.get_str(CHAOS_ENV, env=env) or "").strip()
    if not spec:
        return None
    if ":" in spec:
        return KillSchedule.parse(spec)
    n_steps = knobs.get_int(STEPS_ENV, env=env)
    n_kills = knobs.get_int(NKILLS_ENV, env=env)
    return KillSchedule.seeded(int(spec), n_steps, world, n_kills)


def from_env(rank=None, world=None, env=None):
    """Build the process's ChaosInjector from the environment, or None
    when TPUFLOW_CHAOS is unset. rank/world default to the gang env."""
    env = env if env is not None else os.environ
    if rank is None:
        rank = int(env.get("MF_PARALLEL_NODE_INDEX", "0"))
    if world is None:
        world = int(env.get("MF_PARALLEL_NUM_NODES", "1"))
    schedule = schedule_from_env(world, env=env)
    if schedule is None:
        return None
    ledger = knobs.get_str(DIR_ENV, env=env) or _default_ledger_dir()
    return ChaosInjector(schedule, rank, world, ledger)


class FleetChaosInjector(object):
    """Replica-kill dispatcher for the serving fleet: the router ticks
    `on_dispatch(n, n_replicas)` every time it forwards a request; a
    scheduled (dispatch, replica) event names the victim ONCE (O_EXCL
    ledger, same arbitration as the gang injector). Delivery is the
    caller's job — serving/fleet.py SIGKILLs the replica process, so the
    failure rides the real process-death path (monitor reap, relay-
    thread failover, BackoffPolicy restart), nothing mocked.

    The schedule reuses KillSchedule: "step" is the dispatch ordinal,
    "rank" is the replica index.

        TPUFLOW_CHAOS_FLEET=<seed>          seeded schedule
        TPUFLOW_CHAOS_FLEET=5:1             kill replica 1 on the 5th
                                            dispatch
        TPUFLOW_CHAOS_FLEET_DISPATCHES=N    seeded horizon (default 8)
        TPUFLOW_CHAOS_FLEET_NKILLS=K        kills drawn (default 1)
    """

    def __init__(self, schedule, ledger_dir):
        self.schedule = schedule
        self.ledger_dir = ledger_dir
        self._by_dispatch = {}
        for dispatch, replica in schedule:
            self._by_dispatch.setdefault(int(dispatch), []).append(
                int(replica))

    def _claim(self, dispatch, replica):
        os.makedirs(self.ledger_dir, exist_ok=True)
        path = os.path.join(
            self.ledger_dir,
            "fleetkill-%d-%d" % (int(dispatch), int(replica)))
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def on_dispatch(self, dispatch, n_replicas):
        """The replica index to kill at this dispatch ordinal, or None.
        Out-of-range victims (schedule written for a bigger fleet) wrap
        into the live replica set."""
        victims = self._by_dispatch.get(int(dispatch))
        if not victims:
            return None
        for replica in victims:
            replica = replica % max(1, int(n_replicas))
            if not self._claim(dispatch, replica):
                continue
            telemetry.event(
                "chaos.replica_kill",
                data={"dispatch": int(dispatch), "replica": replica,
                      "replicas": int(n_replicas)})
            return replica
        return None


def fleet_schedule_from_env(n_replicas, env=None):
    """The configured fleet KillSchedule, or None when fleet chaos is
    off."""
    env = env if env is not None else os.environ
    spec = (knobs.get_str(FLEET_ENV, env=env) or "").strip()
    if not spec:
        return None
    if ":" in spec:
        return KillSchedule.parse(spec)
    horizon = knobs.get_int(FLEET_DISPATCHES_ENV, env=env)
    n_kills = knobs.get_int(FLEET_NKILLS_ENV, env=env)
    return KillSchedule.seeded(int(spec), horizon, n_replicas, n_kills)


def fleet_from_env(n_replicas, env=None):
    """Build the router's FleetChaosInjector from the environment, or
    None when TPUFLOW_CHAOS_FLEET is unset."""
    env = env if env is not None else os.environ
    schedule = fleet_schedule_from_env(n_replicas, env=env)
    if schedule is None:
        return None
    ledger = knobs.get_str(DIR_ENV, env=env) or _default_ledger_dir()
    return FleetChaosInjector(schedule, ledger)


_injector_cache = {}


def maybe_chaos_step(step):
    """Module-level tick for instrumented training loops: no-op unless
    TPUFLOW_CHAOS is set. The injector is cached per (pid, rank) — gang
    worker processes each build their own."""
    if not knobs.get_str(CHAOS_ENV):
        return False
    key = (os.getpid(), os.environ.get("MF_PARALLEL_NODE_INDEX", "0"))
    if key not in _injector_cache:
        _injector_cache[key] = from_env()
    injector = _injector_cache[key]
    if injector is None:
        return False
    return injector.on_step(step)
