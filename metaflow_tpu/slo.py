"""Declarative SLO rules over live telemetry-derived metrics.

A rule is an upper bound on one metric: ``p99_ttft_ms <= 500``. Rules come
from a JSON file (``--slo PATH`` / ``TPUFLOW_SLO_FILE``) or from
``TPUFLOW_SLO_*`` environment shorthands, and are evaluated in two places
against the same metric names: the fleet supervisor's health loop (which
emits the pinned ``slo.breach`` telemetry event and surfaces breach state
in ``/healthz``) and the ``tpuflow watch`` watchtower (whose ``--check``
mode exits non-zero on a breach so CI can gate on it).

JSON rule file format::

    {"rules": [
        {"name": "ttft", "metric": "p99_ttft_ms", "max": 500},
        {"name": "stall", "metric": "input_stall_frac", "max": 0.2}
    ]}

Environment shorthands (value = threshold)::

    TPUFLOW_SLO_P99_TTFT_MS            -> p99_ttft_ms
    TPUFLOW_SLO_P99_ITL_MS             -> p99_itl_ms
    TPUFLOW_SLO_INPUT_STALL_FRAC       -> input_stall_frac
    TPUFLOW_SLO_RESTART_RATE_PER_MIN   -> replica_restart_rate_per_min
    TPUFLOW_SLO_DESYNC                 -> desync_count
    TPUFLOW_SLO_TENANT_P99_TTFT_MS     -> tenant.<id>.p99_ttft_ms (every
                                          tenant; see tenant_rules())

A rule whose metric is absent from the metrics dict (or None) is not
evaluated — an idle fleet with no latency samples yet is not in breach.
"""

import json
import os

from . import knobs

# env shorthand -> metric name; the metric vocabulary is shared with
# ServingFleet.slo_metrics() and cmd/watch.WatchState.metrics()
ENV_RULES = (
    ("TPUFLOW_SLO_P99_TTFT_MS", "p99_ttft_ms"),
    ("TPUFLOW_SLO_P99_ITL_MS", "p99_itl_ms"),
    ("TPUFLOW_SLO_INPUT_STALL_FRAC", "input_stall_frac"),
    ("TPUFLOW_SLO_RESTART_RATE_PER_MIN", "replica_restart_rate_per_min"),
    ("TPUFLOW_SLO_DESYNC", "desync_count"),
)

SLO_FILE_VAR = "TPUFLOW_SLO_FILE"

# per-tenant shorthands: the threshold applies to EVERY tenant's metric
# (tenant.<id>.<metric>), synthesized against the live metric set by
# tenant_rules() because the tenant population is dynamic
TENANT_ENV_RULES = (
    ("TPUFLOW_SLO_TENANT_P99_TTFT_MS", "p99_ttft_ms"),
)


class SLORule(object):
    """One upper-bound rule: breach when metrics[metric] > max."""

    __slots__ = ("name", "metric", "max")

    def __init__(self, name, metric, max):
        self.name = str(name)
        self.metric = str(metric)
        self.max = float(max)

    def __repr__(self):
        return "SLORule(%s: %s <= %g)" % (self.name, self.metric, self.max)


def load_rules(path=None, env=None):
    """Rules from a JSON file and/or TPUFLOW_SLO_* env vars (file first,
    env appended). Returns [] when neither is configured. A malformed
    file raises ValueError — a silently dropped SLO is worse than a
    failed startup."""
    env = os.environ if env is None else env
    rules = []
    path = path or knobs.get_str(SLO_FILE_VAR, env=env)
    if path:
        with open(path) as f:
            doc = json.load(f)
        entries = doc.get("rules") if isinstance(doc, dict) else None
        if not isinstance(entries, list):
            raise ValueError(
                "SLO file %s must be {\"rules\": [...]}" % path)
        for e in entries:
            try:
                rules.append(SLORule(
                    e.get("name", e["metric"]), e["metric"], e["max"]))
            except (KeyError, TypeError, ValueError):
                raise ValueError("bad SLO rule in %s: %r" % (path, e))
    for var, metric in ENV_RULES:
        raw = env.get(var)
        if raw in (None, ""):
            continue
        try:
            rules.append(SLORule(metric, metric, float(raw)))
        except ValueError:
            raise ValueError("%s=%r is not a number" % (var, raw))
    return rules


def tenant_rules(metrics, env=None):
    """Per-tenant rules from TPUFLOW_SLO_TENANT_* shorthands: one rule
    per ``tenant.<id>.<metric>`` key present in `metrics`. Returns []
    when no shorthand is set — the common path stays allocation-free.
    Evaluated fresh each health tick so tenants that appear (or idle
    out) after startup are covered without a restart."""
    env = os.environ if env is None else env
    rules = []
    for var, metric in TENANT_ENV_RULES:
        raw = env.get(var)
        if raw in (None, ""):
            continue
        try:
            bound = float(raw)
        except ValueError:
            raise ValueError("%s=%r is not a number" % (var, raw))
        suffix = "." + metric
        for name in sorted(metrics):
            if name.startswith("tenant.") and name.endswith(suffix):
                rules.append(SLORule(name, name, bound))
    return rules


def evaluate(rules, metrics):
    """Breach dicts for every rule whose metric exceeds its bound. The
    dict shape is pinned as SLO_BREACH_SCHEMA — it is also the data
    payload of the slo.breach telemetry event."""
    breaches = []
    for rule in rules:
        value = metrics.get(rule.metric)
        if value is None:
            continue
        value = float(value)
        if value > rule.max:
            breaches.append({
                "rule": rule.name,
                "metric": rule.metric,
                "value": round(value, 4),
                "threshold": rule.max,
            })
    return breaches
