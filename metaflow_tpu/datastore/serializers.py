"""Artifact serializer registry — JAX/numpy arrays are first-class.

Reference shape: metaflow/datastore/artifacts/serializer.py (priority-ordered
registry, pickle as the 9999 fallback). TPU-first choices:

  - `jax.Array` / `np.ndarray` serialize as .npy bytes after a single
    device→host transfer (`jax.device_get`), never through pickle's memo
    machinery — multi-GB arrays stream at memcpy speed.
  - pytrees of arrays (dicts/lists/tuples/flax state) go through a
    treedef + packed-arrays format for the same reason.
  - everything else falls back to pickle (highest protocol).

Each serializer returns (payload_bytes, type_tag); deserialization dispatches
on the stored tag, so formats can evolve independently.
"""

import io
import pickle

import numpy as np

TYPE_NPY = "npy"
TYPE_PYTREE = "pytree"
TYPE_PICKLE = "pickle"


def _is_jax_array(obj):
    try:
        import jax

        return isinstance(obj, jax.Array)
    except ImportError:
        return False


def _tree_only_arrays(obj, depth=0):
    """True if obj is a (nested) dict/list/tuple whose leaves are all
    arrays/scalars — eligible for the fast pytree format."""
    if depth > 16:
        return False
    if isinstance(obj, (np.ndarray,)) or _is_jax_array(obj):
        return True
    if isinstance(obj, (int, float, bool)) or obj is None:
        return True
    if isinstance(obj, dict):
        return all(isinstance(k, str) for k in obj) and all(
            _tree_only_arrays(v, depth + 1) for v in obj.values()
        )
    if isinstance(obj, (list, tuple)):
        return bool(obj) and all(_tree_only_arrays(v, depth + 1) for v in obj)
    return False


def _to_host(arr):
    if _is_jax_array(arr):
        import jax

        return np.asarray(jax.device_get(arr))
    return arr


def _npy_bytes(arr):
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def _npy_load(data):
    return np.load(io.BytesIO(data), allow_pickle=False)


def serialize(obj):
    """Return (payload_bytes, type_tag)."""
    if isinstance(obj, np.ndarray) and obj.dtype != object:
        return _npy_bytes(obj), TYPE_NPY
    if _is_jax_array(obj):
        return _npy_bytes(_to_host(obj)), TYPE_NPY
    if isinstance(obj, (dict, list, tuple)) and _tree_only_arrays(obj):
        return _pytree_bytes(obj), TYPE_PYTREE
    return pickle.dumps(_pickle_safe(obj), protocol=pickle.HIGHEST_PROTOCOL), TYPE_PICKLE


def deserialize(payload, type_tag):
    if type_tag == TYPE_NPY:
        return _npy_load(payload)
    if type_tag == TYPE_PYTREE:
        return _pytree_load(payload)
    return pickle.loads(payload)


def _pickle_safe(obj):
    """Move any device-resident arrays in an arbitrary object graph to host
    before pickling (a jax.Array inside a random user object would otherwise
    force pickle through a slow fallback or fail on non-addressable shards)."""
    if _is_jax_array(obj):
        return _to_host(obj)
    if isinstance(obj, dict):
        return {k: _pickle_safe(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_pickle_safe(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_pickle_safe(v) for v in obj)
    return obj


# ---- pytree format: json header (structure) + concatenated npy blocks ----

import json


def _pytree_bytes(tree):
    leaves = []

    def encode(node):
        if isinstance(node, dict):
            return {"t": "d", "v": {k: encode(v) for k, v in node.items()}}
        if isinstance(node, list):
            return {"t": "l", "v": [encode(v) for v in node]}
        if isinstance(node, tuple):
            return {"t": "t", "v": [encode(v) for v in node]}
        if isinstance(node, (np.ndarray,)) or _is_jax_array(node):
            leaves.append(_npy_bytes(_to_host(node)))
            return {"t": "a", "i": len(leaves) - 1}
        # scalar leaf
        return {"t": "s", "v": node}

    structure = encode(tree)
    header = json.dumps(
        {"structure": structure, "sizes": [len(b) for b in leaves]}
    ).encode("utf-8")
    out = io.BytesIO()
    out.write(len(header).to_bytes(8, "little"))
    out.write(header)
    for b in leaves:
        out.write(b)
    return out.getvalue()


def _pytree_load(data):
    hlen = int.from_bytes(data[:8], "little")
    header = json.loads(data[8 : 8 + hlen].decode("utf-8"))
    offset = 8 + hlen
    leaves = []
    for size in header["sizes"]:
        leaves.append(_npy_load(data[offset : offset + size]))
        offset += size

    def decode(node):
        t = node["t"]
        if t == "d":
            return {k: decode(v) for k, v in node["v"].items()}
        if t == "l":
            return [decode(v) for v in node["v"]]
        if t == "t":
            return tuple(decode(v) for v in node["v"])
        if t == "a":
            return leaves[node["i"]]
        return node["v"]

    return decode(header["structure"])
