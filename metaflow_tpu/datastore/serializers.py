"""Artifact serializer registry — JAX/numpy arrays are first-class.

Reference shape: metaflow/datastore/artifacts/serializer.py (priority-ordered
registry, pickle as the 9999 fallback). TPU-first choices:

  - `jax.Array` / `np.ndarray` serialize as .npy bytes after a single
    device→host transfer (`jax.device_get`), never through pickle's memo
    machinery — multi-GB arrays stream at memcpy speed.
  - pytrees of arrays (dicts/lists/tuples/flax state) go through a
    treedef + packed-arrays format for the same reason.
  - everything else falls back to pickle (highest protocol).

Each serializer returns (payload_bytes, type_tag); deserialization dispatches
on the stored tag, so formats can evolve independently.
"""

import io
import pickle

import numpy as np

import sys

TYPE_NPY = "npy"        # legacy numpy .npy payloads (read-only support)
TYPE_TENSOR = "tensor"  # raw-bytes tensor format (handles TPU dtypes)
TYPE_PYTREE = "pytree"
TYPE_PICKLE = "pickle"

_NATIVE_LITTLE = sys.byteorder == "little"


def _is_jax_array(obj):
    try:
        import jax

        return isinstance(obj, jax.Array)
    except ImportError:
        return False


_TENSOR_KINDS = frozenset("biufc")  # bool/int/uint/float/complex


def _tensor_dtype_ok(dtype):
    """True when the raw-bytes tensor format can round-trip this dtype:
    numeric numpy kinds plus the ml_dtypes TPU types (bfloat16, float8_*)."""
    if dtype.kind in _TENSOR_KINDS:
        return True
    try:
        import ml_dtypes

        return hasattr(ml_dtypes, dtype.name)
    except ImportError:
        return False


def _tree_only_arrays(obj, depth=0):
    """True if obj is a (nested) dict/list/tuple whose leaves are all
    arrays/scalars — eligible for the fast pytree format.

    Container types must match EXACTLY: subclasses (namedtuples, OrderedDict,
    defaultdict, flax FrozenDict...) fall through to pickle, which preserves
    their type — the pytree format would silently flatten them to plain
    dict/list/tuple (e.g. optax's ScaleByAdamState namedtuple)."""
    if depth > 16:
        return False
    if isinstance(obj, np.ndarray):
        return _tensor_dtype_ok(obj.dtype)
    if _is_jax_array(obj):
        return True
    if obj is None or type(obj) in (int, float, bool):
        return True
    if type(obj) is dict:
        return all(isinstance(k, str) for k in obj) and all(
            _tree_only_arrays(v, depth + 1) for v in obj.values()
        )
    if type(obj) in (list, tuple):
        return bool(obj) and all(_tree_only_arrays(v, depth + 1) for v in obj)
    return False


def _to_host(arr):
    if _is_jax_array(arr):
        import jax

        return np.asarray(jax.device_get(arr))
    return arr


def prefetch_to_host(obj, depth=0):
    """Eagerly START device→host transfers for every jax array reachable
    through common containers, without blocking on any of them.

    The persist pipeline calls this once over the whole artifact set
    before serialization begins: `copy_to_host_async` enqueues all the
    D2H copies back-to-back on the device's transfer stream, so the
    per-artifact `_to_host` calls that follow complete already-in-flight
    copies instead of issuing cold, serialized ones. Best-effort by
    design — an array that cannot prefetch (non-addressable shards, old
    jax) simply pays the normal blocking transfer later.
    """
    if depth > 16:
        return
    if _is_jax_array(obj):
        try:
            obj.copy_to_host_async()
        except Exception:
            pass
        return
    if isinstance(obj, dict):
        for v in obj.values():
            prefetch_to_host(v, depth + 1)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            prefetch_to_host(v, depth + 1)


def _npy_bytes(arr):
    """Tensor format: json header {dtype, shape} + raw C-order bytes.

    Unlike .npy this round-trips TPU dtypes (bfloat16, float8_*) which numpy
    itself can't describe — ml_dtypes resolves them on load. Data is stored
    native-endian (non-native input is byteswapped first)."""
    arr = np.ascontiguousarray(arr)
    native = "<" if _NATIVE_LITTLE else ">"
    if arr.dtype.byteorder not in ("=", "|", native):
        # normalize to the native order so tobytes/frombuffer agree
        arr = arr.astype(arr.dtype.newbyteorder("="))
    header = json.dumps({"dtype": arr.dtype.name, "shape": list(arr.shape)}).encode(
        "utf-8"
    )
    return len(header).to_bytes(4, "little") + header + arr.tobytes()


def _resolve_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _npy_load(data):
    hlen = int.from_bytes(data[:4], "little")
    header = json.loads(data[4 : 4 + hlen].decode("utf-8"))
    dtype = _resolve_dtype(header["dtype"])
    return np.frombuffer(
        data[4 + hlen :], dtype=dtype
    ).reshape(header["shape"]).copy()


class ArtifactSerializer(object):
    """One pluggable artifact format (reference shape:
    metaflow/datastore/artifacts/serializer.py). Subclass, set a unique
    `type_tag` and a `priority` (lower runs earlier; the pickle fallback
    sits at 9999), and register with `register_serializer` — directly or
    from an extension package's `SERIALIZERS` list."""

    type_tag = None
    priority = 100

    def can_serialize(self, obj):  # pragma: no cover - interface
        raise NotImplementedError

    def serialize(self, obj):  # -> bytes
        raise NotImplementedError

    def deserialize(self, payload):  # -> object
        raise NotImplementedError


_SERIALIZERS = []  # kept sorted by (priority, registration order)
_BY_TAG = {}


def register_serializer(serializer):
    """Register (or override, by type_tag) an ArtifactSerializer INSTANCE.
    Returns the instance so it can be used as a decorator on a class via
    ``register_serializer(MySerializer())``-style calls in extensions."""
    if not serializer.type_tag:
        raise ValueError("serializer needs a non-empty type_tag")
    existing = _BY_TAG.get(serializer.type_tag)
    if existing is not None:
        _SERIALIZERS.remove(existing)
    _BY_TAG[serializer.type_tag] = serializer
    _SERIALIZERS.append(serializer)
    _SERIALIZERS.sort(key=lambda s: s.priority)
    return serializer


def serialize(obj):
    """Return (payload_bytes, type_tag) via the first serializer that
    accepts obj (priority order; pickle always accepts)."""
    for s in _SERIALIZERS:
        if s.can_serialize(obj):
            return s.serialize(obj), s.type_tag
    raise RuntimeError("no serializer accepted %r" % type(obj))


def deserialize(payload, type_tag):
    s = _BY_TAG.get(type_tag)
    if s is None:
        raise ValueError(
            "artifact has unknown type_tag %r — written by a newer version "
            "or by an extension serializer that isn't installed here"
            % type_tag
        )
    return s.deserialize(payload)


class _TensorSerializer(ArtifactSerializer):
    """jax.Array / np.ndarray → header + raw bytes (one device→host copy,
    TPU dtypes included — see _npy_bytes)."""

    type_tag = TYPE_TENSOR
    priority = 10

    def can_serialize(self, obj):
        if isinstance(obj, np.ndarray):
            return _tensor_dtype_ok(obj.dtype)
        return _is_jax_array(obj)

    def serialize(self, obj):
        return _npy_bytes(_to_host(obj))

    def deserialize(self, payload):
        return _npy_load(payload)


class _PytreeSerializer(ArtifactSerializer):
    """Exact dict/list/tuple trees of arrays → treedef + packed tensors."""

    type_tag = TYPE_PYTREE
    priority = 20

    def can_serialize(self, obj):
        return type(obj) in (dict, list, tuple) and _tree_only_arrays(obj)

    def serialize(self, obj):
        return _pytree_bytes(obj)

    def deserialize(self, payload):
        return _pytree_load(payload)


class _LegacyNpySerializer(ArtifactSerializer):
    """Read-only: artifacts written as real .npy by earlier versions."""

    type_tag = TYPE_NPY
    priority = 10_000  # never chosen for writes

    def can_serialize(self, obj):
        return False

    def deserialize(self, payload):
        return np.load(io.BytesIO(payload), allow_pickle=False)


class _PickleSerializer(ArtifactSerializer):
    """The universal fallback (device arrays moved to host first)."""

    type_tag = TYPE_PICKLE
    priority = 9999

    def can_serialize(self, obj):
        return True

    def serialize(self, obj):
        return pickle.dumps(
            _pickle_safe(obj), protocol=pickle.HIGHEST_PROTOCOL
        )

    def deserialize(self, payload):
        return pickle.loads(payload)


for _s in (_TensorSerializer(), _PytreeSerializer(), _LegacyNpySerializer(),
           _PickleSerializer()):
    register_serializer(_s)


def _pickle_safe(obj):
    """Move any device-resident arrays in an arbitrary object graph to host
    before pickling (a jax.Array inside a random user object would otherwise
    force pickle through a slow fallback or fail on non-addressable shards).
    Container *types* are preserved: namedtuples rebuild via their class,
    dict subclasses via .copy() — flattening optax state to a plain tuple
    would break attribute access on load."""
    if _is_jax_array(obj):
        return _to_host(obj)
    if type(obj) is dict:
        return {k: _pickle_safe(v) for k, v in obj.items()}
    if type(obj) is list:
        return [_pickle_safe(v) for v in obj]
    if type(obj) is tuple:
        return tuple(_pickle_safe(v) for v in obj)
    if isinstance(obj, tuple):
        vals = [_pickle_safe(v) for v in obj]
        if all(v is o for v, o in zip(vals, obj)):
            return obj  # nothing device-resident inside: keep as-is
        if hasattr(obj, "_fields"):  # namedtuple: _make bypasses custom __new__
            try:
                return type(obj)._make(vals)
            except Exception:
                return tuple(vals)
        try:
            return type(obj)(vals)
        except Exception:
            return tuple(vals)  # host transfer beats type fidelity
    if isinstance(obj, dict):
        vals = {k: _pickle_safe(v) for k, v in obj.items()}
        if all(vals[k] is obj[k] for k in obj):
            return obj
        try:
            clone = obj.copy()  # preserves OrderedDict/defaultdict/UserDict
            clone.update(vals)
            return clone
        except Exception:
            return vals
    if isinstance(obj, list):
        vals = [_pickle_safe(v) for v in obj]
        if all(v is o for v, o in zip(vals, obj)):
            return obj
        try:
            clone = obj.copy()
            clone[:] = vals
            return clone
        except Exception:
            return vals
    return obj


# ---- pytree format: json header (structure) + concatenated npy blocks ----

import json


def _pytree_bytes(tree):
    leaves = []

    def encode(node):
        # exact-type dispatch mirrors _tree_only_arrays: subclasses never
        # reach here (they route the whole tree to pickle)
        if type(node) is dict:
            return {"t": "d", "v": {k: encode(v) for k, v in node.items()}}
        if type(node) is list:
            return {"t": "l", "v": [encode(v) for v in node]}
        if type(node) is tuple:
            return {"t": "t", "v": [encode(v) for v in node]}
        if isinstance(node, (np.ndarray,)) or _is_jax_array(node):
            leaves.append(_npy_bytes(_to_host(node)))
            return {"t": "a", "i": len(leaves) - 1}
        # scalar leaf
        return {"t": "s", "v": node}

    structure = encode(tree)
    header = json.dumps(
        {"structure": structure, "sizes": [len(b) for b in leaves]}
    ).encode("utf-8")
    out = io.BytesIO()
    out.write(len(header).to_bytes(8, "little"))
    out.write(header)
    for b in leaves:
        out.write(b)
    return out.getvalue()


def _pytree_load(data):
    hlen = int.from_bytes(data[:8], "little")
    header = json.loads(data[8 : 8 + hlen].decode("utf-8"))
    offset = 8 + hlen
    leaves = []
    for size in header["sizes"]:
        leaves.append(_npy_load(data[offset : offset + size]))
        offset += size

    def decode(node):
        t = node["t"]
        if t == "d":
            return {k: decode(v) for k, v in node["v"].items()}
        if t == "l":
            return [decode(v) for v in node["v"]]
        if t == "t":
            return tuple(decode(v) for v in node["v"])
        if t == "a":
            return leaves[node["i"]]
        return node["v"]

    return decode(header["structure"])
