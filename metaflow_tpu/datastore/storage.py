"""Byte-level storage backends.

Reference behavior: metaflow/datastore/datastore_storage.py (DataStoreStorage
ABC: save_bytes:206 / load_bytes:243 / list_content / is_file) with local and
GCS implementations. GCS is the first-class cloud backend here (TPU-VMs live
in GCP); S3-style paths are not ported (SURVEY.md §7 stage 2).
"""

import os
import shutil
import sys
import time
from tempfile import NamedTemporaryFile

from .. import knobs


def _storage_retry(fn, what, policy=None, attempts=None):
    """Run an idempotent storage network op with bounded, jittered
    retries over TRANSIENT failures (gsop's GSTransientError — i.e. its
    own in-client retry budget already ran dry — plus raw connection
    resets/timeouts). One flaky 503 must not fail a whole task when the
    checkpoint it carries took an hour to compute.

    Backoff rides the shared elastic.policy.BackoffPolicy
    (TPUFLOW_RETRY_BACKOFF_*), so a seeded policy replays the exact
    retry timeline under test. TPUFLOW_STORAGE_RETRIES bounds the extra
    attempts (default 3); the final failure re-raises LOUDLY after a
    stderr warning — never swallowed. GSNotFound is semantics, not
    weather, and passes straight through."""
    from ..elastic.policy import BackoffPolicy
    from ..gsop import GSTransientError

    if attempts is None:
        attempts = knobs.get_int("TPUFLOW_STORAGE_RETRIES")
    attempts = max(0, int(attempts))
    if policy is None:
        policy = BackoffPolicy.from_env()
    # per-attempt wall-clock deadline (TPUFLOW_STORAGE_TIMEOUT_S): a
    # stalled-but-connected transfer becomes a TimeoutError that rides
    # this very retry budget instead of wedging the caller forever
    deadline_s = storage_timeout_s()
    for attempt in range(attempts + 1):
        try:
            return run_with_deadline(fn, what, deadline_s)
        except (GSTransientError, ConnectionError, TimeoutError) as ex:
            if attempt >= attempts:
                sys.stderr.write(
                    "storage: %s failed after %d retries: %s\n"
                    % (what, attempts, ex))
                sys.stderr.flush()
                raise
            delay = policy.delay(attempt, key=what)
            sys.stderr.write(
                "storage: transient failure in %s (%s); retry %d/%d "
                "in %.2fs\n" % (what, ex, attempt + 1, attempts, delay))
            sys.stderr.flush()
            time.sleep(delay)


def storage_timeout_s(env=None):
    """TPUFLOW_STORAGE_TIMEOUT_S: per-operation deadline for blocking
    GS gets/puts and shard fetches (0 / unset = no deadline, the
    historical behavior). A stalled-but-connected socket otherwise hangs
    the caller forever with a live heartbeat — exactly the wedge the
    gang watchdog has to escalate on; the deadline turns it into a
    TimeoutError that rides the normal _storage_retry budget instead."""
    return knobs.get_float("TPUFLOW_STORAGE_TIMEOUT_S", env=env)


def run_with_deadline(fn, what, timeout_s):
    """Run fn() with a wall-clock deadline; raise TimeoutError on expiry.

    The op runs on a daemon thread and is ABANDONED when the deadline
    fires — a client wedged in an uninterruptible read cannot be
    cancelled from Python, so the worker thread may stay blocked. That
    leak is the point: the caller gets its TimeoutError (and its retry)
    instead of inheriting the wedge. timeout_s <= 0 calls fn() inline."""
    if timeout_s <= 0:
        return fn()
    import threading

    result = []  # [("ok", value)] or [("err", exc)]

    def _run():
        try:
            result.append(("ok", fn()))
        except BaseException as ex:
            result.append(("err", ex))

    t = threading.Thread(target=_run, daemon=True,
                         name="storage-deadline")
    t.start()
    t.join(timeout_s)
    if not result:
        raise TimeoutError(
            "storage: %s exceeded the %.1fs deadline "
            "(TPUFLOW_STORAGE_TIMEOUT_S)" % (what, timeout_s))
    kind, value = result[0]
    if kind == "err":
        raise value
    return value


class CloseAfterUse(object):
    """Context manager tying the lifetime of fetched data to a `with` block."""

    def __init__(self, data, closer=None):
        self.data = data
        self._closer = closer

    def __enter__(self):
        return self.data

    def __exit__(self, *args):
        if self._closer:
            self._closer.close()


class DataStoreStorage(object):
    """ABC for byte storage: hierarchical keys relative to datastore_root."""

    TYPE = None

    def __init__(self, root=None):
        self.datastore_root = root

    @classmethod
    def get_datastore_root_from_config(cls, echo=None, create_on_absent=True):
        raise NotImplementedError

    def full_uri(self, path):
        return os.path.join(self.datastore_root, path)

    def path_join(self, *components):
        return os.path.join(*components)

    def path_split(self, path):
        return path.split("/")

    def basename(self, path):
        return os.path.basename(path)

    def dirname(self, path):
        return os.path.dirname(path)

    def is_file(self, paths):
        """Return list of bools: does each path exist as a file."""
        raise NotImplementedError

    def info_file(self, path):
        """Return (exists, metadata_dict)."""
        raise NotImplementedError

    def size_file(self, path):
        raise NotImplementedError

    def list_content(self, paths):
        """Yield (path, is_file) under each given prefix (one level)."""
        raise NotImplementedError

    def save_bytes(self, path_and_bytes_iter, overwrite=False, len_hint=0):
        """Save (path, (byteobj, metadata|None)) or (path, byteobj) pairs."""
        raise NotImplementedError

    def load_bytes(self, paths):
        """Return CloseAfterUse yielding (path, local_file_or_None, metadata)."""
        raise NotImplementedError

    def delete(self, paths):
        raise NotImplementedError


class LocalStorage(DataStoreStorage):
    TYPE = "local"

    @classmethod
    def get_datastore_root_from_config(cls, echo=None, create_on_absent=True):
        from ..util import get_tpuflow_root

        root = get_tpuflow_root()
        if create_on_absent:
            os.makedirs(root, exist_ok=True)
        return root

    def _abs(self, path):
        return os.path.join(self.datastore_root, path)

    def is_file(self, paths):
        return [os.path.isfile(self._abs(p)) for p in paths]

    def info_file(self, path):
        p = self._abs(path)
        if os.path.isfile(p):
            return True, {}
        return False, None

    def size_file(self, path):
        p = self._abs(path)
        try:
            return os.path.getsize(p)
        except OSError:
            return None

    def list_content(self, paths):
        results = []
        for path in paths:
            full = self._abs(path)
            if not os.path.isdir(full):
                continue
            for name in sorted(os.listdir(full)):
                child = os.path.join(path, name)
                results.append((child, os.path.isfile(self._abs(child))))
        return results

    def save_bytes(self, path_and_bytes_iter, overwrite=False, len_hint=0):
        for path, payload in path_and_bytes_iter:
            if isinstance(payload, tuple):
                byte_obj, _meta = payload
            else:
                byte_obj = payload
            full = self._abs(path)
            if os.path.exists(full) and not overwrite:
                if hasattr(byte_obj, "close"):
                    byte_obj.close()
                continue
            os.makedirs(os.path.dirname(full), exist_ok=True)
            # atomic write: temp file + rename, safe under concurrent tasks
            try:
                with NamedTemporaryFile(
                    dir=os.path.dirname(full), delete=False
                ) as tmp:
                    if hasattr(byte_obj, "read"):
                        shutil.copyfileobj(byte_obj, tmp, length=1 << 20)
                    else:
                        tmp.write(byte_obj)
                    tmpname = tmp.name
            finally:
                if hasattr(byte_obj, "close"):
                    byte_obj.close()
            os.replace(tmpname, full)

    def load_bytes(self, paths):
        def iterator():
            for path in paths:
                full = self._abs(path)
                if os.path.isfile(full):
                    yield path, full, None
                else:
                    yield path, None, None

        return CloseAfterUse(iterator())

    def delete(self, paths):
        for path in paths:
            try:
                os.unlink(self._abs(path))
            except OSError:
                pass


class GCSStorage(DataStoreStorage):
    """Google Cloud Storage backend (root = 'gs://bucket/prefix'), built on
    the gsop raw-HTTP engine (metaflow_tpu/gsop.py — the s3op equivalent:
    ranged parallel GET, compose-based parallel PUT, bounded retry).

    Parallelism model: unlike the reference's s3op worker *processes*
    (s3op.py:425), throughput here uses a thread pool — gsop's raw
    http.client path has no SDK CPU overhead, the GIL is released during
    socket I/O, and a TPU-VM NIC is saturated by ~32 streams. Point
    TPUFLOW_GS_ENDPOINT at a fake server (tests/fake_gcs.py) to run the
    whole backend without cloud access.
    """

    TYPE = "gs"

    def __init__(self, root=None):
        super().__init__(root)
        self._gsclient = None
        from urllib.parse import urlparse

        parsed = urlparse(root)
        self._bucket_name = parsed.netloc
        self._prefix = parsed.path.lstrip("/")

    @classmethod
    def get_datastore_root_from_config(cls, echo=None, create_on_absent=True):
        root = knobs.get_str(
            "TPUFLOW_DATASTORE_SYSROOT_GS",
            fallback=os.environ.get("METAFLOW_DATASTORE_SYSROOT_GS"),
        )
        if not root:
            from ..exception import TpuFlowException

            raise TpuFlowException(
                "GCS datastore root not configured: set "
                "TPUFLOW_DATASTORE_SYSROOT_GS=gs://bucket/prefix"
            )
        return root

    @property
    def client(self):
        if self._gsclient is None:
            from ..gsop import GSClient

            self._gsclient = GSClient()
        return self._gsclient

    def _key(self, path):
        return "/".join(x for x in (self._prefix, path) if x)

    def _unkey(self, name):
        return name[len(self._prefix):].lstrip("/") if self._prefix else name

    def is_file(self, paths):
        from concurrent.futures import ThreadPoolExecutor

        paths = list(paths)
        if not paths:
            return []
        with ThreadPoolExecutor(max_workers=min(32, len(paths))) as ex:
            return list(ex.map(
                lambda p: self.client.exists(self._bucket_name, self._key(p)),
                paths,
            ))

    def info_file(self, path):
        meta = self.client.stat(self._bucket_name, self._key(path))
        if meta is None:
            return False, None
        return True, dict(meta.get("metadata") or {})

    def size_file(self, path):
        return self.client.size(self._bucket_name, self._key(path))

    def list_content(self, paths):
        results = []
        for path in paths:
            prefix = self._key(path).rstrip("/") + "/"
            files, prefixes = self.client.list(
                self._bucket_name, prefix=prefix, delimiter="/"
            )
            for name, _size in files:
                results.append((self._unkey(name), True))
            for p in prefixes:
                results.append((self._unkey(p).rstrip("/"), False))
        return results

    # at this many objects in a batch (or an announced stream, via
    # len_hint), cross-object fan-out already saturates the NIC and
    # per-object compose parallelism only multiplies streams + pays the
    # compose/delete round-trips — same rule gsop.get_many applies on
    # the download side (large objects transfer one at a time there)
    COMPOSE_OFF_BATCH = 4
    # ...EXCEPT for objects this many times over the ranged threshold:
    # in a size-skewed batch (one multi-GB tensor among small metadata
    # blobs) the peers finish long before the big object, so it keeps
    # its part-compose fan-out regardless of batch size
    COMPOSE_BIG_MULT = 4

    def save_bytes(self, path_and_bytes_iter, overwrite=False, len_hint=0):
        from concurrent.futures import ThreadPoolExecutor

        items = list(path_and_bytes_iter)
        if not items:
            return
        # len_hint can announce a LARGER stream than this call carries
        # (the persist pipeline uploads one object per call from many
        # workers): honor whichever signal is bigger
        effective_batch = max(len(items), len_hint)
        allow_compose = effective_batch < self.COMPOSE_OFF_BATCH
        from ..elastic.policy import BackoffPolicy

        retry_policy = BackoffPolicy.from_env()

        def upload(item):
            path, payload = item
            if isinstance(payload, tuple):
                byte_obj, _ = payload
            else:
                byte_obj = payload
            key = self._key(path)
            if not overwrite and _storage_retry(
                    lambda: self.client.exists(self._bucket_name, key),
                    "exists(%s)" % path, policy=retry_policy):
                if hasattr(byte_obj, "close"):
                    byte_obj.close()
                return
            if hasattr(byte_obj, "read"):
                try:
                    # stream file-backed payloads through put_file
                    # (pread-based, constant memory) instead of
                    # materializing multi-GB blobs
                    name = getattr(byte_obj, "name", None)
                    if isinstance(name, str) and os.path.isfile(name):
                        # pread-based upload is idempotent: safe to
                        # retry the whole PUT on a transient failure
                        _storage_retry(
                            lambda: self.client.put_file(
                                self._bucket_name, key, name),
                            "put_file(%s)" % path, policy=retry_policy)
                        return
                    # unnamed reader (e.g. the CAS's tagged file stream):
                    # spool through a temp file at bounded memory, then
                    # the same pread-based upload. TPUFLOW_SCRATCH_DIR
                    # picks the spool location — the default /tmp is
                    # tmpfs on many hosts, where a multi-GB spool would
                    # eat RAM-backed storage
                    import tempfile

                    scratch = knobs.get_str("TPUFLOW_SCRATCH_DIR") or None
                    tmp = tempfile.NamedTemporaryFile(
                        delete=False, dir=scratch
                    )
                    try:  # one unlink guard over spool AND upload: a
                        # failed copy (scratch disk full) must not leak
                        # the spool file
                        with tmp:
                            shutil.copyfileobj(byte_obj, tmp,
                                               length=1 << 20)
                        # the spool is single-shot but the PUT from it
                        # is idempotent — retry only the network op
                        _storage_retry(
                            lambda: self.client.put_file(
                                self._bucket_name, key, tmp.name),
                            "put_file(%s)" % path, policy=retry_policy)
                    finally:
                        os.unlink(tmp.name)
                    return
                finally:
                    if hasattr(byte_obj, "close"):
                        byte_obj.close()
            compose_ok = allow_compose or (
                len(byte_obj)
                > self.client.ranged_threshold * self.COMPOSE_BIG_MULT
            )
            _storage_retry(
                lambda: self.client.put_bytes(self._bucket_name, key,
                                              byte_obj,
                                              allow_compose=compose_ok),
                "put_bytes(%s)" % path, policy=retry_policy)

        with ThreadPoolExecutor(max_workers=min(32, len(items))) as ex:
            list(ex.map(upload, items))

    def load_bytes(self, paths):
        import tempfile
        from concurrent.futures import ThreadPoolExecutor

        from ..elastic.policy import BackoffPolicy
        from ..gsop import GSNotFound

        tmpdir = tempfile.mkdtemp(prefix="tpuflow_gs_")
        retry_policy = BackoffPolicy.from_env()

        def download(idx_path):
            idx, path = idx_path
            # index-derived local name: distinct remote paths must never
            # collide in the shared tmpdir ('a/b_c' vs 'a_b/c')
            local = os.path.join(tmpdir, str(idx))
            try:
                # ranged parallel fetch kicks in automatically for big
                # blobs; GSNotFound passes through the transient-retry
                # wrapper untouched (absence is an answer, not a flake)
                _storage_retry(
                    lambda: self.client.get_file(
                        self._bucket_name, self._key(path), local),
                    "get_file(%s)" % path, policy=retry_policy)
                return path, local, None
            except GSNotFound:
                return path, None, None

        class _Closer(object):
            def close(self):
                shutil.rmtree(tmpdir, ignore_errors=True)

        paths = list(paths)
        if not paths:
            return CloseAfterUse(iter([]), closer=_Closer())
        try:
            with ThreadPoolExecutor(max_workers=min(32, len(paths))) as ex:
                results = list(ex.map(download, enumerate(paths)))
        except BaseException:
            # a failed batch never hands the tmpdir to CloseAfterUse —
            # remove it (with any partial downloads) before propagating
            shutil.rmtree(tmpdir, ignore_errors=True)
            raise
        return CloseAfterUse(iter(results), closer=_Closer())

    def delete(self, paths):
        for path in paths:
            try:
                self.client.delete(self._bucket_name, self._key(path))
            except Exception:
                pass


STORAGE_BACKENDS = {"local": LocalStorage, "gs": GCSStorage}
