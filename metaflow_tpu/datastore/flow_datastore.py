"""Per-flow datastore: creates/finds TaskDataStores + raw data (code pkgs).

Reference behavior: metaflow/datastore/flow_datastore.py (FlowDataStore:13,
get_task_datastores:79 latest-attempt resolution, save_data:348).
"""

import hashlib
import os

from .. import knobs
from .cas import ContentAddressedStore
from .task_datastore import TaskDataStore


class FlowDataStore(object):
    def __init__(self, flow_name, storage_impl, ds_root=None,
                 blob_cache=None):
        """storage_impl: a DataStoreStorage subclass; ds_root overrides its
        configured root.

        blob_cache: None (default) auto-attaches the shared on-disk
        FileCache for REMOTE storage — tasks then write artifacts through
        the cache on persist and resumed/forked tasks + load_artifacts
        read locally-present keys from disk instead of GCS, with
        in-flight dedup across gang workers on one host. Pass False to
        disable, or any BlobCache-shaped object to override.
        TPUFLOW_BLOB_CACHE=0 disables the auto-attach globally (local
        storage never attaches one: the datastore already IS local disk).
        """
        root = ds_root or storage_impl.get_datastore_root_from_config()
        self.flow_name = flow_name
        self.storage = storage_impl(root)
        self.ca_store = ContentAddressedStore(
            self.storage.path_join(flow_name, "data"), self.storage
        )
        if blob_cache is None:
            if (self.storage.TYPE != "local"
                    and knobs.get_bool("TPUFLOW_BLOB_CACHE")):
                from ..client.filecache import FileCache

                self.ca_store.set_blob_cache(FileCache())
        elif blob_cache is not False:
            self.ca_store.set_blob_cache(blob_cache)

    @property
    def ds_type(self):
        return self.storage.TYPE

    @property
    def ds_root(self):
        return self.storage.datastore_root

    def get_task_datastore(
        self,
        run_id,
        step_name,
        task_id,
        attempt=None,
        mode="r",
        allow_not_done=False,
    ):
        return TaskDataStore(
            self,
            run_id,
            step_name,
            task_id,
            attempt=attempt,
            mode=mode,
            allow_not_done=allow_not_done,
        )

    RUNSTATE_FILE = "_runstate.json"

    def save_runstate(self, run_id, snapshot):
        """Persist the scheduler's live-state snapshot for a run (the
        counterpart reader is load_runstate; see runtime._persist_runstate
        for the shape)."""
        import json

        path = self.storage.path_join(
            self.flow_name, str(run_id), self.RUNSTATE_FILE
        )
        self.storage.save_bytes(
            [(path, json.dumps(snapshot).encode("utf-8"))], overwrite=True
        )

    def load_runstate(self, run_id):
        """The latest scheduler snapshot for a run, or None."""
        import json

        path = self.storage.path_join(
            self.flow_name, str(run_id), self.RUNSTATE_FILE
        )
        with self.storage.load_bytes([path]) as loaded:
            for _key, local, _meta in loaded:
                if local:
                    with open(local) as f:
                        return json.load(f)
        return None

    def prefetch_task_artifacts(self, datastores, names=None,
                                max_bytes=256 << 20):
        """Warm the blob cache with the (requested) artifacts of many task
        datastores in ONE batched storage fetch.

        Reference behavior: metaflow/datastore/datastore_set.py — a join
        over N inputs otherwise issues N x M sequential blob gets; batching
        lets the storage backend parallelize, and the shared blob cache
        makes the per-name loads that follow pure disk hits.

        Opportunistic by design: blobs over the max_bytes budget (largest
        first) and missing blobs are skipped — a fat carried-forward
        artifact the join never reads must not be downloaded up front, and
        a genuinely missing one should fail (or not) at its actual read.
        No-op without a blob cache (local storage needs no prefetch).
        """
        if self.ca_store._blob_cache is None:
            return 0
        sizes = {}
        for ds in datastores:
            for name, key in ds.items():
                if names is None or name in names:
                    info = ds.artifact_info(name) or {}
                    sizes[key] = info.get("size", 0)
        budget = max_bytes
        keys = []
        for key, size in sorted(sizes.items(), key=lambda kv: kv[1]):
            if size > budget:
                break  # sorted ascending: everything after is bigger
            budget -= size
            keys.append(key)
        fetched = 0
        for _key, _blob in self.ca_store.load_blobs(keys, missing_ok=True):
            fetched += 1  # side effect: blob cache now holds the key
        return fetched

    def get_task_datastores(
        self, run_id=None, steps=None, pathspecs=None, allow_not_done=False
    ):
        """Return read-mode TaskDataStores for many tasks at once.

        Either (run_id, steps) — all tasks of those steps — or explicit
        pathspecs 'run/step/task'.
        """
        task_specs = []
        if pathspecs is not None:
            for ps in pathspecs:
                parts = ps.split("/")
                if len(parts) == 4:  # flow/run/step/task
                    parts = parts[1:]
                run, step, task = parts
                task_specs.append((run, step, task))
        else:
            steps = steps or self.list_steps(run_id)
            for step in steps:
                for task in self.list_tasks(run_id, step):
                    task_specs.append((run_id, step, task))
        out = []
        for run, step, task in task_specs:
            ds = self.get_task_datastore(
                run, step, task, mode="r", allow_not_done=allow_not_done
            )
            if ds.has_attempt():
                out.append(ds)
        return out

    # ---------- run/step/task listing (powers the local client) ----------

    def list_runs(self):
        out = []
        for path, is_file in self.storage.list_content([self.flow_name]):
            name = self.storage.basename(path)
            # 'data' is the CAS; 'checkpoints' is the @checkpoint
            # decorator's tree; '_'-prefixed dirs are flow-level state —
            # none is a run (gc would otherwise age them out as phantom
            # runs, and run listings would surface them)
            if (not is_file and name not in ("data", "checkpoints")
                    and not name.startswith("_")):
                out.append(name)
        return out

    def list_steps(self, run_id):
        prefix = self.storage.path_join(self.flow_name, str(run_id))
        return [
            self.storage.basename(p)
            for p, is_file in self.storage.list_content([prefix])
            if not is_file and not self.storage.basename(p).startswith("_")
        ]

    def list_tasks(self, run_id, step_name):
        prefix = self.storage.path_join(self.flow_name, str(run_id), step_name)
        return [
            self.storage.basename(p)
            for p, is_file in self.storage.list_content([prefix])
            if not is_file
        ]

    # ---------- raw data (code packages, include files) ----------

    def save_data(self, data_iter):
        """Save raw byte blobs (code packages, include files); returns
        [(uri, key)] in order. Keys are recorded in the flow's package
        registry so gc's mark phase keeps them live."""
        results = self.ca_store.save_blobs(data_iter, raw=True)
        self._register_data_keys([key for _uri, key in results])
        return results

    def _registry_path(self):
        return self.storage.path_join(self.flow_name, "_packages.json")

    def _registry_lock(self):
        """Exclusive lock for registry read-modify-write (local storage);
        remote stores get best-effort last-writer-wins."""
        import contextlib

        if self.ds_type != "local":
            return contextlib.nullcontext()

        import fcntl

        path = self.storage.full_uri(self._registry_path()) + ".lock"
        os.makedirs(os.path.dirname(path), exist_ok=True)

        @contextlib.contextmanager
        def locked():
            with open(path, "a+") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                yield

        return locked()

    def _read_registry(self):
        import json

        with self.storage.load_bytes([self._registry_path()]) as loaded:
            for _p, local, _m in loaded:
                if local is None:
                    return {}
                with open(local) as f:
                    data = json.load(f)
                    if isinstance(data, list):  # pre-timestamp format
                        return {k: 0 for k in data}
                    return data
        return {}

    def _write_registry(self, registry):
        import json

        self.storage.save_bytes(
            [(self._registry_path(),
              json.dumps(registry, sort_keys=True).encode("utf-8"))],
            overwrite=True,
        )

    def _register_data_keys(self, keys):
        import time

        with self._registry_lock():
            registry = self._read_registry()
            now = time.time()
            # refresh the timestamp on EVERY registration, including
            # dedup hits: gc's mark phase keeps keys newer than the
            # oldest kept run, so a payload re-included by a recent run
            # must carry that run's timestamp, not its first upload's.
            # Every call therefore rewrites the registry JSON — it is
            # small (one entry per code package) and registration is
            # once per run, not per artifact. max(): a clock-skewed
            # writer must never move a stamp backwards (the lock is
            # best-effort on remote stores) — that could expose a live
            # package to gc pruning.
            for key in keys:
                registry[key] = max(now, registry.get(key, 0))
            if keys:
                self._write_registry(registry)

    def registered_data_keys(self, newer_than=None):
        registry = self._read_registry()
        if newer_than is None:
            return sorted(registry)
        return sorted(k for k, ts in registry.items() if ts >= newer_than)

    def prune_registered_data_keys(self, older_than):
        """Drop registry entries older than the cutoff (gc of packages that
        belonged to deleted runs). Returns the dropped keys."""
        with self._registry_lock():
            registry = self._read_registry()
            dropped = [k for k, ts in registry.items() if ts < older_than]
            if dropped:
                self._write_registry(
                    {k: ts for k, ts in registry.items() if ts >= older_than}
                )
            return dropped

    def save_file(self, path):
        """Stream a file into the CAS at bounded RSS (IncludeFile upload
        path); registers the key for gc. Returns (uri, key)."""
        uri, key = self.ca_store.save_file(path)
        self._register_data_keys([key])
        return uri, key

    def open_data_stream(self, key):
        """Context manager yielding a readable binary stream over a raw
        data blob (IncludeFile download path, bounded RSS)."""
        return self.ca_store.open_blob_stream(key)

    def load_data(self, keys):
        return {k: blob for k, blob in self.ca_store.load_blobs(keys, force_raw=True)}
