"""Pipelined artifact persist: overlap serialization with upload.

The serial persist path (TaskDataStore.save_artifacts → serializers →
ContentAddressedStore.save_blobs) serializes every artifact to bytes one
at a time — each a blocking device→host transfer plus a sha256/gzip pass
— and only then starts uploading, so the device sits idle for the whole
serialize+upload wall-clock. This module is the overlapped version, the
"concurrency limits" lesson from arxiv 2011.03641 / Podracer (2104.06272)
applied to the L1 datastore:

  stage 0 (caller thread)   eager D2H prefetch: copy_to_host_async is
                            issued for EVERY device array up front, so
                            transfers queue back-to-back on the device's
                            transfer stream while the host does other work
  stage 1 (worker pool)     serialize + hash + pack per artifact; sha256
                            and gzip release the GIL, so threads scale
  stage 2 (upload pool)     completed packed payloads stream into storage
                            in ready order over a persistent transfer
                            pool (per-thread gsop connections) — upload
                            of artifact k overlaps serialization of
                            artifact k+1, and cross-object concurrency
                            replaces per-object compose fan-out

Memory is bounded: packed payloads waiting for upload count against an
in-flight byte budget (TPUFLOW_PERSIST_INFLIGHT_MB, default 512), so a
task with 100 GB of artifacts never materializes the full set in RAM —
producers stall until the uploader drains. An oversized single artifact
(bigger than the whole budget) is admitted alone rather than deadlocking.

Equivalence guarantee: keys and packed bytes come from the SAME
ContentAddressedStore.pack_blob the serial path uses, and manifests are
assembled from the same (name → key/type_tag/size) tuples — the pipelined
and serial paths are byte-identical on storage (tests/test_persist_pipeline
verifies this). Any worker or upload failure propagates to the caller;
nothing is swallowed.
"""

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor, as_completed

from .. import tracing
from .. import knobs
from . import serializers

DEFAULT_INFLIGHT_BYTES = 512 << 20
# serialize workers: hash/compress are the CPU cost and release the GIL;
# beyond ~8 threads the memory bus, not the GIL, is the limit
DEFAULT_WORKERS = min(8, max(2, os.cpu_count() or 2))
# upload workers: the persistent transfer pool — each thread keeps its
# gsop connection alive across objects, and cross-object concurrency
# (not per-object compose fan-out) is what saturates the NIC
DEFAULT_UPLOADS = min(8, max(2, os.cpu_count() or 2))


class PipelineCancelled(Exception):
    """Raised inside stalled producers when the pipeline aborts."""


class _ByteBudget(object):
    """Counting semaphore in bytes with cancellation.

    acquire() admits when the budget has room — or unconditionally when
    nothing is in flight, so one oversized payload passes alone instead
    of deadlocking.
    """

    def __init__(self, cap):
        self._cap = cap
        self._used = 0
        self._cancelled = False
        self._cv = threading.Condition()

    def acquire(self, n):
        with self._cv:
            while (not self._cancelled and self._used
                   and self._used + n > self._cap):
                self._cv.wait()
            if self._cancelled:
                raise PipelineCancelled()
            self._used += n

    def release(self, n):
        with self._cv:
            self._used -= n
            self._cv.notify_all()

    def cancel(self):
        with self._cv:
            self._cancelled = True
            self._cv.notify_all()


_DONE = object()


def persist_pipeline(artifacts, ca_store, raw=False, workers=None,
                     upload_workers=None, max_inflight_bytes=None):
    """Persist [(name, obj)] pairs through `ca_store` with serialization
    overlapped against upload. Returns [(name, key, type_tag, size)] in
    input order — the tuples TaskDataStore records in its manifest.

    Raises the first error from any stage; on error the remaining work is
    cancelled (artifacts already uploaded stay in the CAS — harmless:
    content-addressed objects without a manifest reference are inert).
    """
    items = list(artifacts)
    if not items:
        return []
    workers = workers or knobs.get_int(
        "TPUFLOW_PERSIST_WORKERS", fallback=DEFAULT_WORKERS)
    upload_workers = upload_workers or knobs.get_int(
        "TPUFLOW_PERSIST_UPLOADS", fallback=DEFAULT_UPLOADS)
    cap = max_inflight_bytes or (
        knobs.get_int("TPUFLOW_PERSIST_INFLIGHT_MB") << 20
        or DEFAULT_INFLIGHT_BYTES)

    # stage 0: every device array starts its D2H copy NOW — by the time a
    # worker thread reaches artifact k, its transfer is done or in flight
    for _name, obj in items:
        serializers.prefetch_to_host(obj)

    budget = _ByteBudget(cap)
    upload_q = queue.Queue()
    results = [None] * len(items)
    blob_cache = ca_store.blob_cache
    errors = []
    errors_lock = threading.Lock()

    def fail(ex):
        with errors_lock:
            errors.append(ex)
        budget.cancel()

    def serialize_one(idx):
        name, obj = items[idx]
        payload, tag = serializers.serialize(obj)
        size = len(payload)
        key, packed = ca_store.pack_blob(payload, raw=raw)
        if blob_cache is not None:
            # write-through before upload: a local reader that races the
            # upload hits disk; the sha-verified cache cannot serve torn
            # bytes
            blob_cache.store_key(key, payload)
        del payload
        budget.acquire(len(packed))
        return idx, name, key, packed, tag, size

    def uploader():
        # the persistent transfer pool: each worker thread holds its own
        # storage connection across objects; len_hint announces the FULL
        # stream so the backend tunes for cross-object concurrency (e.g.
        # GCSStorage turns per-object compose off) even though each call
        # carries one object
        storage = ca_store.storage
        while True:
            entry = upload_q.get()
            if entry is _DONE:
                return
            idx, name, key, packed, tag, size = entry
            try:
                # overwrite=False: content-addressed ⇒ same key, same bytes
                storage.save_bytes(
                    iter([(ca_store.blob_path(key), packed)]),
                    overwrite=False, len_hint=len(items),
                )
                results[idx] = (name, key, tag, size)
            except BaseException as ex:
                fail(ex)
            finally:
                budget.release(len(packed))

    n_uploads = min(upload_workers, len(items))
    up_threads = [
        threading.Thread(target=uploader, name="persist-upload-%d" % i,
                         daemon=True)
        for i in range(n_uploads)
    ]
    for t in up_threads:
        t.start()
    with tracing.span("persist.pipeline",
                      {"artifacts": len(items), "workers": workers,
                       "upload_workers": n_uploads}):
        try:
            with ThreadPoolExecutor(
                max_workers=min(workers, len(items)),
                thread_name_prefix="persist-serialize",
            ) as pool:
                futs = [pool.submit(serialize_one, i)
                        for i in range(len(items))]
                for fut in as_completed(futs):
                    try:
                        entry = fut.result()
                    except PipelineCancelled:
                        continue  # secondary casualty of the real error
                    except BaseException as ex:
                        fail(ex)
                        for f in futs:
                            f.cancel()
                        continue
                    upload_q.put(entry)
        finally:
            for _ in up_threads:
                upload_q.put(_DONE)
            for t in up_threads:
                t.join()
    if errors:
        raise errors[0]
    return results
