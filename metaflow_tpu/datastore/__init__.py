from .storage import DataStoreStorage, LocalStorage, GCSStorage, STORAGE_BACKENDS
from .cas import ContentAddressedStore
from .task_datastore import TaskDataStore, MAX_ATTEMPTS
from .flow_datastore import FlowDataStore

__all__ = [
    "DataStoreStorage",
    "LocalStorage",
    "GCSStorage",
    "STORAGE_BACKENDS",
    "ContentAddressedStore",
    "TaskDataStore",
    "FlowDataStore",
    "MAX_ATTEMPTS",
]
