"""Per-task artifact dictionary backed by the content-addressed store.

Reference behavior: metaflow/datastore/task_datastore.py (TaskDataStore:93,
save_artifacts:379, load_artifacts:499, persist:880, done():796, clone:850).
Artifacts are serialized via the registry in serializers.py (JAX arrays take
the npy fast path) and stored deduplicated in the flow's CAS; per-task state
is a small JSON manifest mapping name → (content key, type tag).
"""

import json
import os
import time
from functools import wraps

from .. import knobs, tracing
from ..exception import TpuFlowDataMissing, MetaflowInternalError
from . import serializers

MAX_ATTEMPTS = 6


def only_if_not_done(f):
    @wraps(f)
    def method(self, *args, **kwargs):
        if self._is_done_set:
            raise MetaflowInternalError(
                "Tried to write to datastore of %s after it was marked done"
                % self._path
            )
        return f(self, *args, **kwargs)

    return method


def require_mode(mode):
    def wrapper(f):
        @wraps(f)
        def method(self, *args, **kwargs):
            if mode is not None and self._mode != mode:
                raise MetaflowInternalError(
                    "%s requires mode %r (datastore is %r)"
                    % (f.__name__, mode, self._mode)
                )
            return f(self, *args, **kwargs)

        return method

    return wrapper


class TaskDataStore(object):
    METADATA_ATTEMPT_SUFFIX = "attempt.json"
    METADATA_DONE_SUFFIX = "DONE.lock"
    METADATA_DATA_SUFFIX = "artifacts.json"
    METADATA_USER_SUFFIX = "metadata.json"

    def __init__(
        self,
        flow_datastore,
        run_id,
        step_name,
        task_id,
        attempt=None,
        mode="r",
        allow_not_done=False,
    ):
        self._flow_datastore = flow_datastore
        self._ca_store = flow_datastore.ca_store
        self._storage = flow_datastore.storage
        self.run_id = str(run_id)
        self.step_name = step_name
        self.task_id = str(task_id)
        self._mode = mode
        self._attempt = attempt
        self._is_done_set = False
        self._objects = {}   # name -> content key
        self._info = {}      # name -> {"type_tag":..., "size":...}

        self._path = self._storage.path_join(
            flow_datastore.flow_name, self.run_id, step_name, self.task_id
        )

        if mode == "w":
            if attempt is None:
                raise MetaflowInternalError(
                    "'w' mode TaskDataStore requires an explicit attempt"
                )
        elif mode == "r":
            if attempt is None:
                # resolve the latest attempt (prefer DONE ones)
                self._attempt = self._latest_attempt(require_done=not allow_not_done)
            if self._attempt is not None:
                self._load_manifest()
        elif mode == "d":
            # data-check mode: look only at manifests
            if attempt is None:
                self._attempt = self._latest_attempt(require_done=not allow_not_done)
            if self._attempt is not None:
                self._load_manifest()
        else:
            raise MetaflowInternalError("Unknown datastore mode %r" % mode)

    # ---------- path & manifest helpers ----------

    @property
    def pathspec(self):
        return "/".join(
            (self._flow_datastore.flow_name, self.run_id, self.step_name, self.task_id)
        )

    @property
    def attempt(self):
        return self._attempt

    def _fname(self, suffix, attempt=None):
        a = self._attempt if attempt is None else attempt
        return self._storage.path_join(self._path, "%d.%s" % (a, suffix))

    def _latest_attempt(self, require_done=True):
        files = dict(self._storage.list_content([self._path]))
        attempts = []
        for path in files:
            base = self._storage.basename(path)
            parts = base.split(".", 1)
            if len(parts) != 2 or not parts[0].isdigit():
                continue
            attempt, suffix = int(parts[0]), parts[1]
            if suffix == self.METADATA_DONE_SUFFIX:
                attempts.append((attempt, True))
            elif suffix == self.METADATA_ATTEMPT_SUFFIX:
                attempts.append((attempt, False))
        done_attempts = [a for a, done in attempts if done]
        if done_attempts:
            return max(done_attempts)
        if not require_done and attempts:
            return max(a for a, _ in attempts)
        return None

    def _load_manifest(self):
        data = self._load_json(self._fname(self.METADATA_DATA_SUFFIX))
        if data:
            self._objects = data.get("objects", {})
            self._info = data.get("info", {})

    def _load_json(self, path):
        with self._storage.load_bytes([path]) as loaded:
            for _path, local, _meta in loaded:
                if local is None:
                    return None
                with open(local, "rb") as f:
                    return json.loads(f.read().decode("utf-8"))
        return None

    def _save_json(self, path, obj):
        blob = json.dumps(obj).encode("utf-8")
        self._storage.save_bytes([(path, blob)], overwrite=True)

    # ---------- write path ----------

    @only_if_not_done
    @require_mode("w")
    def init_task(self):
        """Mark this attempt as started."""
        self._save_json(
            self._fname(self.METADATA_ATTEMPT_SUFFIX),
            {"time": time.time(), "attempt": self._attempt},
        )

    @only_if_not_done
    @require_mode("w")
    def save_artifacts(self, artifacts_iter, pipelined=None):
        """Save {name: obj} pairs; dedup via CAS.

        pipelined=None (default) picks the overlapped persist pipeline
        (datastore/pipeline.py) for multi-artifact saves unless
        TPUFLOW_PERSIST_PIPELINE=0; both paths produce byte-identical
        CAS objects and manifests — the pipelined one overlaps
        device→host transfer + serialization with upload."""
        items = list(artifacts_iter)
        if pipelined is None:
            pipelined = (
                len(items) > 1
                and knobs.get_bool("TPUFLOW_PERSIST_PIPELINE")
            )
        with tracing.span(
            "persist.save_artifacts",
            {"task": self.pathspec, "artifacts": len(items),
             "pipelined": bool(pipelined)},
        ):
            if pipelined:
                from .pipeline import persist_pipeline

                for name, key, tag, size in persist_pipeline(
                    items, self._ca_store
                ):
                    self._objects[name] = key
                    self._info[name] = {"type_tag": tag, "size": size}
                return
            names, blobs, tags = [], [], []
            for name, obj in items:
                payload, tag = serializers.serialize(obj)
                names.append(name)
                blobs.append(payload)
                tags.append(tag)
            results = self._ca_store.save_blobs(blobs)
            for name, (uri, key), tag, blob in zip(
                names, results, tags, blobs
            ):
                self._objects[name] = key
                self._info[name] = {"type_tag": tag, "size": len(blob)}

    @only_if_not_done
    @require_mode("w")
    def persist(self, flow):
        """Persist all non-ephemeral attributes of a flow instance."""
        if flow._datastore:
            # carry forward upstream artifacts not redefined by this task
            self._objects.update(flow._datastore._objects)
            self._info.update(flow._datastore._info)
        to_save = []
        for name, value in flow.__dict__.items():
            if name in flow._EPHEMERAL:
                continue
            if name in ("_graph_info",):
                continue
            to_save.append((name, value))
        self.save_artifacts(to_save)

    @only_if_not_done
    @require_mode("w")
    def done(self):
        """Write the manifest and the DONE marker; freeze the datastore."""
        self._save_json(
            self._fname(self.METADATA_DATA_SUFFIX),
            {"objects": self._objects, "info": self._info},
        )
        self._save_json(
            self._fname(self.METADATA_DONE_SUFFIX), {"time": time.time()}
        )
        self._is_done_set = True

    @only_if_not_done
    @require_mode("w")
    def clone(self, origin):
        """Clone artifacts from another task datastore (resume fast path:
        only manifests are copied — CAS blobs are shared, zero data motion)."""
        self._objects = dict(origin._objects)
        self._info = dict(origin._info)

    @only_if_not_done
    @require_mode("w")
    def passdown_partial(self, origin, vars):
        for var in vars:
            if var in origin._objects:
                self._objects[var] = origin._objects[var]
                self._info[var] = origin._info[var]

    @only_if_not_done
    @require_mode("w")
    def save_metadata(self, contents):
        """Save {name: json-able} auxiliary metadata files for this attempt."""
        for name, obj in contents.items():
            self._save_json(self._fname(name + ".json"), obj)

    # ---------- read path ----------

    def is_done(self):
        if self._attempt is None:
            return False
        return self._storage.is_file(
            [self._fname(self.METADATA_DONE_SUFFIX)]
        )[0]

    def has_attempt(self):
        return self._attempt is not None

    def load_metadata(self, names):
        out = {}
        for name in names:
            out[name] = self._load_json(self._fname(name + ".json"))
        return out

    def load_artifacts(self, names):
        """Yield (name, obj) for requested artifact names."""
        names = list(names)  # callers may pass a generator; len() below
        keys = {}
        for name in names:
            if name not in self._objects:
                raise TpuFlowDataMissing(
                    "Artifact *%s* not found in task %s" % (name, self.pathspec)
                )
            keys.setdefault(self._objects[name], []).append(name)
        with tracing.span(
            "persist.load_artifacts",
            {"task": self.pathspec, "artifacts": len(names)},
        ):
            for key, blob in self._ca_store.load_blobs(list(keys)):
                for name in keys[key]:
                    yield name, serializers.deserialize(
                        blob, self._info[name]["type_tag"]
                    )

    def __contains__(self, name):
        return name in self._objects

    def __getitem__(self, name):
        _, obj = next(self.load_artifacts([name]))
        return obj

    def get(self, name, default=None):
        try:
            return self[name]
        except (TpuFlowDataMissing, KeyError):
            return default

    def keys(self):
        return self._objects.keys()

    def items(self):
        """Yield (name, content_key): identity comparison without loading."""
        return self._objects.items()

    def artifact_info(self, name):
        return self._info.get(name)

    @require_mode(None)
    def to_dict(self, show_private=False):
        names = [
            n for n in self._objects if show_private or not n.startswith("_")
        ]
        return dict(self.load_artifacts(names))

    # ---------- logs ----------

    def save_logs(self, logsource, contents):
        """contents: {logname ('stdout'/'stderr'): bytes}"""
        to_save = []
        for logname, data in contents.items():
            path = self._fname("%s_%s.log" % (logsource, logname))
            to_save.append((path, data))
        self._storage.save_bytes(iter(to_save), overwrite=True)

    def load_log_legacy(self, logsource, logname, attempt=None):
        path = self._fname("%s_%s.log" % (logsource, logname), attempt=attempt)
        with self._storage.load_bytes([path]) as loaded:
            for _p, local, _m in loaded:
                if local is None:
                    return b""
                with open(local, "rb") as f:
                    return f.read()
        return b""

    def __repr__(self):
        return "TaskDataStore(%s attempt=%s mode=%s)" % (
            self.pathspec,
            self._attempt,
            self._mode,
        )
