"""Content-addressed blob store: hash-keyed, de-duplicated, per-flow.

Reference behavior: metaflow/datastore/content_addressed_store.py
(ContentAddressedStore:11, _pack_v1:211/_unpack_v1:218). Differences chosen
for TPU-first operation:
  - SHA-256 instead of SHA-1 (hardware-accelerated, no collision caveats)
  - per-blob compression is a *format tag*, so large tensor blobs can skip
    gzip (HBM→host→GCS path stays memory-bandwidth bound, not CPU bound)
"""

import gzip
import hashlib
import io
import os


class BlobCache(object):
    def load_key(self, key):
        return None

    def store_key(self, key, blob):
        pass

    def key_lock(self, key):
        """Context manager serializing fetches of one key across readers
        (in-flight dedup). The base cache does not dedup: concurrent
        fetchers proceed independently."""
        import contextlib

        return contextlib.nullcontext()


class _TaggedFileReader(object):
    """File-like that serves a pack-format tag byte, then the file —
    lets save_bytes stream a tagged blob without materializing it."""

    def __init__(self, path, tag):
        self._path = path
        self._tag = tag
        self._file = None

    def read(self, n=-1):
        if n == 0:
            return b""
        if self._file is None:
            self._file = open(self._path, "rb")
            if n is None or n < 0:
                return self._tag + self._file.read()
            return self._tag + self._file.read(max(0, n - len(self._tag)))
        return self._file.read(n)

    def close(self):
        if self._file is not None:
            self._file.close()


class ContentAddressedStore(object):
    # pack formats: first byte of the stored object selects the decoder
    FMT_RAW = b"0"      # raw bytes
    FMT_GZIP = b"1"     # gzip-compressed

    # blobs larger than this skip gzip (tensor data is incompressible and
    # gzip becomes the bottleneck at HBM-scale artifact sizes)
    COMPRESS_MAX = 8 * 1024 * 1024

    def __init__(self, prefix, storage):
        self._prefix = prefix
        self._storage = storage
        self._blob_cache = None

    def set_blob_cache(self, blob_cache):
        self._blob_cache = blob_cache

    @property
    def blob_cache(self):
        return self._blob_cache

    @property
    def storage(self):
        return self._storage

    def _path(self, key):
        return self._storage.path_join(self._prefix, key[:2], key)

    def blob_path(self, key):
        """Storage path of a content key (the persist pipeline streams
        packed blobs straight to storage under these paths)."""
        return self._path(key)

    # once a persist has streamed this much hash+gzip work, the REMAINING
    # blobs are fanned over forked workers (multicore.parallel_map —
    # reference behavior: metaflow/multicore_utils.py on the persist
    # path). The prefix stays streaming so small persists never buffer
    # and big ones only materialize the parallel tail.
    PARALLEL_PACK_MIN_BYTES = 8 << 20
    PARALLEL_PACK_MIN_BLOBS = 4
    PARALLEL_PACK_WORKERS = None  # None = multicore's cpu-count default

    def pack_blob(self, blob, raw=False):
        """(sha256 hex key, packed bytes) for one blob — the SINGLE pack
        implementation, shared by the serial save path and the pipelined
        one so both produce byte-identical objects and keys."""
        sha = hashlib.sha256(blob).hexdigest()
        if raw or len(blob) > self.COMPRESS_MAX:
            packed = self.FMT_RAW + blob
        else:
            # mtime=0: gzip otherwise stamps wall-clock into the header,
            # making packed bytes non-reproducible — a CAS object's bytes
            # must be a pure function of its payload
            packed = self.FMT_GZIP + gzip.compress(blob, compresslevel=3,
                                                   mtime=0)
        return sha, packed

    # internal alias kept for the forked parallel_map closure below
    _pack_blob = pack_blob

    def save_blobs(self, blob_iter, raw=False, len_hint=0, cacheable=True):
        """Save blobs; returns list of (uri, key) in input order.

        cacheable=False skips the blob-cache write-through (checkpoint
        snapshots use it: a superseded multi-GB checkpoint payload in
        the LRU cache would only evict the artifact blobs the cache
        exists for)."""
        # write-through happens INLINE at pack time: a resumed/forked
        # task on this host reads the artifact back from disk instead of
        # re-downloading, and no raw payload is pinned past its pack (the
        # streaming prefix keeps its one-blob-at-a-time memory profile)
        keep = cacheable and self._blob_cache is not None
        packed_all = []
        it = iter(blob_iter)
        count = 0
        total = 0
        tail = None
        for blob in it:
            count += 1
            total += len(blob)
            sha, packed = self._pack_blob(blob, raw)
            packed_all.append((sha, packed))
            if keep:
                self._blob_cache.store_key(sha, blob)
            if (count >= self.PARALLEL_PACK_MIN_BLOBS
                    and total >= self.PARALLEL_PACK_MIN_BYTES):
                tail = list(it)
                break
        if tail:
            from ..multicore import parallel_map

            packed_tail = parallel_map(
                lambda b: self._pack_blob(b, raw), tail,
                max_parallel=self.PARALLEL_PACK_WORKERS, min_chunk=2,
            )
            if keep:
                # tail blobs are already materialized (tail list) — this
                # adds no pinning beyond the pre-existing parallel_map
                for (sha, _packed), blob in zip(packed_tail, tail):
                    self._blob_cache.store_key(sha, blob)
            packed_all.extend(packed_tail)
        results = []
        to_save = []
        for sha, packed in packed_all:
            path = self._path(sha)
            results.append((self._storage.full_uri(path), sha))
            to_save.append((path, packed))
        # overwrite=False: content-addressed ⇒ existing key has same bytes
        self._storage.save_bytes(iter(to_save), overwrite=False,
                                 len_hint=len(to_save))
        return results

    CHUNK = 1 << 20

    def save_file(self, path):
        """Stream one FILE into the store at bounded RSS: chunked SHA-256,
        then a tag-prefixed reader handed to the storage backend (local
        storage copies it file-to-file; GCS spools through a temp file
        into the pread-based put_file path). Stored FMT_RAW — include
        payloads are arbitrary user data, often incompressible, and raw
        keeps the download side streamable too. Returns (uri, key)."""
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(self.CHUNK), b""):
                h.update(chunk)
        sha = h.hexdigest()
        storage_path = self._path(sha)
        self._storage.save_bytes(
            iter([(storage_path, _TaggedFileReader(path, self.FMT_RAW))]),
            overwrite=False, len_hint=1,
        )
        return self._storage.full_uri(storage_path), sha

    def open_blob_stream(self, key):
        """Context manager yielding a binary file object positioned at the
        blob's payload (pack tag consumed, gzip transparently wrapped) —
        the bounded-RSS read path for large raw blobs."""
        import contextlib

        @contextlib.contextmanager
        def opened():
            with self._storage.load_bytes([self._path(key)]) as loaded:
                for _path, local, _meta in loaded:
                    if local is None:
                        raise KeyError(
                            "Content-addressed blob %s not found in "
                            "datastore" % key
                        )
                    with open(local, "rb") as f:
                        fmt = f.read(1)
                        if fmt == self.FMT_RAW:
                            yield f
                        elif fmt == self.FMT_GZIP:
                            yield gzip.GzipFile(fileobj=f, mode="rb")
                        else:
                            # no tag byte: MIRROR _unpack's fallback —
                            # pre-tag-era blobs are whole-object gzip;
                            # only yield raw when it isn't gzip at all
                            f.seek(0)
                            gz = gzip.GzipFile(fileobj=f, mode="rb")
                            try:
                                gz.peek(1)
                                yield gz
                            except OSError:
                                f.seek(0)
                                yield f
                    return

        return opened()

    def load_blobs(self, keys, force_raw=False, missing_ok=False,
                   cacheable=True):
        """Yield (key, bytes) for each key (order not guaranteed).

        missing_ok=True skips absent keys instead of raising — for
        opportunistic prefetch, where a missing blob should surface (or
        not) at the actual read.

        cacheable=False reads THROUGH the cache (hits still served) but
        never stores into it — for one-shot multi-GB payloads (checkpoint
        restore) that would only evict the artifact blobs the LRU cache
        exists for. Also skips the key locks: without a store there is
        nothing for a deduped second reader to pick up."""
        remaining = []
        for key in keys:
            if self._blob_cache is not None:
                cached = self._blob_cache.load_key(key)
                if cached is not None:
                    yield key, cached
                    continue
            remaining.append(key)
        if not remaining:
            return
        for pair in self._fetch_blobs(remaining, missing_ok,
                                      cacheable=cacheable):
            yield pair

    def _fetch_blobs(self, keys, missing_ok, cacheable=True):
        """Fetch keys from storage with in-flight dedup: when the blob
        cache provides key locks (FileCache does), concurrent gang
        workers racing on the same keys serialize per key and all but the
        first fetcher resolve from the cache instead of re-downloading.
        Locks are taken in sorted key order, and the cache's key_lock is
        BOUNDED (times out into an unlocked fetch) — nested loads across
        workers can interleave lock batches in conflicting orders, so an
        untimed lock could cycle; a timeout costs at most one duplicate
        download, never a hang.

        Streaming: blobs yield ONE at a time (bulk data stages on disk
        via load_bytes), so peak RSS is one unpacked blob regardless of
        the artifact set size. The key locks consequently stay held
        while the consumer iterates — that can extend another worker's
        wait, but never beyond this reader's own load, and the
        alternative (buffering every blob to release locks early) trades
        a wait for an OOM."""
        cache = self._blob_cache if cacheable else None
        lock_fn = getattr(cache, "key_lock", None) if cache else None
        locks = []
        try:
            if lock_fn is not None:
                for key in sorted(set(keys)):
                    lk = lock_fn(key)
                    lk.__enter__()
                    locks.append(lk)
                # under the locks another worker may have landed the blob
                still = []
                for key in keys:
                    cached = cache.load_key(key)
                    if cached is not None:
                        yield key, cached
                    else:
                        still.append(key)
                keys = still
            if keys:
                paths = {self._path(k): k for k in keys}
                with self._storage.load_bytes(list(paths)) as loaded:
                    for path, local, _meta in loaded:
                        key = paths[path]
                        if local is None:
                            if missing_ok:
                                continue
                            raise KeyError(
                                "Content-addressed blob %s not found in "
                                "datastore" % key
                            )
                        with open(local, "rb") as f:
                            packed = f.read()
                        blob = self._unpack(packed)
                        if cache is not None:
                            cache.store_key(key, blob)
                        yield key, blob
        finally:
            for lk in reversed(locks):
                lk.__exit__(None, None, None)

    def blob_exists(self, keys):
        return self._storage.is_file([self._path(k) for k in keys])

    def _unpack(self, packed):
        fmt, payload = packed[:1], packed[1:]
        if fmt == self.FMT_RAW:
            return payload
        if fmt == self.FMT_GZIP:
            return gzip.decompress(payload)
        # backward-compatible fallback: whole object is gzip (no tag byte)
        try:
            return gzip.GzipFile(fileobj=io.BytesIO(packed)).read()
        except OSError:
            return packed
