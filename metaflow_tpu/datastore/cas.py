"""Content-addressed blob store: hash-keyed, de-duplicated, per-flow.

Reference behavior: metaflow/datastore/content_addressed_store.py
(ContentAddressedStore:11, _pack_v1:211/_unpack_v1:218). Differences chosen
for TPU-first operation:
  - SHA-256 instead of SHA-1 (hardware-accelerated, no collision caveats)
  - per-blob compression is a *format tag*, so large tensor blobs can skip
    gzip (HBM→host→GCS path stays memory-bandwidth bound, not CPU bound)
"""

import gzip
import hashlib
import io
import os


class BlobCache(object):
    def load_key(self, key):
        return None

    def store_key(self, key, blob):
        pass


class _TaggedFileReader(object):
    """File-like that serves a pack-format tag byte, then the file —
    lets save_bytes stream a tagged blob without materializing it."""

    def __init__(self, path, tag):
        self._path = path
        self._tag = tag
        self._file = None

    def read(self, n=-1):
        if n == 0:
            return b""
        if self._file is None:
            self._file = open(self._path, "rb")
            if n is None or n < 0:
                return self._tag + self._file.read()
            return self._tag + self._file.read(max(0, n - len(self._tag)))
        return self._file.read(n)

    def close(self):
        if self._file is not None:
            self._file.close()


class ContentAddressedStore(object):
    # pack formats: first byte of the stored object selects the decoder
    FMT_RAW = b"0"      # raw bytes
    FMT_GZIP = b"1"     # gzip-compressed

    # blobs larger than this skip gzip (tensor data is incompressible and
    # gzip becomes the bottleneck at HBM-scale artifact sizes)
    COMPRESS_MAX = 8 * 1024 * 1024

    def __init__(self, prefix, storage):
        self._prefix = prefix
        self._storage = storage
        self._blob_cache = None

    def set_blob_cache(self, blob_cache):
        self._blob_cache = blob_cache

    def _path(self, key):
        return self._storage.path_join(self._prefix, key[:2], key)

    # once a persist has streamed this much hash+gzip work, the REMAINING
    # blobs are fanned over forked workers (multicore.parallel_map —
    # reference behavior: metaflow/multicore_utils.py on the persist
    # path). The prefix stays streaming so small persists never buffer
    # and big ones only materialize the parallel tail.
    PARALLEL_PACK_MIN_BYTES = 8 << 20
    PARALLEL_PACK_MIN_BLOBS = 4
    PARALLEL_PACK_WORKERS = None  # None = multicore's cpu-count default

    def _pack_blob(self, blob, raw):
        sha = hashlib.sha256(blob).hexdigest()
        if raw or len(blob) > self.COMPRESS_MAX:
            packed = self.FMT_RAW + blob
        else:
            packed = self.FMT_GZIP + gzip.compress(blob, compresslevel=3)
        return sha, packed

    def save_blobs(self, blob_iter, raw=False, len_hint=0):
        """Save blobs; returns list of (uri, key) in input order."""
        packed_all = []
        it = iter(blob_iter)
        count = 0
        total = 0
        tail = None
        for blob in it:
            count += 1
            total += len(blob)
            packed_all.append(self._pack_blob(blob, raw))
            if (count >= self.PARALLEL_PACK_MIN_BLOBS
                    and total >= self.PARALLEL_PACK_MIN_BYTES):
                tail = list(it)
                break
        if tail:
            from ..multicore import parallel_map

            packed_all.extend(parallel_map(
                lambda b: self._pack_blob(b, raw), tail,
                max_parallel=self.PARALLEL_PACK_WORKERS, min_chunk=2,
            ))
        results = []
        to_save = []
        for sha, packed in packed_all:
            path = self._path(sha)
            results.append((self._storage.full_uri(path), sha))
            to_save.append((path, packed))
        # overwrite=False: content-addressed ⇒ existing key has same bytes
        self._storage.save_bytes(iter(to_save), overwrite=False,
                                 len_hint=len(to_save))
        return results

    CHUNK = 1 << 20

    def save_file(self, path):
        """Stream one FILE into the store at bounded RSS: chunked SHA-256,
        then a tag-prefixed reader handed to the storage backend (local
        storage copies it file-to-file; GCS spools through a temp file
        into the pread-based put_file path). Stored FMT_RAW — include
        payloads are arbitrary user data, often incompressible, and raw
        keeps the download side streamable too. Returns (uri, key)."""
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(self.CHUNK), b""):
                h.update(chunk)
        sha = h.hexdigest()
        storage_path = self._path(sha)
        self._storage.save_bytes(
            iter([(storage_path, _TaggedFileReader(path, self.FMT_RAW))]),
            overwrite=False, len_hint=1,
        )
        return self._storage.full_uri(storage_path), sha

    def open_blob_stream(self, key):
        """Context manager yielding a binary file object positioned at the
        blob's payload (pack tag consumed, gzip transparently wrapped) —
        the bounded-RSS read path for large raw blobs."""
        import contextlib

        @contextlib.contextmanager
        def opened():
            with self._storage.load_bytes([self._path(key)]) as loaded:
                for _path, local, _meta in loaded:
                    if local is None:
                        raise KeyError(
                            "Content-addressed blob %s not found in "
                            "datastore" % key
                        )
                    with open(local, "rb") as f:
                        fmt = f.read(1)
                        if fmt == self.FMT_RAW:
                            yield f
                        elif fmt == self.FMT_GZIP:
                            yield gzip.GzipFile(fileobj=f, mode="rb")
                        else:
                            # no tag byte: MIRROR _unpack's fallback —
                            # pre-tag-era blobs are whole-object gzip;
                            # only yield raw when it isn't gzip at all
                            f.seek(0)
                            gz = gzip.GzipFile(fileobj=f, mode="rb")
                            try:
                                gz.peek(1)
                                yield gz
                            except OSError:
                                f.seek(0)
                                yield f
                    return

        return opened()

    def load_blobs(self, keys, force_raw=False, missing_ok=False):
        """Yield (key, bytes) for each key (order not guaranteed).

        missing_ok=True skips absent keys instead of raising — for
        opportunistic prefetch, where a missing blob should surface (or
        not) at the actual read."""
        remaining = []
        for key in keys:
            if self._blob_cache is not None:
                cached = self._blob_cache.load_key(key)
                if cached is not None:
                    yield key, cached
                    continue
            remaining.append(key)
        if not remaining:
            return
        paths = {self._path(k): k for k in remaining}
        with self._storage.load_bytes(list(paths)) as loaded:
            for path, local, _meta in loaded:
                key = paths[path]
                if local is None:
                    if missing_ok:
                        continue
                    raise KeyError(
                        "Content-addressed blob %s not found in datastore"
                        % key
                    )
                with open(local, "rb") as f:
                    packed = f.read()
                blob = self._unpack(packed)
                if self._blob_cache is not None:
                    self._blob_cache.store_key(key, blob)
                yield key, blob

    def blob_exists(self, keys):
        return self._storage.is_file([self._path(k) for k in keys])

    def _unpack(self, packed):
        fmt, payload = packed[:1], packed[1:]
        if fmt == self.FMT_RAW:
            return payload
        if fmt == self.FMT_GZIP:
            return gzip.decompress(payload)
        # backward-compatible fallback: whole object is gzip (no tag byte)
        try:
            return gzip.GzipFile(fileobj=io.BytesIO(packed)).read()
        except OSError:
            return packed
