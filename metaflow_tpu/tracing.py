"""Tracing: OpenTelemetry with a graceful no-op default.

Reference behavior: metaflow/tracing/ (__init__.py:14-50 no-op shims unless
deps + an endpoint are configured; spans wrap CLI commands; context
propagates into subprocesses via env). Enable by setting
TPUFLOW_OTEL_ENDPOINT (requires opentelemetry-sdk to be installed).
"""

import functools
import os
from contextlib import contextmanager

_ENDPOINT_VAR = "TPUFLOW_OTEL_ENDPOINT"
_TRACEPARENT_VAR = "TRACEPARENT"

_tracer = None
_initialized = False


def _init():
    global _tracer, _initialized
    if _initialized:
        return _tracer
    _initialized = True
    endpoint = os.environ.get(_ENDPOINT_VAR)
    if not endpoint:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": "metaflow_tpu"})
        )
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
        )
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer("metaflow_tpu")
    except ImportError:
        _tracer = None
    return _tracer


@contextmanager
def span(name, attributes=None):
    """Span context manager; no-op when tracing is disabled."""
    tracer = _init()
    if tracer is None:
        yield None
        return
    with tracer.start_as_current_span(name) as s:
        for k, v in (attributes or {}).items():
            s.set_attribute(k, v)
        yield s


def cli(name):
    """Decorator wrapping a CLI command in a span (reference: @tracing.cli)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def inject_tracing_vars(env):
    """Propagate trace context into a subprocess env (no-op when off)."""
    tracer = _init()
    if tracer is None:
        return env
    try:
        from opentelemetry.propagate import inject

        carrier = {}
        inject(carrier)
        env.update({k.upper().replace("-", "_"): v
                    for k, v in carrier.items()})
    except ImportError:
        pass
    return env


def get_trace_id():
    tracer = _init()
    if tracer is None:
        return ""
    try:
        from opentelemetry import trace

        ctx = trace.get_current_span().get_span_context()
        return format(ctx.trace_id, "032x") if ctx.is_valid else ""
    except ImportError:
        return ""
