"""Tracing: OpenTelemetry with a graceful no-op default.

Reference behavior: metaflow/tracing/ (__init__.py:14-50 no-op shims unless
deps + an endpoint are configured; spans wrap CLI commands; context
propagates into subprocesses via env). Enable by setting
TPUFLOW_OTEL_ENDPOINT (requires opentelemetry-sdk to be installed).
"""

import functools
import os
from contextlib import contextmanager

_ENDPOINT_VAR = "TPUFLOW_OTEL_ENDPOINT"
_TRACEPARENT_VAR = "TRACEPARENT"

_tracer = None
_initialized = False


def _init():
    global _tracer, _initialized
    if _initialized:
        return _tracer
    _initialized = True
    endpoint = os.environ.get(_ENDPOINT_VAR)
    if not endpoint:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": "metaflow_tpu"})
        )
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
        )
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer("metaflow_tpu")
    except ImportError:
        _tracer = None
    return _tracer


@contextmanager
def span(name, attributes=None):
    """Span context manager; no-op when tracing is disabled.

    Spans also tee into the run's flight recorder (telemetry.py) as timer
    records when one is active — the `persist.*` spans around datastore
    ops thereby land in `tpuflow metrics` without double instrumentation.
    Exceptions are recorded on the span (ERROR status) and re-raised,
    never swallowed into a clean span.
    """
    from . import telemetry

    tracer = _init()
    if tracer is None:
        if telemetry.current_recorder() is None:
            yield None
            return
        with telemetry.timer(name, data=_span_data(attributes)):
            yield None
        return
    # attributes at creation: samplers and processors see them at
    # span-start, not after the fact
    with telemetry.timer(name, data=_span_data(attributes)):
        with tracer.start_as_current_span(
            name, attributes=attributes or {}, record_exception=True,
            set_status_on_exception=True,
        ) as s:
            yield s


def _span_data(attributes):
    if not attributes:
        return None
    # telemetry records are JSON: keep attribute values primitive
    return {
        k: (v if isinstance(v, (str, int, float, bool)) else str(v))
        for k, v in attributes.items()
    }


def cli(name):
    """Decorator wrapping a CLI command in a span (reference: @tracing.cli)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def inject_tracing_vars(env):
    """Propagate trace context into a subprocess env.

    With an active OTel tracer the current span context is injected; with
    tracing off, an ambient TRACEPARENT (set by a CI driver, a parent
    scheduler, or ensure_traceparent) is still forwarded so all ranks of
    a gang — and every task of a run — share one trace id in their
    telemetry records."""
    tracer = _init()
    if tracer is None:
        if _TRACEPARENT_VAR in os.environ:
            env.setdefault(_TRACEPARENT_VAR,
                           os.environ[_TRACEPARENT_VAR])
        return env
    try:
        from opentelemetry.propagate import inject

        carrier = {}
        inject(carrier)
        env.update({k.upper().replace("-", "_"): v
                    for k, v in carrier.items()})
    except ImportError:
        pass
    return env


def ensure_traceparent(seed):
    """Make sure this process carries a W3C TRACEPARENT, synthesizing a
    deterministic one from `seed` (the run id) when absent — so OTel
    spans and telemetry records from every task/rank of a run join one
    trace even without an OTel SDK in the tasks. Returns the value."""
    existing = os.environ.get(_TRACEPARENT_VAR)
    if existing:
        return existing
    import hashlib

    digest = hashlib.sha256(("tpuflow-run:%s" % seed).encode()).hexdigest()
    value = "00-%s-%s-01" % (digest[:32], digest[32:48])
    os.environ[_TRACEPARENT_VAR] = value
    return value


def get_trace_id():
    tracer = _init()
    if tracer is None:
        return ""
    try:
        from opentelemetry import trace

        ctx = trace.get_current_span().get_span_context()
        return format(ctx.trace_id, "032x") if ctx.is_valid else ""
    except ImportError:
        return ""
