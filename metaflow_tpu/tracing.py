"""Tracing: OpenTelemetry with a graceful no-op default.

Reference behavior: metaflow/tracing/ (__init__.py:14-50 no-op shims unless
deps + an endpoint are configured; spans wrap CLI commands; context
propagates into subprocesses via env). Enable by setting
TPUFLOW_OTEL_ENDPOINT (requires opentelemetry-sdk to be installed).
"""

import functools
import os
from contextlib import contextmanager

from . import knobs

_ENDPOINT_VAR = "TPUFLOW_OTEL_ENDPOINT"
_TRACEPARENT_VAR = "TRACEPARENT"

_tracer = None
_initialized = False


def _init():
    global _tracer, _initialized
    if _initialized:
        return _tracer
    _initialized = True
    endpoint = knobs.get_str(_ENDPOINT_VAR)
    if not endpoint:
        return None
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        provider = TracerProvider(
            resource=Resource.create({"service.name": "metaflow_tpu"})
        )
        provider.add_span_processor(
            BatchSpanProcessor(OTLPSpanExporter(endpoint=endpoint))
        )
        trace.set_tracer_provider(provider)
        _tracer = trace.get_tracer("metaflow_tpu")
    except ImportError:
        _tracer = None
    return _tracer


@contextmanager
def span(name, attributes=None):
    """Span context manager; no-op when tracing is disabled.

    Spans also tee into the run's flight recorder (telemetry.py) as timer
    records when one is active — the `persist.*` spans around datastore
    ops thereby land in `tpuflow metrics` without double instrumentation.
    Exceptions are recorded on the span (ERROR status) and re-raised,
    never swallowed into a clean span.
    """
    from . import telemetry

    tracer = _init()
    if tracer is None:
        if telemetry.current_recorder() is None:
            yield None
            return
        with telemetry.timer(name, data=_span_data(attributes)):
            yield None
        return
    # attributes at creation: samplers and processors see them at
    # span-start, not after the fact
    with telemetry.timer(name, data=_span_data(attributes)):
        with tracer.start_as_current_span(
            name, attributes=attributes or {}, record_exception=True,
            set_status_on_exception=True,
        ) as s:
            yield s


def _span_data(attributes):
    if not attributes:
        return None
    # telemetry records are JSON: keep attribute values primitive
    return {
        k: (v if isinstance(v, (str, int, float, bool)) else str(v))
        for k, v in attributes.items()
    }


def cli(name):
    """Decorator wrapping a CLI command in a span (reference: @tracing.cli)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def inject_tracing_vars(env):
    """Propagate trace context into a subprocess env.

    With an active OTel tracer the current span context is injected; with
    tracing off, an ambient TRACEPARENT (set by a CI driver, a parent
    scheduler, or ensure_traceparent) is still forwarded so all ranks of
    a gang — and every task of a run — share one trace id in their
    telemetry records."""
    tracer = _init()
    if tracer is None:
        if _TRACEPARENT_VAR in os.environ:
            env.setdefault(_TRACEPARENT_VAR,
                           os.environ[_TRACEPARENT_VAR])
        return env
    try:
        from opentelemetry.propagate import inject

        carrier = {}
        inject(carrier)
        env.update({k.upper().replace("-", "_"): v
                    for k, v in carrier.items()})
    except ImportError:
        pass
    return env


def ensure_traceparent(seed):
    """Make sure this process carries a W3C TRACEPARENT, synthesizing a
    deterministic one from `seed` (the run id) when absent — so OTel
    spans and telemetry records from every task/rank of a run join one
    trace even without an OTel SDK in the tasks. Returns the value."""
    existing = os.environ.get(_TRACEPARENT_VAR)
    if existing:
        return existing
    import hashlib

    digest = hashlib.sha256(("tpuflow-run:%s" % seed).encode()).hexdigest()
    value = "00-%s-%s-01" % (digest[:32], digest[32:48])
    os.environ[_TRACEPARENT_VAR] = value
    return value


def get_trace_id():
    tracer = _init()
    if tracer is None:
        return ""
    try:
        from opentelemetry import trace

        ctx = trace.get_current_span().get_span_context()
        return format(ctx.trace_id, "032x") if ctx.is_valid else ""
    except ImportError:
        return ""


# ---------------------------------------------------------------------------
# Per-request trace context (serving path)
#
# The fleet router mints one traceparent per request and forwards it as an
# HTTP header on every dispatch (including failover re-dispatch), deriving a
# fresh child span id per attempt. Replicas stamp the received trace/span
# into every serve.request.* telemetry record, so `tpuflow trace` can
# reassemble queued -> dispatch -> prefill -> first_token -> failover ->
# finished as ONE tree from the records alone. All ids are deterministic
# sha256 derivations: a re-run with the same request ids produces the same
# tree, and no coordination between router and replicas is needed.
# ---------------------------------------------------------------------------

_TRACE_REQUESTS_VAR = "TPUFLOW_TRACE_REQUESTS"


def trace_requests_enabled(env=None):
    """Per-request tracing is on unless TPUFLOW_TRACE_REQUESTS=0."""
    return knobs.get_bool(_TRACE_REQUESTS_VAR, env=env)


def _hexdigest(seed, n):
    import hashlib

    return hashlib.sha256(seed.encode()).hexdigest()[:n]


def request_traceparent(request_id):
    """Mint the root traceparent for one serving request.

    The trace id joins the ambient run trace (TRACEPARENT set by
    ensure_traceparent / the launching driver) when one exists, so request
    subtrees nest under the run; otherwise it is derived from the request
    id alone. The span id is always derived from the request id — it is
    the root of the request's subtree."""
    ambient = os.environ.get(_TRACEPARENT_VAR, "")
    parts = ambient.split("-")
    if len(parts) >= 3 and len(parts[1]) == 32:
        trace_id = parts[1]
    else:
        trace_id = _hexdigest("tpuflow-request-trace:%s" % request_id, 32)
    span_id = _hexdigest("tpuflow-request:%s" % request_id, 16)
    return "00-%s-%s-01" % (trace_id, span_id)


def child_traceparent(traceparent, key):
    """Derive a child traceparent: same trace id, span id keyed off the
    parent span + `key` (e.g. "dispatch-2" for the second dispatch
    attempt). Deterministic so the assembler can re-derive parentage."""
    trace_id, span_id = traceparent_ids(traceparent)
    child = _hexdigest("tpuflow-span:%s:%s" % (span_id, key), 16)
    return "00-%s-%s-01" % (trace_id, child)


def traceparent_ids(traceparent):
    """Split a W3C traceparent into (trace_id, span_id); ("", "") when
    malformed or absent."""
    parts = (traceparent or "").split("-")
    if len(parts) >= 3 and len(parts[1]) == 32 and len(parts[2]) == 16:
        return parts[1], parts[2]
    return "", ""
