"""Small shared utilities (reference shape: metaflow/util.py)."""

import os
import pwd
import sys
import zlib
import base64
from functools import wraps

from .exception import MetaflowUnknownUser  # noqa: F401  (re-export site)


# resolved ONCE at import time (main thread, pre-fork): fork children must
# not import — a thread holding the import lock at the fork instant would
# deadlock the child before exec
try:
    import ctypes as _ctypes

    _prctl = _ctypes.CDLL(None, use_errno=True).prctl
except Exception:  # non-Linux / restricted: hardening becomes a no-op
    _prctl = None

_PR_SET_PDEATHSIG = 1


def env_int(name, default):
    """int(os.environ[name]) with the default on missing OR malformed
    values — config knobs must degrade to their default, never crash the
    scheduler/loader that reads them."""
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return int(default)


def env_float(name, default):
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return float(default)


def preexec_die_with_parent(expected_ppid=None, sig=9, setsid=False):
    """A Popen preexec_fn arming PR_SET_PDEATHSIG: the kernel signals the
    child the instant its parent dies — no matter how the parent died
    (SIGKILL, OOM, crash), which Python-level cleanup can never cover.

    sig defaults to SIGKILL, deliberately: this is the last-resort edge,
    and a Python-level SIGTERM handler (every task installs the
    preemption handler) only runs at a bytecode boundary — a rank wedged
    inside an XLA collective would never reach one and would hold the
    chips forever. Graceful paths (spot preemption, scheduler teardown)
    signal explicitly; the kernel edge must actually kill.

    expected_ppid closes the inherent race: if the parent died before the
    prctl took effect, the child was already reparented, so exit at once
    (checked on every platform — only the prctl itself is Linux-only).
    setsid=True additionally makes the child a session leader (the
    scheduler's process-group kills rely on it)."""

    def preexec():
        # only already-resolved calls here: the fork child may hold
        # inherited locks no other thread will ever release
        if setsid:
            os.setsid()
        if _prctl is not None:
            _prctl(_PR_SET_PDEATHSIG, sig, 0, 0, 0)
        if expected_ppid is not None and os.getppid() != expected_ppid:
            os._exit(1)  # parent already gone

    return preexec


def get_username():
    """Resolve the current user for namespacing and tags."""
    for var in ("METAFLOW_USER", "TPUFLOW_USER", "SUDO_USER", "USERNAME", "USER"):
        user = os.environ.get(var)
        if user and user != "root":
            return user
    try:
        return pwd.getpwuid(os.getuid()).pw_name
    except Exception:
        return os.environ.get("USER", "unknown")


def resolve_identity():
    return "user:%s" % get_username()


def pathspec(*components):
    return "/".join(str(c) for c in components)


def compress_list(lst, separator=",", zlibmarker="!", zlibmin=500):
    """Encode a list of strings into a single CLI-safe token: the joined
    list, switching to zlib+base64 once it grows past zlibmin (fills the
    same role as the reference's input-path encoding, metaflow/util.py).
    Items must not contain the separator or marker characters."""
    bad = [x for x in lst if separator in x or zlibmarker in x]
    if bad:
        raise RuntimeError("Item(s) %s contain reserved characters" % bad)
    res = separator.join(lst)
    if len(res) < zlibmin:
        return res
    return zlibmarker + base64.b64encode(
        zlib.compress(res.encode("utf-8"))
    ).decode("utf-8")


def decompress_list(lststr, separator=",", zlibmarker="!"):
    if lststr.startswith(zlibmarker):
        lststr = zlib.decompress(
            base64.b64decode(lststr[1:].encode("utf-8"))
        ).decode("utf-8")
    return lststr.split(separator) if lststr else []


def to_unicode(x):
    if isinstance(x, bytes):
        return x.decode("utf-8", errors="replace")
    return str(x)


def to_bytes(x):
    if isinstance(x, bytes):
        return x
    return str(x).encode("utf-8")


def cached_property(fn):
    attr = "_cached_" + fn.__name__

    @wraps(fn)
    def getter(self):
        if not hasattr(self, attr):
            setattr(self, attr, fn(self))
        return getattr(self, attr)

    return property(getter)


def is_stringish(x):
    return isinstance(x, (str, bytes))


def all_equal(it):
    lst = list(it)
    return not lst or lst.count(lst[0]) == len(lst)


def get_tpuflow_root():
    """Root directory for the local datastore/metadata tree (env →
    profile config → ./.tpuflow)."""
    from .metaflow_config import datastore_sysroot_local

    return datastore_sysroot_local()


def write_latest_run_id(flow_name, run_id, root=None):
    root = root or get_tpuflow_root()
    d = os.path.join(root, flow_name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "latest_run"), "w") as f:
        f.write(str(run_id))


def read_latest_run_id(flow_name, root=None):
    root = root or get_tpuflow_root()
    try:
        with open(os.path.join(root, flow_name, "latest_run")) as f:
            return f.read().strip()
    except IOError:
        return None


def unicode_to_stream(text, stream=None):
    (stream or sys.stdout).write(to_unicode(text))
