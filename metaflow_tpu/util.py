"""Small shared utilities (reference shape: metaflow/util.py)."""

import os
import pwd
import sys
import zlib
import base64
from functools import wraps

from .exception import MetaflowUnknownUser  # noqa: F401  (re-export site)


def get_username():
    """Resolve the current user for namespacing and tags."""
    for var in ("METAFLOW_USER", "TPUFLOW_USER", "SUDO_USER", "USERNAME", "USER"):
        user = os.environ.get(var)
        if user and user != "root":
            return user
    try:
        return pwd.getpwuid(os.getuid()).pw_name
    except Exception:
        return os.environ.get("USER", "unknown")


def resolve_identity():
    return "user:%s" % get_username()


def pathspec(*components):
    return "/".join(str(c) for c in components)


def compress_list(lst, separator=",", zlibmarker="!", zlibmin=500):
    """Encode a list of strings into a single CLI-safe token: the joined
    list, switching to zlib+base64 once it grows past zlibmin (fills the
    same role as the reference's input-path encoding, metaflow/util.py).
    Items must not contain the separator or marker characters."""
    bad = [x for x in lst if separator in x or zlibmarker in x]
    if bad:
        raise RuntimeError("Item(s) %s contain reserved characters" % bad)
    res = separator.join(lst)
    if len(res) < zlibmin:
        return res
    return zlibmarker + base64.b64encode(
        zlib.compress(res.encode("utf-8"))
    ).decode("utf-8")


def decompress_list(lststr, separator=",", zlibmarker="!"):
    if lststr.startswith(zlibmarker):
        lststr = zlib.decompress(
            base64.b64decode(lststr[1:].encode("utf-8"))
        ).decode("utf-8")
    return lststr.split(separator) if lststr else []


def to_unicode(x):
    if isinstance(x, bytes):
        return x.decode("utf-8", errors="replace")
    return str(x)


def to_bytes(x):
    if isinstance(x, bytes):
        return x
    return str(x).encode("utf-8")


def cached_property(fn):
    attr = "_cached_" + fn.__name__

    @wraps(fn)
    def getter(self):
        if not hasattr(self, attr):
            setattr(self, attr, fn(self))
        return getattr(self, attr)

    return property(getter)


def is_stringish(x):
    return isinstance(x, (str, bytes))


def all_equal(it):
    lst = list(it)
    return not lst or lst.count(lst[0]) == len(lst)


def get_tpuflow_root():
    """Root directory for the local datastore/metadata tree (env →
    profile config → ./.tpuflow)."""
    from .metaflow_config import datastore_sysroot_local

    return datastore_sysroot_local()


def write_latest_run_id(flow_name, run_id, root=None):
    root = root or get_tpuflow_root()
    d = os.path.join(root, flow_name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "latest_run"), "w") as f:
        f.write(str(run_id))


def read_latest_run_id(flow_name, root=None):
    root = root or get_tpuflow_root()
    try:
        with open(os.path.join(root, flow_name, "latest_run")) as f:
            return f.read().strip()
    except IOError:
        return None


def unicode_to_stream(text, stream=None):
    (stream or sys.stdout).write(to_unicode(text))
