"""Click CLI: `python flow.py run|resume|step|check|show|dump|logs|output-dot`.

Reference behavior: metaflow/cli.py (start group:235-333) +
cli_components/{run_cmds,step_cmd,dump_cmd}.py. The `step` command is the
hidden per-task entrypoint the runtime launches as a subprocess; `run` drives
the NativeRuntime scheduler.
"""

import json
import os
import sys
import threading
import traceback

import click

from . import knobs
from .datastore import STORAGE_BACKENDS, FlowDataStore
from .decorators import (
    _attach_decorators,
    _init_step_decorators,
    _init_flow_decorators,
)
from .exception import TpuFlowException
from .graph import FlowGraph
from .lint import lint
from .metadata import METADATA_PROVIDERS
from .plugins.parallel_decorator import ParallelDecorator
from .plugins.tpu.tpu_parallel import TpuParallelDecorator
from .runtime import NativeRuntime
from .task import MetaflowTask
from .unbounded_foreach import UBF_CONTROL
from .util import (
    decompress_list,
    get_tpuflow_root,
    read_latest_run_id,
    resolve_identity,
)

# the step command records its argv here so gang control tasks can replay it
# for worker ranks (plugins/parallel_decorator.py)
STEP_ARGV_ENV = "TPUFLOW_STEP_ARGV"


def echo(line):
    print(line, flush=True)


def echo_quiet(line):
    pass


class CliState(object):
    def __init__(self, flow):
        self.flow = flow
        self.graph = None
        self.flow_datastore = None
        self.metadata = None
        self.echo = echo
        self.quiet = False
        self.decospecs = []
        self.raw_decospecs = []
        self.config_args = []
        self.config_files = {}
        self.config_values = {}
        self.finalized = False


def _prepare(state, decospecs):
    """Lint, attach --with decorators, auto-attach the TPU gang decorator."""
    flow = state.flow
    state.graph = flow._graph
    lint(state.graph)
    if decospecs:
        _attach_decorators(flow, decospecs)
        state.decospecs = list(decospecs)
    # TPU-first default: gang steps get jax.distributed wiring automatically
    for node in state.graph:
        if node.parallel_step:
            step_func = getattr(flow, node.name)
            if not any(
                isinstance(d, ParallelDecorator) for d in step_func.decorators
            ):
                step_func.decorators.append(
                    TpuParallelDecorator(statically_defined=False)
                )
    _init_flow_decorators(flow, state.graph, None, state.flow_datastore,
                          state.metadata, state.echo, state.echo, {})
    _init_step_decorators(flow, state.graph, None, state.flow_datastore, state.echo)


def _finalize(state, origin_run=None):
    """Resolve configs ONCE (merging the origin run's values under any
    explicit --config/--config-value flags when resuming), run mutators,
    lint, and init decorators. Idempotent per process."""
    if state.finalized:
        return
    from .config_system import apply_mutators, resolve_configs

    files = dict(state.config_files)
    values = dict(state.config_values)
    if origin_run is not None:
        try:
            origin_start = state.flow_datastore.get_task_datastores(
                run_id=origin_run, steps=["start"]
            )
        except Exception:
            origin_start = []
        if origin_start:
            ds = origin_start[0]
            for name in list(ds.keys()):
                if not name.startswith("_config_"):
                    continue
                cfg = name[len("_config_"):]
                if cfg in files or cfg in values:
                    continue  # explicit flags on resume win
                serialized = json.dumps(ds[name])
                values[cfg] = serialized
                state.config_args += ["--config-value", cfg, serialized]
    resolve_configs(state.flow.__class__, files, values)
    apply_mutators(state.flow.__class__)
    _prepare(state, state.raw_decospecs)
    state.finalized = True


def _param_options(flow):
    opts = []
    for name, param in flow._get_parameters():
        kwargs = {"default": None, "required": False}
        if param.help:
            kwargs["help"] = param.help
        opts.append(click.Option(["--" + name.replace("_", "-"), name], **kwargs))
    return opts


def _parse_task_pathspec(pathspec):
    parts = pathspec.split("/")
    if len(parts) == 4:
        parts = parts[1:]  # allow flow/run/step/task
    if len(parts) != 3:
        raise TpuFlowException(
            "Specify a task as RUN_ID/STEP/TASK_ID; got %r" % pathspec
        )
    return parts


def _write_argo_outputs(state, out_dir, run_id, step_name, task_id,
                        iteration=None):
    """Drop Argo output-parameter files (read via valueFrom.path): the
    foreach fan-out cardinality as a JSON index list (consumed by withParam
    and by the join's --join-inputs), the switch's chosen next step
    (consumed by `when` conditions), and — for recursive-switch loop
    templates — the next iteration counter plus this task's own id (the
    loop template exports the FINAL iteration's pathspec to its exits)."""
    os.makedirs(out_dir, exist_ok=True)
    ds = state.flow_datastore.get_task_datastore(run_id, step_name, task_id)
    num_splits = ds.get("_foreach_num_splits") or 0
    transition = ds.get("_transition")
    next_step = ""
    if transition and transition[0]:
        next_step = transition[0][0]
    with open(os.path.join(out_dir, "num-splits"), "w") as f:
        json.dump(list(range(int(num_splits))), f)
    with open(os.path.join(out_dir, "num-parallel"), "w") as f:
        # gang cardinality as a scalar: substituted into the JobSet
        # manifest's completions/parallelism by the gang resource template
        f.write(str(int(num_splits) or 1))
    with open(os.path.join(out_dir, "next-step"), "w") as f:
        f.write(next_step)
    with open(os.path.join(out_dir, "own-task-id"), "w") as f:
        f.write(str(task_id))
    if iteration not in (None, ""):
        with open(os.path.join(out_dir, "iter-next"), "w") as f:
            f.write(str(int(iteration) + 1))


def _collect_params(flow, kwargs):
    params = {}
    for name, _param in flow._get_parameters():
        val = kwargs.pop(name, None)
        if val is not None:
            params[name] = val
    return params, kwargs


def make_cli(flow, state):
    """Build the flow's click command group. main() invokes it; the
    programmatic API (runner/click_api.py) introspects it so Runner kwargs
    track the CLI surface automatically."""
    from . import metaflow_config as _cfg

    @click.group(name=flow.name, invoke_without_command=False)
    @click.option("--datastore", default=_cfg.default_datastore,
                  type=click.Choice(list(STORAGE_BACKENDS)),
                  help="Artifact storage backend.")
    @click.option("--datastore-root", default=None,
                  help="Root path for the datastore.")
    @click.option("--metadata", default=_cfg.default_metadata,
                  type=click.Choice(list(METADATA_PROVIDERS)),
                  help="Metadata provider.")
    @click.option("--quiet/--no-quiet", default=False)
    @click.option("--with", "decospecs", multiple=True,
                  help="Attach a decorator to all steps (name:attr=val,...)")
    @click.option("--config", "config_files", nargs=2, multiple=True,
                  help="Resolve a Config from a file: --config name path")
    @click.option("--config-value", "config_values", nargs=2, multiple=True,
                  help="Resolve a Config inline: --config-value name '<json>'")
    @click.pass_context
    def start(ctx, datastore, datastore_root, metadata, quiet, decospecs,
              config_files, config_values):
        storage_impl = STORAGE_BACKENDS[datastore]
        state.flow_datastore = FlowDataStore(
            flow.name, storage_impl, ds_root=datastore_root
        )
        if datastore != "local" and knobs.get_bool("TPUFLOW_BLOB_CACHE"):
            # task-side reads share the host-local blob cache too — CAS
            # blobs are immutable, so N tasks on one host download each
            # input artifact once, not N times (reference gap:
            # client/filecache.py was client-only)
            from .client.filecache import FileCache

            state.flow_datastore.ca_store.set_blob_cache(FileCache())
        state.metadata = METADATA_PROVIDERS[metadata](flow=flow)
        # raw selections, re-emitted into compiled (Argo) container commands
        state.datastore_type = datastore
        state.metadata_type = metadata
        # the *explicit* root only: a defaulted local root is this machine's
        # filesystem and must not be compiled into remote pod commands
        state.datastore_root_explicit = datastore_root
        state.quiet = quiet
        if quiet:
            state.echo = echo_quiet
        # config resolution + mutators + lint happen in _finalize, invoked
        # by the commands that execute the graph (resume merges the origin
        # run's configs FIRST — resolving here would be too early)
        state.config_files = dict(config_files)
        state.config_values = dict(config_values)
        state.raw_decospecs = list(decospecs)
        state.config_args = []
        for name, path in config_files:
            state.config_args += ["--config", name, path]
        for name, val in config_values:
            state.config_args += ["--config-value", name, val]
        ctx.obj = state

    @start.command(help="Run the workflow locally.")
    @click.option("--max-workers", default=16, show_default=True)
    @click.option("--max-num-splits", default=100, show_default=True)
    @click.option("--tag", "tags", multiple=True)
    @click.option("--run-id-file", default=None)
    @click.option("--namespace", "user_namespace", default=None)
    @click.pass_obj
    def run(state, max_workers, max_num_splits, tags, run_id_file,
            user_namespace, **kwargs):
        _finalize(state)
        params, _ = _collect_params(state.flow, kwargs)
        state.metadata.add_sticky_tags(tags=tags)
        runtime = NativeRuntime(
            state.flow,
            state.graph,
            state.flow_datastore,
            state.metadata,
            params=params,
            namespace=user_namespace or resolve_identity(),
            max_workers=max_workers,
            max_num_splits=max_num_splits,
            echo=echo,
            decospecs=state.decospecs,
            config_args=state.config_args,
        )
        if run_id_file:
            with open(run_id_file, "w") as f:
                f.write(str(runtime.run_id))
        runtime.execute()

    run.params.extend(_param_options(flow))

    @start.command(help="Resume a past run from where it failed.")
    @click.argument("step-to-rerun", required=False)
    @click.option("--origin-run-id", default=None,
                  help="Run to resume (default: latest run).")
    @click.option("--max-workers", default=16)
    @click.option("--max-num-splits", default=100)
    @click.option("--run-id-file", default=None)
    @click.pass_obj
    def resume(state, step_to_rerun, origin_run_id, max_workers,
               max_num_splits, run_id_file):
        origin = origin_run_id or read_latest_run_id(flow.name)
        if origin is None:
            raise TpuFlowException(
                "No previous run found for flow %s: nothing to resume."
                % flow.name
            )
        # single config resolution: origin-run values merged under any
        # explicit flags, BEFORE mutators/lint run
        _finalize(state, origin_run=origin)
        if step_to_rerun and step_to_rerun not in state.graph:
            raise TpuFlowException(
                "Step *%s* does not exist in flow %s." % (step_to_rerun, flow.name)
            )
        # reuse the origin run's parameters
        params = {}
        try:
            origin_start = state.flow_datastore.get_task_datastores(
                run_id=origin, steps=["start"]
            )
            if origin_start:
                from .includefile import IncludedFile

                include_params = {
                    name for name, p in flow._get_parameters()
                    if getattr(p, "IS_INCLUDE_FILE", False)
                }
                ds = origin_start[0]
                for name in ds.get("_parameter_names") or []:
                    value = ds[name]
                    if isinstance(value, IncludedFile):
                        # replay the DESCRIPTOR (JSON-safe): the start
                        # task resolves it without touching the original
                        # path or re-uploading the content
                        value = value.descriptor
                    elif name in include_params and isinstance(
                            value, (str, bytes)):
                        # pre-descriptor runs stored the CONTENT itself;
                        # provenance (an IncludeFile param's artifact)
                        # makes this unambiguous — wrap explicitly
                        value = IncludedFile.legacy_inline_descriptor(value)
                    params[name] = value
        except Exception:
            pass
        runtime = NativeRuntime(
            state.flow,
            state.graph,
            state.flow_datastore,
            state.metadata,
            params=params,
            namespace=resolve_identity(),
            max_workers=max_workers,
            max_num_splits=max_num_splits,
            origin_run_id=origin,
            clone_run_id=origin,
            resume_step=step_to_rerun,
            echo=echo,
            decospecs=state.decospecs,
            config_args=state.config_args,
        )
        if run_id_file:
            with open(run_id_file, "w") as f:
                f.write(str(runtime.run_id))
        runtime.execute()

    @start.command(hidden=True, help="Run a single task (internal).")
    @click.argument("step-name")
    @click.option("--run-id", required=True)
    @click.option("--task-id", required=True)
    @click.option("--input-paths", default=None)
    @click.option("--input-paths-any", default=None,
                  help="Candidate input paths of which exactly ONE exists "
                       "(the step after alternative switch branches — only "
                       "the taken branch's task is in the datastore).")
    @click.option("--join-inputs", default=None,
                  help="Join inputs as '<run>/<step>/<task-id base>:<json "
                       "index list>' — expands to that step's deterministic "
                       "per-split task ids (used by compiled Argo workflows, "
                       "where the scheduler isn't around to enumerate "
                       "arrivals).")
    @click.option("--join-inputs-control", default=None,
                  help="Gang-join inputs: pathspec of the control task; its "
                       "recorded _control_mapper_tasks become the inputs.")
    @click.option("--split-index", default=None)
    @click.option("--retry-count", default=0)
    @click.option("--max-user-code-retries", default=0)
    @click.option("--namespace", "user_namespace", default=None)
    @click.option("--ubf-context", default=None)
    @click.option("--origin-run-id", default=None)
    @click.option("--params-json", default=None)
    @click.option("--params-from-env", default=None,
                  help="Read parameter values from environment variables "
                       "named <prefix><param> (JSON-encoded values). Used "
                       "by compiled Argo workflows: env injection is "
                       "shell-safe where argv templating is not.")
    @click.option("--argo-output-dir", default=None,
                  help="Directory to drop Argo output-parameter files into "
                       "after the task finishes (num-splits, next-step).")
    @click.option("--argo-iteration", default=None,
                  help="Recursive-switch loop iteration counter (compiled "
                       "Argo loop templates only): written back as the "
                       "iter-next output parameter = iteration + 1.")
    @click.pass_obj
    def step(state, step_name, run_id, task_id, input_paths, split_index,
             retry_count, max_user_code_retries, user_namespace, ubf_context,
             origin_run_id, params_json, params_from_env, input_paths_any,
             join_inputs, join_inputs_control, argo_output_dir,
             argo_iteration):
        _finalize(state)
        os.environ[STEP_ARGV_ENV] = json.dumps(sys.argv)
        if ubf_context not in (None, "", "none"):
            ubf = ubf_context
        else:
            ubf = None
        if params_from_env and not params_json:
            values = {}
            for name, _param in flow._get_parameters():
                raw = os.environ.get(params_from_env + name)
                if raw is not None:
                    values[name] = json.loads(raw)
            params_json = json.dumps(values)
        paths = decompress_list(input_paths) if input_paths else []
        if input_paths_any:
            existing = []
            for cand in decompress_list(input_paths_any):
                c_run, c_step, c_task = cand.split("/")
                ds = state.flow_datastore.get_task_datastore(
                    c_run, c_step, c_task, allow_not_done=True
                )
                if ds.is_done():
                    existing.append(cand)
            if len(existing) != 1:
                raise TpuFlowException(
                    "Expected exactly one completed input among %s, found "
                    "%s." % (input_paths_any, existing or "none")
                )
            paths += existing
        if join_inputs:
            # '<run>/<step>/<task-id base>:<json index list>' — the base
            # carries the enclosing foreach's compound split path for
            # nested fan-outs ('leaf-2' joins leaf-2-0, leaf-2-1, ...)
            prefix, _, indices = join_inputs.rpartition(":")
            j_run, j_step, j_base = prefix.split("/")
            paths += [
                "%s/%s/%s-%d" % (j_run, j_step, j_base, int(i))
                for i in json.loads(indices)
            ]
        if join_inputs_control:
            ctl_run, ctl_step, ctl_task = join_inputs_control.split("/")
            ctl_ds = state.flow_datastore.get_task_datastore(
                ctl_run, ctl_step, ctl_task
            )
            paths += [
                "/".join(ps.split("/")[-3:])
                for ps in ctl_ds["_control_mapper_tasks"]
            ]

        # task heartbeat: mtime-based liveness, 10s cadence
        state.metadata.start_task_heartbeat(flow.name, run_id, step_name, task_id)
        beat_stop = threading.Event()

        def beats():
            while not beat_stop.wait(10):
                state.metadata.heartbeat()

        beat_thread = threading.Thread(target=beats, daemon=True)
        beat_thread.start()

        task = MetaflowTask(
            state.flow,
            state.flow_datastore,
            state.metadata,
            console_logger=echo,
            ubf_context=ubf,
        )
        try:
            task.run_step(
                step_name,
                run_id,
                task_id,
                origin_run_id=origin_run_id,
                input_paths=paths,
                split_index=int(split_index) if split_index not in (None, "") else None,
                retry_count=int(retry_count),
                max_user_code_retries=int(max_user_code_retries),
                namespace=user_namespace,
                parameters_json=params_json,
                num_parallel=0,
            )
            if argo_output_dir:
                _write_argo_outputs(state, argo_output_dir, run_id, step_name,
                                    task_id, iteration=argo_iteration)
        finally:
            beat_stop.set()

    @start.command(help="Re-run ONE task of a past run against its recorded "
                        "inputs (fast dev loop).")
    @click.argument("step-name")
    @click.option("--run-id", default=None, help="Origin run (default: latest)")
    @click.option("--task-id", default=None,
                  help="Origin task (default: first task of the step)")
    @click.pass_obj
    def spin(state, step_name, run_id, task_id):
        import time as _time

        _finalize(state)

        origin_run = run_id or read_latest_run_id(flow.name)
        if origin_run is None:
            raise TpuFlowException("No previous run to spin from.")
        if step_name not in state.graph:
            raise TpuFlowException("Step *%s* does not exist." % step_name)
        if state.graph[step_name].parallel_step:
            raise TpuFlowException("spin does not support gang steps.")
        if task_id is None:
            tasks = state.flow_datastore.list_tasks(origin_run, step_name)
            if not tasks:
                raise TpuFlowException(
                    "No task of step *%s* found in run %s."
                    % (step_name, origin_run)
                )
            task_id = sorted(tasks)[0]
        # recorded inputs from the origin task's metadata
        meta = state.metadata.get_task_metadata(
            flow.name, origin_run, step_name, task_id
        )
        input_paths = []
        for m in meta:
            if m.get("field_name") == "input-paths":
                input_paths = json.loads(m["value"])
        spin_run_id = "spin-%d" % int(_time.time() * 1000)
        state.metadata.register_run_id(spin_run_id, sys_tags=["spin"])
        echo("Spinning %s/%s/%s as run %s"
             % (origin_run, step_name, task_id, spin_run_id))
        origin_ds = state.flow_datastore.get_task_datastore(
            origin_run, step_name, task_id
        )
        split_index = None
        stack = origin_ds.get("_foreach_stack")
        if stack:
            split_index = stack[-1][1]
        # the start step has no input task: replay the origin's parameters
        params_json = None
        if step_name == "start":
            params = {
                name: origin_ds[name]
                for name in origin_ds.get("_parameter_names") or []
                if name in origin_ds
            }
            params_json = json.dumps(params)
        task = MetaflowTask(
            state.flow, state.flow_datastore, state.metadata,
            console_logger=echo,
        )
        task.run_step(
            step_name, spin_run_id, "1",
            origin_run_id=origin_run,
            input_paths=input_paths,
            split_index=split_index,
            parameters_json=params_json,
        )
        echo("Spin task done: %s/%s/1 — inspect with dump %s/%s/1"
             % (spin_run_id, step_name, spin_run_id, step_name))

    @start.group(help="Mutate run tags.")
    def tag():
        pass

    @tag.command(name="add")
    @click.option("--run-id", default=None)
    @click.argument("tags", nargs=-1, required=True)
    @click.pass_obj
    def tag_add(state, run_id, tags):
        run_id = run_id or read_latest_run_id(flow.name)
        info = state.metadata.mutate_run_tags(flow.name, run_id, add=tags)
        if info is None:
            raise TpuFlowException("Run %s not found" % run_id)
        echo("Tags of %s/%s: %s" % (flow.name, run_id,
                                    ", ".join(info["tags"])))

    @tag.command(name="remove")
    @click.option("--run-id", default=None)
    @click.argument("tags", nargs=-1, required=True)
    @click.pass_obj
    def tag_remove(state, run_id, tags):
        run_id = run_id or read_latest_run_id(flow.name)
        info = state.metadata.mutate_run_tags(flow.name, run_id, remove=tags)
        if info is None:
            raise TpuFlowException("Run %s not found" % run_id)
        echo("Tags of %s/%s: %s" % (flow.name, run_id,
                                    ", ".join(info["tags"])))

    @tag.command(name="list")
    @click.option("--run-id", default=None)
    @click.pass_obj
    def tag_list(state, run_id):
        run_id = run_id or read_latest_run_id(flow.name)
        info = state.metadata.get_run_info(flow.name, run_id)
        if info is None:
            raise TpuFlowException("Run %s not found" % run_id)
        for t in info.get("tags", []):
            echo(t)

    @start.group(help="Inspect task cards.")
    def card():
        pass

    @card.command(name="get", help="Print the card HTML of a task.")
    @click.argument("pathspec")
    @click.option("--type", "card_type", default="default")
    @click.pass_obj
    def card_get(state, pathspec, card_type):
        from .plugins.cards.card_decorator import card_path

        run_id, step_name, task_id = _parse_task_pathspec(pathspec)
        path = card_path(state.flow_datastore.storage, flow.name, run_id,
                         step_name, task_id, card_type)
        with state.flow_datastore.storage.load_bytes([path]) as loaded:
            for _p, local, _m in loaded:
                if local is None:
                    raise TpuFlowException(
                        "No card found for %s (type=%s)" % (pathspec,
                                                            card_type)
                    )
                with open(local) as f:
                    print(f.read())

    @card.command(name="server", help="Serve cards over HTTP for browsing.")
    @click.option("--port", default=8324)
    @click.pass_obj
    def card_server(state, port):
        import http.server

        if state.flow_datastore.ds_type != "local":
            raise TpuFlowException(
                "card server currently serves local datastores only; for "
                "remote stores use 'card get' (reads via the storage "
                "abstraction)."
            )
        root = state.flow_datastore.storage.datastore_root
        cards_root = os.path.join(root, flow.name, "mf.cards")

        class Handler(http.server.SimpleHTTPRequestHandler):
            def __init__(self, *a, **kw):
                super().__init__(*a, directory=cards_root, **kw)

            def log_message(self, *args):
                pass

        echo("Serving cards of %s on http://127.0.0.1:%d (run/step/task/"
             "default.html)" % (flow.name, port))
        http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler
                                        ).serve_forever()

    @card.command(name="list", help="List cards of a task.")
    @click.argument("pathspec")
    @click.pass_obj
    def card_list(state, pathspec):
        run_id, step_name, task_id = _parse_task_pathspec(pathspec)
        prefix = state.flow_datastore.storage.path_join(
            flow.name, "mf.cards", run_id, step_name, task_id
        )
        for path, is_file in state.flow_datastore.storage.list_content(
            [prefix]
        ):
            if is_file:
                echo(state.flow_datastore.storage.basename(path))

    @start.group(name="argo-workflows",
                 help="Compile/deploy the flow to Argo Workflows (GKE TPU).")
    def argo_workflows():
        pass

    @argo_workflows.command(name="create")
    @click.option("--image", default=None, help="Container image.")
    @click.option("--k8s-namespace", default="default")
    @click.option("--only-json/--deploy", default=True,
                  help="Print manifests instead of applying them.")
    @click.option("--package/--no-package", "do_package", default=False,
                  help="Build+upload the code package first.")
    @click.pass_obj
    def argo_create(state, image, k8s_namespace, only_json, do_package,
                    **param_kwargs):
        from .plugins.argo import ArgoWorkflows

        _finalize(state)
        # deploy-time parameter values become the workflow's defaults
        deploy_params, _ = _collect_params(flow, param_kwargs)

        package_url = None
        if do_package:
            from .package import MetaflowPackage

            pkg = MetaflowPackage.for_flow(flow)
            package_url, sha = pkg.upload(state.flow_datastore)
            echo("Code package uploaded: %s (sha %s)" % (package_url,
                                                         sha[:12]))
        from .metaflow_config import service_url as _service_url

        compiler = ArgoWorkflows(
            state.flow, state.graph, package_url=package_url, image=image,
            namespace=k8s_namespace,
            datastore=state.datastore_type,
            datastore_root=(state.datastore_root_explicit
                            or (None if state.datastore_type == "local"
                                else state.flow_datastore.ds_root)),
            metadata=state.metadata_type,
            service_url=_service_url(),
            parameters=deploy_params,
        )
        manifests = [
            compiler.compile(),
            compiler.compile_cron(),
            compiler.compile_sensor(),
        ]
        output = compiler.to_yaml(manifests)
        if only_json:
            print(output)
        else:
            raise TpuFlowException(
                "Direct deploy needs kubectl/cluster access: pipe the "
                "manifests to 'kubectl apply -f -' instead (re-run with "
                "--only-json)."
            )

    argo_create.params.extend(_param_options(flow))

    @start.command(name="argo-exit-hook", hidden=True,
                   help="Run @exit_hook callables (Argo onExit handler).")
    @click.option("--status", required=True,
                  help="Argo {{workflow.status}}: Succeeded/Failed/Error.")
    @click.option("--run-id", required=True)
    @click.pass_obj
    def argo_exit_hook(state, status, run_id):
        success = status == "Succeeded"
        decos = getattr(flow, "_flow_decorators", {}).get("exit_hook", [])
        for deco in decos:
            deco.run_hooks(success, "%s/%s" % (flow.name, run_id), echo)
        # the onExit handler is also where a deployed run announces its
        # completion (reference: argo_events publish from the workflow's
        # final templates) — webhook when TPUFLOW_ARGO_EVENTS_URL is set,
        # local JSONL bus otherwise
        if success:
            from .events import publish_run_finished

            publish_run_finished(flow, run_id)

    @start.command(name="list-triggers", hidden=True,
                   help="Print the event names this flow subscribes to.")
    def list_triggers():
        from .events import subscribed_event_names

        print(json.dumps(subscribed_event_names(flow)))

    @start.command(help="Show the live status of a run (heartbeats, "
                        "attempts, durations).")
    @click.option("--run-id", default=None)
    @click.pass_obj
    def status(state, run_id):
        run_id = run_id or read_latest_run_id(flow.name)
        if run_id is None:
            raise TpuFlowException("No run found for %s." % flow.name)
        info = state.metadata.get_run_info(flow.name, run_id)
        if info is None:
            raise TpuFlowException("Run %s not found." % run_id)
        echo("Run %s/%s (user %s, tags: %s)"
             % (flow.name, run_id, info.get("user"),
                ", ".join(info.get("tags", [])) or "-"))
        # live scheduler snapshot, when one was persisted (runtime.py
        # _persist_runstate): shows in-flight state metadata can't
        try:
            rs = state.flow_datastore.load_runstate(run_id)
        except Exception:
            rs = None
        if rs:
            import time as _time

            echo(
                "  scheduler: %d queued, %d active, %d done"
                " (snapshot %.0fs ago)%s"
                % (
                    len(rs.get("queued", [])),
                    len(rs.get("active", [])),
                    rs.get("finished_tasks", 0),
                    max(0, _time.time() - rs.get("ts", 0)),
                    " FAILED" if rs.get("failed") else "",
                )
            )
        for step_name in state.flow_datastore.list_steps(run_id):
            for task_id in sorted(
                state.flow_datastore.list_tasks(run_id, step_name)
            ):
                ds = state.flow_datastore.get_task_datastore(
                    run_id, step_name, task_id, allow_not_done=True
                )
                meta = {
                    m["field_name"]: m["value"]
                    for m in state.metadata.get_task_metadata(
                        flow.name, run_id, step_name, task_id
                    )
                }
                age = state.metadata.task_heartbeat_age(
                    flow.name, run_id, step_name, task_id
                )
                # progress beat (tasks running an instrumented train
                # loop stamp _progress.json every step): distinguishes
                # HUNG? (alive by heartbeat, stalled by progress) from
                # DEAD? (no heartbeat at all)
                from .progress import read_progress

                beat = read_progress(
                    get_tpuflow_root(), flow.name, run_id, step_name,
                    task_id)
                prog = ""
                if beat and not beat.get("done"):
                    import time as _time

                    page = _time.time() - float(beat.get("ts") or 0.0)
                    prog = " step=%s prog=%.0fs" % (
                        beat.get("step_num"), max(0, page))
                if ds.is_done():
                    word = "done"
                elif age is not None and age < 30:
                    # a live heartbeat wins over a prior attempt's failure
                    # record (a retry may be running right now)
                    word = "running"
                    deadline = float(
                        (beat or {}).get("deadline_s") or 0.0)
                    if (beat and not beat.get("done") and deadline > 0
                            and page > deadline):
                        word = ("HUNG? (no progress %.0fs, deadline %.0fs)"
                                % (page, deadline))
                elif meta.get("attempt_ok") == "false":
                    word = "FAILED"
                elif age is not None:
                    word = "DEAD? (no heartbeat %.0fs)" % age
                else:
                    word = "pending"
                duration = meta.get("duration-ms")
                extra = " %sms" % duration if duration else ""
                extra += prog
                echo("  %-20s %-8s attempt=%s%s"
                     % ("%s/%s" % (step_name, task_id), word,
                        ds.attempt if ds.has_attempt() else "-", extra))

    @start.command(help="Show a run's flight-recorder telemetry: per-task "
                        "durations, training tokens/sec + MFU aggregated "
                        "across gang ranks, slowest spans, captured "
                        "profiles (datastore-persisted; works after the "
                        "workers are gone).")
    @click.argument("run-id", required=False)
    @click.option("--json", "as_json", is_flag=True,
                  help="Emit the aggregation as JSON.")
    @click.option("--timeline", is_flag=True,
                  help="Per-train-step wall/tokens-per-sec/MFU series.")
    @click.option("--spans", default=0, type=int,
                  help="Show the N slowest timer spans of the run.")
    @click.option("--step", "step_filter", default=None,
                  help="Only records from this flow step.")
    @click.option("--rank", "rank_filter", default=None, type=int,
                  help="Only records from this gang rank.")
    @click.pass_obj
    def metrics(state, run_id, as_json, timeline, spans, step_filter,
                rank_filter):
        from .cmd.metrics import show_metrics

        run_id = run_id or read_latest_run_id(flow.name)
        if run_id is None:
            raise TpuFlowException("No run found for %s." % flow.name)
        show_metrics(state.flow_datastore, run_id, as_json=as_json,
                     timeline=timeline, spans=spans, step=step_filter,
                     rank=rank_filter, echo=print)

    @start.command(help="Garbage-collect old runs (keep the newest N) and "
                        "unreferenced CAS blobs.")
    @click.option("--keep", default=5, show_default=True,
                  help="How many most-recent runs to keep.")
    @click.option("--dry-run/--delete", default=True,
                  help="Only report what would be removed (default).")
    @click.pass_obj
    def gc(state, keep, dry_run):
        import shutil

        if state.flow_datastore.ds_type != "local":
            raise TpuFlowException("gc currently supports local datastores.")
        root = state.flow_datastore.ds_root
        flow_dir = os.path.join(root, flow.name)
        runs = sorted(
            (r for r in state.flow_datastore.list_runs()
             if not r.startswith("spin-")),
            key=lambda r: os.path.getmtime(os.path.join(flow_dir, r)),
        )
        doomed = runs[:-keep] if keep else runs
        kept = [r for r in runs if r not in doomed]

        # never sweep while a run is alive: an executing task's blobs are
        # unreferenced until its manifest lands
        import time as _t

        for run_id in runs:
            age = None
            hb = os.path.join(flow_dir, run_id, "_heartbeat.json")
            try:
                age = _t.time() - os.path.getmtime(hb)
            except OSError:
                pass
            if age is None or age >= 60:
                continue
            # fresh heartbeat on a COMPLETED run is fine (the scheduler
            # beats once more on exit); only refuse for unfinished runs
            end_done = any(
                state.flow_datastore.get_task_datastore(
                    run_id, "end", t, mode="d", allow_not_done=True
                ).is_done()
                for t in state.flow_datastore.list_tasks(run_id, "end")
            )
            if not end_done:
                raise TpuFlowException(
                    "Run %s looks alive (heartbeat %.0fs ago) — rerun gc "
                    "after it finishes." % (run_id, age)
                )

        # registry pruning cutoff: packages registered before the oldest
        # kept run started belonged to doomed runs
        oldest_kept_ts = min(
            (os.path.getmtime(os.path.join(flow_dir, r)) for r in kept),
            default=0,
        )

        # mark: every CAS key referenced by ANY attempt manifest of a kept
        # run (earlier attempts stay readable), plus still-registered raw
        # data (code packages, include files)
        import json as _json

        live = set(
            state.flow_datastore.registered_data_keys(
                newer_than=oldest_kept_ts if doomed else None
            )
        )
        keep_runs = kept + [r for r in state.flow_datastore.list_runs()
                            if r.startswith("spin-")]
        for run_id in keep_runs:
            run_dir = os.path.join(flow_dir, run_id)
            for dirpath, _dirs, files in os.walk(run_dir):
                for name in files:
                    if not name.endswith(".artifacts.json"):
                        continue
                    try:
                        with open(os.path.join(dirpath, name)) as f:
                            manifest = _json.load(f)
                        live.update(manifest.get("objects", {}).values())
                    except (OSError, ValueError):
                        continue
        # async-checkpoint manifests (<flow>/_checkpoints/<name>/
        # step_N.json) reference CAS blobs too — their snapshots must
        # survive the sweep or restore() finds a manifest over a hole
        ckpt_dir = os.path.join(flow_dir, "_checkpoints")
        for dirpath, _dirs, files in os.walk(ckpt_dir):
            for name in files:
                if not (name.startswith("step_")
                        and name.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(dirpath, name)) as f:
                        manifest = _json.load(f)
                    if manifest.get("key"):
                        live.add(manifest["key"])
                except (OSError, ValueError):
                    continue
        # sweep: blobs not referenced by any kept run
        data_dir = os.path.join(flow_dir, "data")
        dead_blobs = []
        for dirpath, _dirs, files in os.walk(data_dir):
            for name in files:
                if name not in live:
                    dead_blobs.append(os.path.join(dirpath, name))

        verb = "would remove" if dry_run else "removing"
        echo("%s %d run(s): %s" % (verb, len(doomed),
                                   ", ".join(doomed) or "-"))
        echo("%s %d unreferenced blob(s)" % (verb, len(dead_blobs)))
        if not dry_run:
            for run_id in doomed:
                shutil.rmtree(os.path.join(flow_dir, run_id),
                              ignore_errors=True)
            for path in dead_blobs:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if doomed and oldest_kept_ts:
                state.flow_datastore.prune_registered_data_keys(
                    older_than=oldest_kept_ts
                )
            echo("gc done (%d runs kept)" % len(kept))

    @start.command(help="Validate the flow graph. --deep adds artifact "
                        "dataflow + SPMD config analysis; exits non-zero "
                        "on any error-severity finding.")
    @click.option("--deep", is_flag=True,
                  help="Run the artifact dataflow and SPMD config "
                       "analyzers on top of the graph lint.")
    @click.option("--json", "as_json", is_flag=True,
                  help="Emit a machine-readable report (schema pinned in "
                       "tests/schema_validate.py).")
    @click.pass_obj
    def check(state, deep, as_json):
        from .analysis import ERROR, AnalysisReport, Finding, analyze_flow
        from .lint import LintWarn, linter

        report = AnalysisReport(flow.name)
        report.analyses.append("lint")
        lint_ok = True
        try:
            _finalize(state)
        except LintWarn as ex:
            lint_ok = False
            report.add(Finding(
                "lint", ERROR, ex.message,
                lineno=ex.lineno, source_file=ex.source_file))
        report.checks_run += len(linter._checks)
        graph = state.graph or flow._graph
        report.steps_analyzed = list(graph.sorted_nodes())
        if deep and lint_ok:
            # a graph that fails shape lint has no reliable dataflow
            report.merge(analyze_flow(flow.__class__, graph))
        if as_json:
            echo(json.dumps(report.to_dict(), indent=2))
        else:
            echo("Validating your flow...")
            for line in report.render_lines():
                echo("    %s" % line)
            if report.ok:
                echo("    The graph looks good!")
        if not report.ok:
            sys.exit(1)

    @start.command(help="Show the structure of the flow.")
    @click.pass_obj
    def show(state):
        _finalize(state)
        echo("\n%s\n" % (state.graph.doc or flow.name))
        for name in state.graph.sorted_nodes():
            node = state.graph[name]
            echo("Step *%s* (%s)" % (name, node.type))
            if node.doc:
                echo("    %s" % node.doc)
            if node.type == "end":
                echo("    => done")
            else:
                extra = ""
                if node.type == "foreach":
                    extra = " (foreach over '%s')" % node.foreach_param
                elif node.type == "split-parallel":
                    extra = " (gang)"
                elif node.type == "split-switch":
                    extra = " (switch on '%s')" % node.condition
                echo("    => %s%s" % (", ".join(node.out_funcs), extra))

    @start.command(name="output-dot", help="Print the DAG in DOT format.")
    @click.pass_obj
    def output_dot(state):
        _finalize(state)
        print(state.graph.output_dot())

    @start.command(help="Dump artifacts of a task: dump RUN/STEP/TASK")
    @click.argument("pathspec")
    @click.option("--private/--no-private", default=False,
                  help="Include internal (underscore) artifacts.")
    @click.option("--max-value-size", default=1000)
    @click.pass_obj
    def dump(state, pathspec, private, max_value_size):
        run_id, step_name, task_id = _parse_task_pathspec(pathspec)
        ds = state.flow_datastore.get_task_datastore(run_id, step_name, task_id)
        for name, value in sorted(ds.to_dict(show_private=private).items()):
            rep = repr(value)
            if len(rep) > max_value_size:
                rep = rep[:max_value_size] + "..."
            print("%s = %s" % (name, rep))

    @start.command(help="Show logs of a task: logs RUN/STEP/TASK. "
                        "--scrub PERMANENTLY replaces the stored stream "
                        "with a scrub marker (leaked secrets, PII).")
    @click.argument("pathspec")
    @click.option("--stderr/--stdout", default=False)
    @click.option("--scrub", is_flag=True,
                  help="Overwrite the selected stream's persisted content "
                       "instead of showing it.")
    @click.pass_obj
    def logs(state, pathspec, stderr, scrub):
        run_id, step_name, task_id = _parse_task_pathspec(pathspec)
        ds = state.flow_datastore.get_task_datastore(
            run_id, step_name, task_id, allow_not_done=True
        )
        name = "stderr" if stderr else "stdout"
        from . import mflog

        if scrub:
            # EVERY attempt: failed attempts persist logs too, and a
            # leaked secret usually predates the successful retry
            from .datastore import MAX_ATTEMPTS

            marker = mflog.decorate(b"runtime", b"[log content scrubbed]")
            scrubbed = []
            for attempt in range(MAX_ATTEMPTS):
                att_ds = state.flow_datastore.get_task_datastore(
                    run_id, step_name, task_id, attempt=attempt,
                    allow_not_done=True,
                )
                if att_ds.load_log_legacy("runtime", name):
                    att_ds.save_logs("runtime", {name: marker})
                    scrubbed.append(attempt)
            echo("scrubbed %s of %s/%s/%s (attempts: %s)"
                 % (name, run_id, step_name, task_id,
                    ", ".join(map(str, scrubbed)) or "none"))
            return
        data = ds.load_log_legacy("runtime", name)
        sys.stdout.write(
            mflog.format_merged([data]).decode("utf-8", errors="replace")
        )

    # commands contributed by metaflow_tpu_extensions.* packages
    from .extension_support import CLI_COMMANDS as _ext_commands

    for _cmd in _ext_commands:
        start.add_command(_cmd)

    return start


def main(flow, args=None):
    state = CliState(flow)
    start = make_cli(flow, state)

    try:
        start(args=args, standalone_mode=False, obj=state)
    except click.exceptions.ClickException as ex:
        ex.show()
        sys.exit(ex.exit_code)
    except TpuFlowException as ex:
        sys.stderr.write("%s: %s\n" % (ex.headline, str(ex)))
        if knobs.get_bool("TPUFLOW_DEBUG"):
            traceback.print_exc()
        sys.exit(1)
    except click.exceptions.Abort:
        sys.exit(1)
