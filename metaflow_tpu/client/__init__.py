"""Client (inspection) API: Metaflow → Flow → Run → Step → Task → DataArtifact.

Reference behavior: metaflow/client/core.py (object hierarchy, namespace
filtering `namespace():154`, `Run.data`, `Task.artifacts`). Reads go through
the same FlowDataStore/metadata providers the runtime writes with.
"""

import json
import os

from ..datastore import FlowDataStore, LocalStorage, STORAGE_BACKENDS
from ..exception import (
    MetaflowNamespaceMismatch,
    MetaflowNotFound,
    MetaflowTaggingError,
)
from ..metadata import LocalMetadataProvider
from ..util import get_tpuflow_root, get_username

_current_namespace = None
_namespace_initialized = False


def default_namespace():
    global _current_namespace, _namespace_initialized
    _current_namespace = "user:%s" % get_username()
    _namespace_initialized = True
    return _current_namespace


def namespace(ns):
    """Set the global namespace filter; None disables filtering."""
    global _current_namespace, _namespace_initialized
    _current_namespace = ns
    _namespace_initialized = True
    return _current_namespace


def get_namespace():
    if not _namespace_initialized:
        default_namespace()
    return _current_namespace


def _metadata_provider():
    from ..metaflow_config import default_metadata

    if default_metadata() == "service":
        from ..metadata import ServiceMetadataProvider

        return ServiceMetadataProvider()
    return LocalMetadataProvider()


def _flow_datastore(flow_name):
    from ..metaflow_config import default_datastore

    ds_type = default_datastore()
    # FlowDataStore auto-attaches the shared on-disk blob cache for
    # remote storage (read-through for the client, write-through for
    # tasks) — no client-side special case needed anymore
    return FlowDataStore(flow_name, STORAGE_BACKENDS[ds_type])


class MetaflowObject(object):
    _NAME = "base"

    def __init__(self, pathspec=None, _namespace_check=True):
        self.pathspec = pathspec
        self._check_ns = _namespace_check

    def _check_namespace(self, tags):
        ns = get_namespace()
        if ns is None or not self._check_ns:
            return
        if ns not in tags:
            raise MetaflowNamespaceMismatch(ns)

    def __repr__(self):
        return "%s('%s')" % (self.__class__.__name__, self.pathspec)

    def __eq__(self, other):
        return (
            isinstance(other, self.__class__) and self.pathspec == other.pathspec
        )

    def __hash__(self):
        return hash((self.__class__.__name__, self.pathspec))


class Metaflow(object):
    """Entry point: all flows in the datastore."""

    @property
    def flows(self):
        root = get_tpuflow_root()
        if not os.path.isdir(root):
            return []
        out = []
        for name in sorted(os.listdir(root)):
            if os.path.isdir(os.path.join(root, name)):
                try:
                    out.append(Flow(name))
                except (MetaflowNotFound, MetaflowNamespaceMismatch):
                    pass
        return out

    def __iter__(self):
        return iter(self.flows)

    def __repr__(self):
        return "Metaflow()"


class Flow(MetaflowObject):
    _NAME = "flow"

    def __init__(self, name, _namespace_check=True):
        super().__init__(name, _namespace_check)
        self.id = name
        root = os.path.join(get_tpuflow_root(), name)
        if not os.path.isdir(root):
            raise MetaflowNotFound("Flow *%s* does not exist" % name)

    @property
    def runs(self):
        return list(self)

    def __iter__(self):
        meta = _metadata_provider()
        for info in meta.list_runs(self.id):
            try:
                yield Run(
                    "%s/%s" % (self.id, info["run_number"]),
                    _namespace_check=self._check_ns,
                )
            except MetaflowNamespaceMismatch:
                continue

    @property
    def latest_run(self):
        for run in self:
            return run
        return None

    @property
    def latest_successful_run(self):
        for run in self:
            if run.successful:
                return run
        return None

    def __getitem__(self, run_id):
        return Run("%s/%s" % (self.id, run_id), _namespace_check=self._check_ns)


class Run(MetaflowObject):
    _NAME = "run"

    def __init__(self, pathspec, _namespace_check=True):
        super().__init__(pathspec, _namespace_check)
        parts = pathspec.split("/")
        if len(parts) != 2:
            raise MetaflowNotFound("Specify a run as FlowName/run_id")
        self.flow_name, self.id = parts
        self._meta = _metadata_provider()
        info = self._meta.get_run_info(self.flow_name, self.id)
        if info is None:
            raise MetaflowNotFound("Run *%s* does not exist" % pathspec)
        self._info = info
        self._check_namespace(
            set(info.get("tags", [])) | set(info.get("system_tags", []))
        )
        self._ds = _flow_datastore(self.flow_name)

    @property
    def tags(self):
        return frozenset(self._info.get("tags", []))

    @property
    def system_tags(self):
        return frozenset(self._info.get("system_tags", []))

    # ---- tag mutation (reference: client/core.py Run.add_tag region) ----
    # same optimistic-concurrency provider path as the `tag` CLI, so
    # client and CLI mutations compose safely under concurrency

    def _mutate_tags(self, add=(), remove=()):
        add, remove = list(add), list(remove)  # generators: consume once
        for t in add + remove:
            if not isinstance(t, str):
                raise MetaflowTaggingError(
                    "Tags must be strings, got %r" % (t,)
                )
        info = self._meta.mutate_run_tags(
            self.flow_name, self.id, add=add, remove=remove
        )
        if info is None:
            raise MetaflowNotFound(
                "Run %s/%s disappeared while mutating tags"
                % (self.flow_name, self.id)
            )
        self._info = info
        return self.tags

    def add_tag(self, tag):
        """Add one user tag to this run."""
        return self._mutate_tags(add=[tag])

    def add_tags(self, tags):
        """Add several user tags to this run."""
        return self._mutate_tags(add=tags)

    def remove_tag(self, tag):
        """Remove one user tag from this run."""
        return self._mutate_tags(remove=[tag])

    def remove_tags(self, tags):
        """Remove several user tags from this run."""
        return self._mutate_tags(remove=tags)

    def replace_tag(self, tag_to_remove, tag_to_add):
        """Atomically swap one tag for another (one provider round-trip,
        so concurrent mutators never observe the intermediate state)."""
        return self._mutate_tags(add=[tag_to_add], remove=[tag_to_remove])

    def replace_tags(self, tags_to_remove, tags_to_add):
        """Atomically swap several tags."""
        return self._mutate_tags(add=tags_to_add, remove=tags_to_remove)

    @property
    def created_at(self):
        return self._info.get("ts_epoch")

    def steps(self):
        for name in self._ds.list_steps(self.id):
            yield Step("%s/%s/%s" % (self.flow_name, self.id, name),
                       _namespace_check=False)

    def __iter__(self):
        return self.steps()

    def __getitem__(self, step_name):
        if step_name not in self._ds.list_steps(self.id):
            raise MetaflowNotFound(
                "Step *%s* does not exist in run %s" % (step_name, self.pathspec)
            )
        return Step("%s/%s/%s" % (self.flow_name, self.id, step_name),
                    _namespace_check=False)

    @property
    def finished(self):
        try:
            return self["end"].task.finished
        except MetaflowNotFound:
            return False

    @property
    def successful(self):
        return self.finished

    @property
    def data(self):
        """Artifacts of the end task (the run's final state)."""
        try:
            return self["end"].task.data
        except MetaflowNotFound:
            return None

    def end_task(self):
        try:
            return self["end"].task
        except MetaflowNotFound:
            return None

    def lineage_index(self):
        """Reverse input-paths index: parent pathspec → [child pathspecs].
        Built in ONE pass over the run's task metadata (cached per Run)."""
        if getattr(self, "_lineage_index", None) is not None:
            return self._lineage_index
        index = {}
        meta = _metadata_provider()
        for step_name in self._ds.list_steps(self.id):
            for task_id in self._ds.list_tasks(self.id, step_name):
                records = meta.get_task_metadata(
                    self.flow_name, self.id, step_name, task_id
                )
                child = "%s/%s/%s" % (self.id, step_name, task_id)
                for m in records:
                    if m.get("field_name") == "input-paths":
                        for parent in json.loads(m["value"]):
                            index.setdefault(parent, []).append(child)
        self._lineage_index = index
        return index


class Step(MetaflowObject):
    _NAME = "step"

    def __init__(self, pathspec, _namespace_check=True):
        super().__init__(pathspec, _namespace_check)
        self.flow_name, self.run_id, self.id = pathspec.split("/")
        self._ds = _flow_datastore(self.flow_name)

    def tasks(self):
        for task_id in sorted(self._ds.list_tasks(self.run_id, self.id)):
            yield Task("%s/%s/%s/%s"
                       % (self.flow_name, self.run_id, self.id, task_id),
                       _namespace_check=False)

    def __iter__(self):
        return self.tasks()

    def __getitem__(self, task_id):
        return Task("%s/%s/%s/%s"
                    % (self.flow_name, self.run_id, self.id, task_id),
                    _namespace_check=False)

    @property
    def task(self):
        """Any one task of this step (the only one, for non-foreach steps)."""
        for task in self.tasks():
            return task
        raise MetaflowNotFound("Step %s has no tasks" % self.pathspec)

    @property
    def finished_at(self):
        return max((t.finished_at or 0) for t in self.tasks())

    @property
    def environment_info(self):
        return {}


class MetaflowData(object):
    """Attribute-style view over a task's artifacts."""

    def __init__(self, artifacts):
        self._artifacts = artifacts

    def __getattr__(self, name):
        arts = object.__getattribute__(self, "_artifacts")
        if name in arts:
            return arts[name].data
        raise AttributeError("No artifact '%s'" % name)

    def __contains__(self, var):
        return var in self._artifacts

    def _asdict(self):
        return {k: v.data for k, v in self._artifacts.items()}

    def __repr__(self):
        return "<MetaflowData: %s>" % ", ".join(sorted(self._artifacts))


class Task(MetaflowObject):
    _NAME = "task"

    def __init__(self, pathspec, _namespace_check=True):
        super().__init__(pathspec, _namespace_check)
        self.flow_name, self.run_id, self.step_name, self.id = pathspec.split("/")
        self._flow_ds = _flow_datastore(self.flow_name)
        self._task_ds = self._flow_ds.get_task_datastore(
            self.run_id, self.step_name, self.id, allow_not_done=True
        )
        if not self._task_ds.has_attempt():
            raise MetaflowNotFound("Task *%s* does not exist" % pathspec)

    @property
    def current_attempt(self):
        return self._task_ds.attempt

    @property
    def finished(self):
        return self._task_ds.is_done()

    @property
    def successful(self):
        meta = _metadata_provider().get_task_metadata(
            self.flow_name, self.run_id, self.step_name, self.id
        )
        oks = [
            m for m in meta if m.get("field_name") == "attempt_ok"
        ]
        if oks:
            try:
                return json.loads(oks[-1]["value"]) is True
            except (ValueError, TypeError):
                return False
        return self.finished

    @property
    def finished_at(self):
        meta = _metadata_provider().get_task_metadata(
            self.flow_name, self.run_id, self.step_name, self.id
        )
        ts = [m.get("ts_epoch") for m in meta if m.get("ts_epoch")]
        return max(ts) if ts else None

    @property
    def exception(self):
        ds = self._task_ds
        return ds.get("_exception_str")

    @property
    def artifacts(self):
        return MetaflowData(
            {
                name: DataArtifact(
                    "%s/%s" % (self.pathspec, name), _task_ds=self._task_ds
                )
                for name in self._task_ds.keys()
                if not name.startswith("_")
            }
        )

    @property
    def data(self):
        return self.artifacts

    def __getitem__(self, name):
        return DataArtifact("%s/%s" % (self.pathspec, name),
                            _task_ds=self._task_ds)

    def __iter__(self):
        for name in self._task_ds.keys():
            if not name.startswith("_"):
                yield self[name]

    @property
    def metadata_dict(self):
        meta = _metadata_provider().get_task_metadata(
            self.flow_name, self.run_id, self.step_name, self.id
        )
        return {m["field_name"]: m["value"] for m in meta}

    @property
    def index(self):
        stack = self._task_ds.get("_foreach_stack")
        if stack:
            return stack[-1][1]
        return None

    @property
    def stdout(self):
        return self._load_log("stdout")

    @property
    def stderr(self):
        return self._load_log("stderr")

    def _load_log(self, name):
        from .. import mflog

        data = self._task_ds.load_log_legacy("runtime", name)
        return mflog.format_merged([data]).decode("utf-8", errors="replace")

    @property
    def parent_tasks(self):
        meta = self.metadata_dict
        paths = meta.get("input-paths")
        if not paths:
            return []
        return [
            Task("%s/%s" % (self.flow_name, p), _namespace_check=False)
            for p in json.loads(paths)
        ]

    @property
    def child_tasks(self):
        """Tasks of this run whose recorded input-paths include this task.

        One metadata pass over the run per call; to traverse lineage for
        MANY tasks, build `Run.lineage_index()` once instead."""
        run = Run("%s/%s" % (self.flow_name, self.run_id),
                  _namespace_check=False)
        me = "%s/%s/%s" % (self.run_id, self.step_name, self.id)
        index = run.lineage_index()
        return [
            Task("%s/%s" % (self.flow_name, child), _namespace_check=False)
            for child in index.get(me, [])
        ]


class DataArtifact(MetaflowObject):
    _NAME = "artifact"

    def __init__(self, pathspec, _namespace_check=True, _task_ds=None):
        super().__init__(pathspec, _namespace_check)
        parts = pathspec.split("/")
        self.flow_name, self.run_id, self.step_name, self.task_id, self.id = parts
        if _task_ds is None:
            _task_ds = _flow_datastore(self.flow_name).get_task_datastore(
                self.run_id, self.step_name, self.task_id
            )
        self._task_ds = _task_ds
        if self.id not in self._task_ds:
            raise MetaflowNotFound("Artifact *%s* does not exist" % pathspec)

    @property
    def data(self):
        return self._task_ds[self.id]

    @property
    def size(self):
        info = self._task_ds.artifact_info(self.id)
        return info.get("size") if info else None

    @property
    def sha(self):
        return self._task_ds._objects.get(self.id)
