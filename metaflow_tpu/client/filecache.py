"""Shared on-disk blob cache (LRU by atime, size-capped).

Reference behavior: metaflow/client/filecache.py:44 — artifacts fetched from
remote storage are cached locally keyed by content hash; content addressing
makes entries immutable so invalidation is just eviction.

Beyond the reference this cache is shared read-through/write-through for
the whole datastore (FlowDataStore attaches it for remote storage): tasks
write artifacts through it on persist, and resumed/forked tasks plus
`load_artifacts` read locally-present keys from disk instead of GCS. The
`key_lock` hook gives the CAS in-flight dedup — N gang workers on one host
racing on the same blob serialize per key (fcntl across processes, a lock
table across threads) and N-1 of them resolve from the cache.
"""

import contextlib
import os
import tempfile
import threading

from .. import knobs


class FileCache(object):
    """Plugs into ContentAddressedStore.set_blob_cache."""

    def __init__(self, cache_dir=None, max_size=4 << 30):
        self._dir = cache_dir or knobs.get_str(
            "TPUFLOW_CLIENT_CACHE",
            fallback=os.path.join(tempfile.gettempdir(), "tpuflow_cache"),
        )
        self._max_size = max_size
        self._approx_total = None  # lazily initialized running size counter
        self._tlocks = {}  # key -> threading.RLock (in-process dedup)
        self._tlocks_mu = threading.Lock()
        self._held = {}  # key -> [fh|None, refcount] for reentrant flock
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, key):
        return os.path.join(self._dir, key[:2], key)

    def _thread_lock(self, key):
        with self._tlocks_mu:
            lk = self._tlocks.get(key)
            if lk is None:
                # bound the table: these locks only matter while a fetch
                # of that key is in flight — never drop entries whose
                # flock is currently held (self._held)
                if len(self._tlocks) > 4096:
                    self._tlocks = {k: v for k, v in self._tlocks.items()
                                    if k in self._held}
                lk = self._tlocks[key] = threading.RLock()
            return lk

    def key_lock(self, key):
        """Context manager serializing fetches of `key` across threads of
        this process AND across processes sharing the cache dir (fcntl).
        The CAS re-checks the cache under this lock, so concurrent gang
        workers download a missing blob once, not N times.

        REENTRANT per thread: load_blobs acquires every requested key's
        lock for the lifetime of its generator, so a consumer that
        triggers a nested load of an overlapping key from the same thread
        must not self-deadlock — the thread layer is an RLock and the
        flock layer refcounts (only the first acquire flocks, only the
        last release unlocks).

        BOUNDED, never deadlocking: both layers acquire with a timeout
        (LOCK_WAIT_SECS) and fall back to proceeding UNLOCKED on expiry.
        Nested loads across workers can order lock batches arbitrarily
        (per-call sorted order cannot rule out an A-B/B-A cycle between
        two generators' held sets), so an untimed flock could hang two
        gang workers forever; dedup is opportunistic — the worst case of
        the fallback is one duplicate download, kept correct by the
        sha-verified cache."""

        @contextlib.contextmanager
        def locked():
            rlock = self._thread_lock(key)
            if not rlock.acquire(timeout=self.LOCK_WAIT_SECS):
                yield  # degraded: duplicate download possible, no hang
                return
            try:
                # under the RLock this thread is the only one touching
                # self._held[key]
                entry = self._held.get(key)
                if entry is not None:
                    entry[1] += 1
                else:
                    entry = self._held[key] = [self._flock(key), 1]
                try:
                    yield
                finally:
                    entry[1] -= 1
                    if entry[1] == 0:
                        del self._held[key]
                        if entry[0] is not None:
                            entry[0].close()  # releases the flock
                            # unlink the sidecar so the cache dir doesn't
                            # grow one permanent file per key ever
                            # fetched. A waiter still holding the old
                            # inode's flock races a fresh opener onto a
                            # NEW inode — worst case one duplicate
                            # download (dedup is opportunistic; the
                            # sha-verified cache keeps it correct)
                            try:
                                os.unlink(self._path(key) + ".lock")
                            except OSError:
                                pass
            finally:
                rlock.release()

        return locked()

    # how long a fetch waits for another worker's in-flight download of
    # the same key before giving up on dedup and downloading itself
    LOCK_WAIT_SECS = 20.0

    def _flock(self, key):
        """Exclusive flock on the key's sidecar with a bounded wait;
        returns the open file handle, or None (degraded, no lock)."""
        import time

        path = self._path(key) + ".lock"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fh = open(path, "a+")
        except OSError:
            return None
        import fcntl

        deadline = time.monotonic() + self.LOCK_WAIT_SECS
        while True:
            try:
                fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return fh
            except OSError:
                if time.monotonic() >= deadline:
                    fh.close()
                    return None
                time.sleep(0.05)

    def load_key(self, key):
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        # the key IS the blob's sha256: verify before trusting — the cache
        # dir may be shared (e.g. /tmp), and these bytes feed pickle in
        # task processes. A mismatch (corruption or poisoning) is evicted
        # and treated as a miss.
        import hashlib

        if hashlib.sha256(data).hexdigest() != key:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return data

    def store_key(self, key, blob):
        # a blob near the cache cap would evict everything else on store
        # and often itself too — pass it through uncached
        if len(blob) * 4 > self._max_size:
            return
        path = self._path(key)
        if os.path.exists(path):
            # content-addressed: same key ⇒ same bytes. Re-storing would
            # add zero real bytes but inflate the running size counter
            # into spurious full-dir eviction walks (retried tasks and
            # gang workers re-store the same artifact sets constantly)
            try:
                os.utime(path)  # LRU touch
            except OSError:
                pass
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            # pid AND thread id: the persist pipeline calls store_key
            # from concurrent serialize workers — a pid-only suffix lets
            # two same-key writers interleave on one tmp file
            tmp = path + ".tmp.%d.%d" % (os.getpid(),
                                         threading.get_ident())
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            return
        if self._approx_total is None:
            self._approx_total = self._scan_total()
        else:
            self._approx_total += len(blob)
        if self._approx_total > self._max_size:
            self._evict()

    @staticmethod
    def _is_blob(name):
        # .lock files must survive eviction (unlinking one out from under
        # a holder breaks the cross-process dedup) and .tmp.* are races
        # in progress; neither counts against the budget
        return not (name.endswith(".lock") or ".tmp." in name)

    def _scan_total(self):
        total = 0
        for dirpath, _dirs, files in os.walk(self._dir):
            for name in files:
                if not self._is_blob(name):
                    continue
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    continue
        return total

    # a .tmp.* older than this is an orphan from a crashed writer (the
    # normal preemption failure mode), not a write in flight
    STALE_TMP_SECS = 3600.0

    def _evict(self):
        import time

        entries = []
        total = 0
        stale_cutoff = time.time() - self.STALE_TMP_SECS
        for dirpath, _dirs, files in os.walk(self._dir):
            for name in files:
                full = os.path.join(dirpath, name)
                if not self._is_blob(name):
                    # reap orphaned tmp files from SIGKILLed writers so
                    # the dir can't grow unbounded outside the budget;
                    # fresh ones are writes in flight — leave them
                    if ".tmp." in name:
                        try:
                            if os.stat(full).st_mtime < stale_cutoff:
                                os.unlink(full)
                        except OSError:
                            pass
                    continue
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((st.st_atime, st.st_size, full))
                total += st.st_size
        entries.sort()  # oldest atime first
        for _atime, size, full in entries:
            if total <= self._max_size:
                break
            try:
                os.unlink(full)
            except OSError:
                continue
            total -= size
        self._approx_total = total
