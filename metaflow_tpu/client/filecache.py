"""Client-side on-disk blob cache (LRU by atime, size-capped).

Reference behavior: metaflow/client/filecache.py:44 — artifacts fetched from
remote storage are cached locally keyed by content hash; content addressing
makes entries immutable so invalidation is just eviction.
"""

import os
import tempfile


class FileCache(object):
    """Plugs into ContentAddressedStore.set_blob_cache."""

    def __init__(self, cache_dir=None, max_size=4 << 30):
        self._dir = cache_dir or os.environ.get(
            "TPUFLOW_CLIENT_CACHE",
            os.path.join(tempfile.gettempdir(), "tpuflow_cache"),
        )
        self._max_size = max_size
        self._approx_total = None  # lazily initialized running size counter
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, key):
        return os.path.join(self._dir, key[:2], key)

    def load_key(self, key):
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        # the key IS the blob's sha256: verify before trusting — the cache
        # dir may be shared (e.g. /tmp), and these bytes feed pickle in
        # task processes. A mismatch (corruption or poisoning) is evicted
        # and treated as a miss.
        import hashlib

        if hashlib.sha256(data).hexdigest() != key:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return data

    def store_key(self, key, blob):
        # a blob near the cache cap would evict everything else on store
        # and often itself too — pass it through uncached
        if len(blob) * 4 > self._max_size:
            return
        path = self._path(key)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            return
        if self._approx_total is None:
            self._approx_total = self._scan_total()
        else:
            self._approx_total += len(blob)
        if self._approx_total > self._max_size:
            self._evict()

    def _scan_total(self):
        total = 0
        for dirpath, _dirs, files in os.walk(self._dir):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    continue
        return total

    def _evict(self):
        entries = []
        total = 0
        for dirpath, _dirs, files in os.walk(self._dir):
            for name in files:
                full = os.path.join(dirpath, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((st.st_atime, st.st_size, full))
                total += st.st_size
        entries.sort()  # oldest atime first
        for _atime, size, full in entries:
            if total <= self._max_size:
                break
            try:
                os.unlink(full)
            except OSError:
                continue
            total -= size
        self._approx_total = total
