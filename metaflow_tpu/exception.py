"""Exception hierarchy for metaflow_tpu.

Behavior parity with the reference's MetaflowException family
(/root/reference/metaflow/exception.py) — a headline + body that the CLI
renders without a traceback for user-facing errors.
"""

import traceback


class TpuFlowException(Exception):
    headline = "Flow error"

    def __init__(self, msg="", lineno=None):
        self.message = msg
        self.line_no = lineno
        super().__init__()

    def __str__(self):
        prefix = "line %d: " % self.line_no if self.line_no else ""
        return "%s%s" % (prefix, self.message)


# Keep the reference-compatible alias so user code reads naturally.
MetaflowException = TpuFlowException


class ExternalCommandFailed(TpuFlowException):
    headline = "External command failed"


class InvalidDecoratorAttribute(TpuFlowException):
    headline = "Unknown decorator attribute"

    def __init__(self, deconame, attr, defaults):
        msg = (
            "Decorator '{deco}' does not support the attribute '{attr}'. "
            "These attributes are supported: {defaults}.".format(
                deco=deconame, attr=attr, defaults=", ".join(defaults)
            )
        )
        super().__init__(msg=msg)


class CommandException(TpuFlowException):
    headline = "Invalid command"


class ParameterFieldFailed(TpuFlowException):
    headline = "Parameter field failed"


class ParameterFieldTypeMismatch(TpuFlowException):
    headline = "Parameter type mismatch"


class MetaflowInvalidPathspec(TpuFlowException):
    headline = "Invalid pathspec"


class MetaflowTaggingError(TpuFlowException):
    headline = "Tag mutation failed"


class MetaflowNotFound(TpuFlowException):
    headline = "Object not found"


class MetaflowNamespaceMismatch(TpuFlowException):
    headline = "Object not in namespace"

    def __init__(self, namespace):
        msg = "Object not in namespace '%s'" % namespace
        super().__init__(msg=msg)


class MetaflowInternalError(TpuFlowException):
    headline = "Internal error"


class MetaflowUnknownUser(TpuFlowException):
    headline = "Unknown user"

    def __init__(self):
        msg = (
            "Could not determine your user name based on environment variables "
            "($USERNAME etc.)"
        )
        super().__init__(msg=msg)


class InvalidNextException(TpuFlowException):
    """Raised by FlowSpec.next() on a malformed transition; points at the
    user's line (reference behavior: metaflow/exception.py InvalidNextException)."""

    headline = "Invalid self.next() transition"

    def __init__(self, msg):
        tb = traceback.extract_stack()
        # Walk back past library frames to the user's next() call site.
        self.file, self.line_no = tb[0][:2]
        for frame in reversed(tb):
            if "metaflow_tpu" not in frame[0]:
                self.file, self.line_no = frame[:2]
                break
        super().__init__(msg=msg, lineno=self.line_no)


class TpuFlowDataMissing(TpuFlowException):
    headline = "Data missing"


class UnhandledInMergeArtifactsException(TpuFlowException):
    headline = "Unhandled artifacts in merge"

    def __init__(self, msg, unhandled):
        super().__init__(msg=msg)
        self.artifact_names = list(unhandled)


class MissingInMergeArtifactsException(TpuFlowException):
    headline = "Missing artifacts in merge"

    def __init__(self, msg, missing):
        super().__init__(msg=msg)
        self.artifact_names = list(missing)


class TaskPreempted(TpuFlowException):
    """The host received a preemption notice (spot/queued TPU capacity
    reclaim); the attempt fails retryably so the next attempt can resume
    from the last checkpoint."""

    headline = "Task preempted"
