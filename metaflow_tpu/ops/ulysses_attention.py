"""All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

The OTHER long-context strategy next to ring attention (SURVEY.md §5:
"ring attention or all-to-all sequence/context parallelism"): instead of
streaming K/V blocks around a ring (n-1 hops of [B, S/n, Hkv, D] each),
all-to-alls re-shard the activations from sequence-sharded to
HEAD-sharded — each device then runs ordinary full attention over the
ENTIRE sequence for H/n of the heads, and a final all-to-all restores
the sequence sharding (four all-to-alls total: q, k, v in, output out;
k/v move at their GQA width, so their two are Hkv/H the size of q's).

Trade-off vs ring (PAPERS.md: Ulysses vs ring/striped attention):
  - comm is dense single-shot collectives XLA schedules without ring
    attention's per-hop latency chain;
  - attention itself is UNSHARDED per head group, so any inner kernel
    (the pallas flash path included) runs at full sequence length —
    no per-block causal bookkeeping;
  - the head count must divide by the mesh axis (ring has no such
    constraint) and activations momentarily hold [B, S, H/n, D] — at
    extreme S, ring's O(S/n) residency wins; Ulysses wins while
    S·H/n fits HBM.

Parity with ring_attention's API: [B, S, H, D], S sharded over
`axis_name`, batch over data axes when present, causal supported, GQA
via minimal K/V head widening.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .attention import shard_map_novma


def ulysses_attention_sharded(mesh, axis_name="sequence", causal=True,
                              scale=None, impl="auto"):
    """Build the sharded fn for [B, S, H, D] inputs with S split over
    `axis_name` (batch over data/fsdp axes when the mesh has them).

    impl: 'auto' | 'flash' | 'flash_interpret' | 'xla' — the inner
    (full-sequence) attention; 'auto' picks flash when pallas is usable
    and the shapes satisfy the 128-block constraint, else xla.
    """
    n = dict(mesh.shape).get(axis_name, 1)

    def inner(q, k, v):
        from .attention import attention

        return attention(q, k, v, causal=causal, scale=scale, impl=impl)

    if n == 1:
        return inner

    def local(q, k, v):
        H = q.shape[2]
        if H % n:
            raise ValueError(
                "Ulysses needs heads %% mesh axis == 0 (H=%d, %s=%d); "
                "use ring_attention for indivisible head counts"
                % (H, axis_name, n)
            )

        def seq_to_heads(x):
            # [B, S/n, h, D] -> [B, S, h/n, D]
            return jax.lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )

        def heads_to_seq(x):
            # [B, S, h/n, D] -> [B, S/n, h, D]
            return jax.lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True
            )

        qg = seq_to_heads(q)
        # K/V cross at their GQA width: widen only as much as the
        # all-to-all split and the inner broadcast require (full
        # widening would inflate K/V comm + residency by H/Hkv)
        kw = _widen_kv_minimal(k, H, n)
        vw = _widen_kv_minimal(v, H, n)
        out = inner(qg, seq_to_heads(kw), seq_to_heads(vw))
        return heads_to_seq(out)

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes or None, axis_name, None, None)
    return shard_map_novma(local, mesh, in_specs=(spec, spec, spec),
                           out_specs=spec)


def _widen_kv_minimal(x, n_heads, n):
    """Repeat K/V heads to the SMALLEST count that (a) splits over the
    mesh axis and (b) still divides the query head count per device (so
    the inner attention's GQA broadcast stays valid)."""
    kv = x.shape[2]
    reps = 1
    while ((kv * reps) % n or n_heads % (kv * reps)) \
            and kv * reps < n_heads:
        reps += 1
    if reps == 1:
        return x
    return jnp.repeat(x, reps, axis=2)


def ulysses_attention(q, k, v, mesh, axis_name="sequence", causal=True,
                      scale=None, impl="auto"):
    return ulysses_attention_sharded(mesh, axis_name, causal, scale, impl)(
        q, k, v
    )
