"""Mixture-of-Experts: top-k router + dense einsum dispatch.

Expert-parallel path (SURVEY.md §5.7, Mixtral target): experts live on the
'expert' mesh axis. Dispatch uses one-hot einsums (MXU-friendly dense
matmuls, no dynamic gather/scatter — XLA turns the expert dimension into an
all-to-all when sharded). Capacity-dropping keeps shapes static for jit.
"""

import jax
import jax.numpy as jnp


def top_k_router(logits, num_experts, k, dtype=jnp.float32):
    """logits: [tokens, experts] → (weights [tokens, k], idx [tokens, k]).

    Softmax over the selected k (Mixtral convention)."""
    gate_logits, idx = jax.lax.top_k(logits, k)
    weights = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    return weights.astype(dtype), idx


def moe_ffn(x, router_w, w_gate, w_up, w_down, num_experts_per_tok=2,
            capacity_factor=None, activation=jax.nn.silu):
    """Token-choice MoE feed-forward.

    x:        [B, S, E]
    router_w: [E, num_experts]
    w_gate/w_up: [num_experts, E, F]; w_down: [num_experts, F, E]

    Dense dispatch: combine weights become a [tokens, experts] matrix and the
    expert computation is a batched einsum over the expert dim — sharded on
    the 'expert' mesh axis this becomes all-to-all + local expert matmuls.
    """
    B, S, E = x.shape
    num_experts = router_w.shape[1]
    tokens = x.reshape(B * S, E)

    router_logits = jnp.einsum(
        "te,en->tn", tokens.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    weights, idx = top_k_router(router_logits, num_experts,
                                num_experts_per_tok, dtype=x.dtype)

    # combine matrix: [tokens, experts], rows sum to 1 over selected experts
    one_hot = jax.nn.one_hot(idx, num_experts, dtype=x.dtype)  # [t, k, n]
    combine = jnp.einsum("tkn,tk->tn", one_hot, weights)

    # dense dispatch: every expert sees every token, scaled post-hoc.
    # With capacity_factor set, tokens beyond an expert's capacity drop out
    # (position-in-expert computed via a cumulative sum).
    if capacity_factor is not None:
        capacity = int(capacity_factor * (B * S) * num_experts_per_tok
                       / num_experts)
        dispatch_mask = combine > 0
        position_in_expert = jnp.cumsum(dispatch_mask, axis=0) * dispatch_mask
        combine = jnp.where(position_in_expert <= capacity, combine, 0.0)

    # [n, t, E]: per-expert token batch (sharded over 'expert' this is the
    # all-to-all boundary)
    h = jnp.einsum("te,tn->nte", tokens, combine != 0)
    gate = activation(jnp.einsum("nte,nef->ntf", h, w_gate,
                                 preferred_element_type=jnp.float32))
    up = jnp.einsum("nte,nef->ntf", h, w_up,
                    preferred_element_type=jnp.float32)
    expert_out = jnp.einsum("ntf,nfe->nte", (gate * up).astype(x.dtype),
                            w_down, preferred_element_type=jnp.float32)
    out = jnp.einsum("nte,tn->te", expert_out.astype(x.dtype), combine)
    aux = _load_balancing_loss(router_logits, one_hot)
    return out.reshape(B, S, E), aux


def _load_balancing_loss(router_logits, one_hot):
    """Switch-style auxiliary loss: num_experts * Σ fraction_i * prob_i."""
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits, axis=-1)
    fraction = jnp.mean(one_hot.sum(axis=1), axis=0)
    prob_mean = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(fraction * prob_mean)
