"""Mixture-of-Experts: top-k router + capacity-bucketed sparse dispatch.

Expert-parallel path (SURVEY.md §5.7, Mixtral target): the reference
delegates MoE entirely to user frameworks (its training substrate is the
rank/world-size env shim, /root/reference/metaflow/plugins/frameworks/
pytorch.py:11-46), so an efficient TPU dispatch is this repo's job.

Two dispatch strategies, numerically equivalent modulo capacity drops:

``sparse`` (default) — capacity-bucketed dispatch, the GShard/Switch
    pattern: top-k → position-in-expert (cumsum over a static slot order)
    → scatter into static ``[experts, capacity, embed]`` buffers → local
    expert matmuls → gather-combine. Compute and memory scale with
    ``k × tokens × capacity_factor``, NOT ``num_experts × tokens``.
    Sharded on the 'expert' mesh axis the scatter/gather become the
    all-to-all boundary (XLA inserts it; we pin the buffer sharding so
    the expert matmuls stay local).

``dense`` — reference oracle: every expert sees every token via one-hot
    einsums. O(num_experts × tokens) FLOPs; kept for equivalence tests
    and tiny-scale debugging only.

``gmm`` — DROPLESS dispatch via the pallas grouped-matmul kernel
    (ops/gmm.py, megablocks pattern): slots sort into expert-contiguous
    tiles and each tile multiplies its expert's weights directly on the
    MXU. Exact top-k semantics (no capacity, no drops) at
    O(k × tokens + experts·block) FLOPs. Single-shard experts (dense/
    tensor-parallel meshes).

``gmm_ep`` — dropless dispatch COMPOSED with expert parallelism
    (shard_map over the 'expert' mesh axis): each expert-axis member
    routes a 1/P token slice, all-to-alls slots to the shard owning
    their expert, runs the LOCAL grouped matmul over its n/P experts,
    and all-to-alls results back. Static shapes force a per-(src,dst)
    send budget: ``ep_buffer_factor=None`` (default) sizes it at the
    worst case — bit-equivalent to the dense oracle, truly dropless,
    but each shard's gmm is padded to the full slot count (weights and
    grads still shard P ways); a finite factor sizes buffers at
    ``factor·slots/P`` for real P-fold FLOPs scaling with
    shard-overflow drops only under routing imbalance (the aux loss
    pushes toward balance).

Capacity semantics are identical in the sparse and dense paths: an
expert accepts its first ``capacity`` tokens in token order; the rest
are dropped (their combine weight becomes 0 and the residual stream
passes through). The gmm path has no capacity — it is exactly dropless.
"""

import math

import jax
import jax.numpy as jnp


def top_k_router(logits, num_experts, k, dtype=jnp.float32):
    """logits: [tokens, experts] → (weights [tokens, k], idx [tokens, k]).

    Softmax over the selected k (Mixtral convention)."""
    gate_logits, idx = jax.lax.top_k(logits, k)
    weights = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    return weights.astype(dtype), idx


def expert_capacity(num_tokens, num_experts, k, capacity_factor):
    """Static per-expert token budget.

    capacity_factor=None means lossless: capacity = num_tokens (the worst
    case — every token routes a slot to the same expert), which makes the
    sparse path bit-equivalent to dense dispatch without capacity."""
    if capacity_factor is None:
        return num_tokens
    cap = int(math.ceil(capacity_factor * num_tokens * k / num_experts))
    return max(1, min(cap, num_tokens))


def _active_mesh():
    """The mesh from an enclosing `with mesh:` block, if any."""
    try:
        try:  # jax >= 0.8.2 deprecated the pxla re-export
            from jax._src.mesh import thread_resources
        except ImportError:
            from jax.interpreters.pxla import thread_resources

        mesh = thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception:
        pass
    return None


def _constrain_expert_axis(x, mesh):
    """Pin buffer axis 0 to the 'expert' mesh axis so the scatter/gather is
    the single all-to-all boundary and expert matmuls stay chip-local."""
    if mesh is None or "expert" not in mesh.axis_names:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec("expert", *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def moe_ffn(x, router_w, w_gate, w_up, w_down, num_experts_per_tok=2,
            capacity_factor=None, activation=jax.nn.silu, dispatch="sparse",
            mesh=None, ep_buffer_factor=None):
    """Token-choice MoE feed-forward.

    x:        [B, S, E]
    router_w: [E, num_experts]
    w_gate/w_up: [num_experts, E, F]; w_down: [num_experts, F, E]
    mesh:     pass the device mesh explicitly so the sparse path can pin
              its expert buffers to the 'expert' axis even when the step
              is traced outside a `with mesh:` block; falls back to the
              ambient mesh context when omitted.
    ep_buffer_factor: 'gmm_ep' only — per-(src,dst) all-to-all budget as
              a multiple of the balanced share. None = exact worst case
              (dropless); ~1-2 trades shard-overflow drops under extreme
              imbalance for P-fold FLOPs scaling.

    Returns (out [B, S, E], aux_loss scalar).
    """
    B, S, E = x.shape
    num_experts = router_w.shape[1]
    k = num_experts_per_tok

    if dispatch == "gmm_ep":
        # routing happens per token-slice INSIDE the shard_map; branch
        # before the full-batch router below
        if capacity_factor is not None:
            raise ValueError(
                "dispatch='gmm_ep' is dropless — capacity_factor must be "
                "None (bound memory with ep_buffer_factor instead)")
        active = mesh if mesh is not None else _active_mesh()
        if active is None or "expert" not in active.axis_names:
            raise ValueError(
                "dispatch='gmm_ep' needs a mesh with an 'expert' axis "
                "(use dispatch='gmm' for single-shard experts)")
        return _gmm_ep_dispatch_ffn(
            x, router_w, w_gate, w_up, w_down, num_experts, k, activation,
            active, ep_buffer_factor,
        )
    if ep_buffer_factor is not None:
        raise ValueError("ep_buffer_factor only applies to dispatch='gmm_ep'")
    tokens = x.reshape(B * S, E)

    router_logits = jnp.einsum(
        "te,en->tn", tokens.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    weights, idx = top_k_router(router_logits, num_experts, k, dtype=x.dtype)
    one_hot = jax.nn.one_hot(idx, num_experts, dtype=x.dtype)  # [t, k, n]
    aux = _load_balancing_loss(router_logits, one_hot)

    if dispatch == "sparse":
        out = _sparse_dispatch_ffn(
            tokens, weights, idx, w_gate, w_up, w_down, num_experts, k,
            capacity_factor, activation,
            mesh if mesh is not None else _active_mesh(),
        )
    elif dispatch == "dense":
        out = _dense_dispatch_ffn(
            tokens, weights, idx, one_hot, w_gate, w_up, w_down, num_experts,
            k, capacity_factor, activation,
        )
    elif dispatch == "gmm":
        if capacity_factor is not None:
            raise ValueError(
                "dispatch='gmm' is dropless — capacity_factor must be None"
            )
        active = mesh if mesh is not None else _active_mesh()
        if active is not None and "expert" in active.axis_names:
            # silently all-gathering every expert's weights (and fp32
            # grads) onto every chip would defeat the expert axis the
            # user asked for — the capacity path is the EP story
            raise ValueError(
                "dispatch='gmm' runs experts single-shard; on an "
                "expert-parallel mesh use dispatch='gmm_ep' (dropless) "
                "or 'sparse' (capacity-bucketed)"
            )
        out = _gmm_dispatch_ffn(
            tokens, weights, idx, w_gate, w_up, w_down, num_experts, k,
            activation,
        )
    else:
        raise ValueError("dispatch must be 'sparse', 'dense', 'gmm' or "
                         "'gmm_ep', got %r" % (dispatch,))
    return out.reshape(B, S, E), aux


def _sparse_dispatch_ffn(tokens, weights, idx, w_gate, w_up, w_down,
                         num_experts, k, capacity_factor, activation, mesh):
    """Capacity-bucketed dispatch: O(k·T·capacity_factor) expert FLOPs.

    Slot order is token-major (slot t·k+j precedes t'·k+j' iff t<t' or
    (t==t', j<j')); since top-k indices are distinct per token, each token
    holds at most one slot per expert, so per-expert arrival order equals
    token order — the same drop decisions as the dense oracle's
    token-axis cumsum."""
    T, E = tokens.shape
    N = num_experts
    C = expert_capacity(T, N, k, capacity_factor)

    e_flat = idx.reshape(T * k)                      # expert id per slot
    w_flat = weights.reshape(T * k)                  # combine weight per slot
    slot_one_hot = jax.nn.one_hot(e_flat, N, dtype=jnp.int32)  # [T*k, N]
    # 0-based arrival position of each slot within its expert
    pos = jnp.cumsum(slot_one_hot, axis=0) - 1       # [T*k, N]
    pos_flat = jnp.take_along_axis(pos, e_flat[:, None], axis=1)[:, 0]
    keep = pos_flat < C
    # dropped slots scatter out of range; mode="drop" discards them with
    # static shapes (positions are unique per expert, so add == set)
    safe_pos = jnp.where(keep, pos_flat, C)
    t_flat = jnp.arange(T * k) // k                  # owning token per slot

    x_buf = jnp.zeros((N, C, E), tokens.dtype).at[e_flat, safe_pos].add(
        tokens[t_flat], mode="drop"
    )
    x_buf = _constrain_expert_axis(x_buf, mesh)      # all-to-all boundary in

    gate = activation(jnp.einsum("nce,nef->ncf", x_buf, w_gate,
                                 preferred_element_type=jnp.float32))
    up = jnp.einsum("nce,nef->ncf", x_buf, w_up,
                    preferred_element_type=jnp.float32)
    y_buf = jnp.einsum("ncf,nfe->nce", (gate * up).astype(tokens.dtype),
                       w_down, preferred_element_type=jnp.float32)
    y_buf = _constrain_expert_axis(y_buf.astype(tokens.dtype), mesh)

    # combine: gather each slot's expert output back (all-to-all boundary
    # out); out-of-range gathers clamp but are zeroed by the keep mask
    y_slots = y_buf[e_flat, safe_pos]                # [T*k, E]
    y_slots = jnp.where(keep[:, None], y_slots, 0) * w_flat[:, None]
    return y_slots.reshape(T, k, E).sum(axis=1)


def _gmm_dispatch_ffn(tokens, weights, idx, w_gate, w_up, w_down,
                      num_experts, k, activation):
    """Dropless dispatch through the pallas grouped matmul: sort slots
    into expert-contiguous 128-row tiles, run the three expert matmuls as
    gmm, gather-combine. Exact top-k output (bit-comparable to the dense
    oracle without capacity)."""
    from .gmm import gather_rows, gmm, make_group_layout, scatter_rows

    T, E = tokens.shape
    e_flat = idx.reshape(T * k)
    w_flat = weights.reshape(T * k)
    t_flat = jnp.arange(T * k) // k

    layout = make_group_layout(e_flat, num_experts)
    x_pad = scatter_rows(tokens[t_flat], layout)
    tg, ta = layout["tile_group"], layout["tile_active"]
    gate = activation(gmm(x_pad, w_gate, tg, tile_active=ta))
    up = gmm(x_pad, w_up, tg, tile_active=ta)
    y_pad = gmm((gate * up).astype(tokens.dtype), w_down, tg,
                tile_active=ta)
    y_slots = gather_rows(y_pad, layout) * w_flat[:, None]
    return y_slots.reshape(T, k, E).sum(axis=1)


def _gmm_ep_dispatch_ffn(x, router_w, w_gate, w_up, w_down, num_experts, k,
                         activation, mesh, ep_buffer_factor):
    """Dropless grouped-matmul dispatch composed with expert parallelism.

    shard_map over the WHOLE mesh: batch rides its usual ('data','fsdp')
    axes, expert weights live split on 'expert' (and their mlp dim on
    'tensor'). Per expert-axis member, over its static 1/P token slice:

      route → bucket slots by destination shard → all_to_all in →
      local gmm over this shard's n/P experts → psum partial mlp
      contractions over 'tensor' → all_to_all back → weighted combine →
      all_gather token slices.

    The per-(src,dst) buffer is the static-shape price of dropless EP on
    TPU (XLA cannot ship dynamic row counts): exact worst case when
    ep_buffer_factor is None, `ceil(factor·slots/P)` otherwise. The
    reference delegates all of MoE to user frameworks
    (/root/reference/metaflow/plugins/frameworks/pytorch.py:11-46); this
    composition is the repo's own per-chip-efficiency path for the
    Mixtral target.
    """
    import math as _math

    from jax.sharding import PartitionSpec as P

    from .attention import shard_map_novma
    from .gmm import BLOCK_S, gather_rows, gmm, make_group_layout, \
        scatter_rows

    axes = set(mesh.axis_names)
    ep = mesh.shape["expert"]
    if num_experts % ep:
        raise ValueError(
            "gmm_ep needs num_experts %% expert-axis size == 0 "
            "(experts=%d, expert axis=%d)" % (num_experts, ep))
    n_local = num_experts // ep
    batch_axes = tuple(a for a in ("data", "fsdp") if a in axes)
    tensor = "tensor" if "tensor" in axes else None
    token_axes = batch_axes + ("expert",)

    B, S, E = x.shape
    batch_shards = 1
    for a in batch_axes:
        batch_shards *= mesh.shape[a]
    if B % batch_shards:
        raise ValueError("gmm_ep: batch %d not divisible by batch shards %d"
                         % (B, batch_shards))
    T_block = (B // batch_shards) * S   # tokens per batch-shard block
    if T_block % ep:
        raise ValueError(
            "gmm_ep: per-shard token count %d not divisible by the "
            "expert axis (%d) — each member routes a 1/P token slice"
            % (T_block, ep))
    T_slice = T_block // ep
    slots = T_slice * k
    if ep_buffer_factor is None:
        c_send = slots                  # worst case: every slot, one dst
    else:
        c_send = min(slots, int(_math.ceil(ep_buffer_factor * slots / ep)))
        c_send = max(1, c_send)

    def per_member(xb, rw, wg, wu, wd):
        Bb, Sb, Eb = xb.shape
        tok_all = xb.reshape(Bb * Sb, Eb)
        p = jax.lax.axis_index("expert")
        tok = jax.lax.dynamic_slice_in_dim(tok_all, p * T_slice, T_slice, 0)

        logits = jnp.einsum("te,en->tn", tok.astype(jnp.float32),
                            rw.astype(jnp.float32))
        weights, idx = top_k_router(logits, num_experts, k, dtype=xb.dtype)
        sel = jax.nn.one_hot(idx, num_experts, dtype=xb.dtype)
        # aux: pmean the per-slice ingredients over every token-sharding
        # axis, THEN combine — sum(mean·mean) is not mean(sum·sum)
        probs = jax.nn.softmax(logits, axis=-1)
        fraction = jax.lax.pmean(jnp.mean(sel.sum(axis=1), axis=0),
                                 token_axes)
        prob_mean = jax.lax.pmean(jnp.mean(probs, axis=0), token_axes)
        aux = num_experts * jnp.sum(fraction * prob_mean)

        e_flat = idx.reshape(slots)
        w_flat = weights.reshape(slots)
        t_flat = jnp.arange(slots) // k
        dst = e_flat // n_local
        # arrival position of each slot within its destination block
        pos = jnp.cumsum(jax.nn.one_hot(dst, ep, dtype=jnp.int32),
                         axis=0) - 1
        pos_flat = jnp.take_along_axis(pos, dst[:, None], axis=1)[:, 0]
        keep = pos_flat < c_send        # exact mode: always true
        safe_pos = jnp.where(keep, pos_flat, c_send)

        send_x = jnp.zeros((ep, c_send, Eb), xb.dtype).at[
            dst, safe_pos].add(tok[t_flat], mode="drop")
        # local expert id AND a validity flag ride with each row:
        # unwritten buffer slots must not masquerade as expert-0 rows,
        # or the grouped layout would mark their tiles active and the
        # kernels would burn the full worst-case MXU work on padding
        send_le = jnp.zeros((ep, c_send), jnp.int32).at[dst, safe_pos].set(
            e_flat % n_local, mode="drop")
        send_ok = jnp.zeros((ep, c_send), jnp.int32).at[dst, safe_pos].set(
            1, mode="drop")

        # [P, C, ·] tiled all_to_all = (member, block) grid transpose:
        # recv[src] is what src addressed to this member
        recv_x = jax.lax.all_to_all(send_x, "expert", 0, 0, tiled=True)
        recv_le = jax.lax.all_to_all(send_le, "expert", 0, 0, tiled=True)
        recv_ok = jax.lax.all_to_all(send_ok, "expert", 0, 0, tiled=True)

        rows = recv_x.reshape(ep * c_send, Eb)
        layout = make_group_layout(recv_le.reshape(ep * c_send), n_local,
                                   block_s=BLOCK_S,
                                   row_valid=recv_ok.reshape(ep * c_send))
        x_pad = scatter_rows(rows, layout)
        tg, ta = layout["tile_group"], layout["tile_active"]
        gate = activation(gmm(x_pad, wg, tg, tile_active=ta))
        up = gmm(x_pad, wu, tg, tile_active=ta)
        y_pad = gmm((gate * up).astype(xb.dtype), wd, tg,
                    tile_active=ta)
        # invalid rows gathered from skipped tiles read zeros, exactly
        # what their (zero) data would have produced
        y_rows = gather_rows(y_pad, layout)
        if tensor:                      # w_down contracted a sharded mlp dim
            y_rows = jax.lax.psum(y_rows, tensor)

        y_back = jax.lax.all_to_all(
            y_rows.reshape(ep, c_send, Eb), "expert", 0, 0, tiled=True)
        y_slots = y_back[dst, safe_pos]
        y_slots = jnp.where(keep[:, None], y_slots, 0) * w_flat[:, None]
        y_slice = y_slots.reshape(T_slice, k, Eb).sum(axis=1)
        y_full = jax.lax.all_gather(y_slice, "expert", axis=0, tiled=True)
        return y_full.reshape(Bb, Sb, Eb), aux

    batch_spec = batch_axes if len(batch_axes) > 1 else \
        (batch_axes[0] if batch_axes else None)
    out, aux = shard_map_novma(
        per_member, mesh,
        in_specs=(P(batch_spec, None, None), P(None, None),
                  P("expert", None, tensor), P("expert", None, tensor),
                  P("expert", tensor, None)),
        out_specs=(P(batch_spec, None, None), P()),
    )(x, router_w, w_gate, w_up, w_down)
    return out, aux


def _dense_dispatch_ffn(tokens, weights, idx, one_hot, w_gate, w_up, w_down,
                        num_experts, k, capacity_factor, activation):
    """Reference oracle: every expert sees every token (one-hot einsums)."""
    T, E = tokens.shape
    # combine matrix: [tokens, experts], rows sum to 1 over selected experts
    combine = jnp.einsum("tkn,tk->tn", one_hot, weights)

    if capacity_factor is not None:
        C = expert_capacity(T, num_experts, k, capacity_factor)
        # count capacity from the ROUTING mask (one_hot), not `combine > 0`:
        # a top-k slot whose softmax weight underflowed to exactly 0 still
        # occupies a capacity slot in the sparse path, and the oracle must
        # make identical drop decisions
        dispatch_mask = jnp.sum(one_hot, axis=1) > 0  # [t, n]
        # 1-based arrival position in token order
        position_in_expert = jnp.cumsum(dispatch_mask, axis=0) * dispatch_mask
        combine = jnp.where(position_in_expert <= C, combine, 0.0)

    # [n, t, E]: per-expert token batch
    h = jnp.einsum("te,tn->nte", tokens, combine != 0)
    gate = activation(jnp.einsum("nte,nef->ntf", h, w_gate,
                                 preferred_element_type=jnp.float32))
    up = jnp.einsum("nte,nef->ntf", h, w_up,
                    preferred_element_type=jnp.float32)
    expert_out = jnp.einsum("ntf,nfe->nte", (gate * up).astype(tokens.dtype),
                            w_down, preferred_element_type=jnp.float32)
    return jnp.einsum("nte,tn->te", expert_out.astype(tokens.dtype), combine)


def _load_balancing_loss(router_logits, one_hot):
    """Switch-style auxiliary loss: num_experts * Σ fraction_i * prob_i."""
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits, axis=-1)
    fraction = jnp.mean(one_hot.sum(axis=1), axis=0)
    prob_mean = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(fraction * prob_mean)
