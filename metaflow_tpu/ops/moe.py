"""Mixture-of-Experts: top-k router + capacity-bucketed sparse dispatch.

Expert-parallel path (SURVEY.md §5.7, Mixtral target): the reference
delegates MoE entirely to user frameworks (its training substrate is the
rank/world-size env shim, /root/reference/metaflow/plugins/frameworks/
pytorch.py:11-46), so an efficient TPU dispatch is this repo's job.

Two dispatch strategies, numerically equivalent modulo capacity drops:

``sparse`` (default) — capacity-bucketed dispatch, the GShard/Switch
    pattern: top-k → position-in-expert (cumsum over a static slot order)
    → scatter into static ``[experts, capacity, embed]`` buffers → local
    expert matmuls → gather-combine. Compute and memory scale with
    ``k × tokens × capacity_factor``, NOT ``num_experts × tokens``.
    Sharded on the 'expert' mesh axis the scatter/gather become the
    all-to-all boundary (XLA inserts it; we pin the buffer sharding so
    the expert matmuls stay local).

``dense`` — reference oracle: every expert sees every token via one-hot
    einsums. O(num_experts × tokens) FLOPs; kept for equivalence tests
    and tiny-scale debugging only.

``gmm`` — DROPLESS dispatch via the pallas grouped-matmul kernel
    (ops/gmm.py, megablocks pattern): slots sort into expert-contiguous
    tiles and each tile multiplies its expert's weights directly on the
    MXU. Exact top-k semantics (no capacity, no drops) at
    O(k × tokens + experts·block) FLOPs. Single-shard experts (dense/
    tensor-parallel meshes); the capacity path remains the
    expert-parallel all-to-all story.

Capacity semantics are identical in the sparse and dense paths: an
expert accepts its first ``capacity`` tokens in token order; the rest
are dropped (their combine weight becomes 0 and the residual stream
passes through). The gmm path has no capacity — it is exactly dropless.
"""

import math

import jax
import jax.numpy as jnp


def top_k_router(logits, num_experts, k, dtype=jnp.float32):
    """logits: [tokens, experts] → (weights [tokens, k], idx [tokens, k]).

    Softmax over the selected k (Mixtral convention)."""
    gate_logits, idx = jax.lax.top_k(logits, k)
    weights = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    return weights.astype(dtype), idx


def expert_capacity(num_tokens, num_experts, k, capacity_factor):
    """Static per-expert token budget.

    capacity_factor=None means lossless: capacity = num_tokens (the worst
    case — every token routes a slot to the same expert), which makes the
    sparse path bit-equivalent to dense dispatch without capacity."""
    if capacity_factor is None:
        return num_tokens
    cap = int(math.ceil(capacity_factor * num_tokens * k / num_experts))
    return max(1, min(cap, num_tokens))


def _active_mesh():
    """The mesh from an enclosing `with mesh:` block, if any."""
    try:
        try:  # jax >= 0.8.2 deprecated the pxla re-export
            from jax._src.mesh import thread_resources
        except ImportError:
            from jax.interpreters.pxla import thread_resources

        mesh = thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception:
        pass
    return None


def _constrain_expert_axis(x, mesh):
    """Pin buffer axis 0 to the 'expert' mesh axis so the scatter/gather is
    the single all-to-all boundary and expert matmuls stay chip-local."""
    if mesh is None or "expert" not in mesh.axis_names:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec("expert", *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def moe_ffn(x, router_w, w_gate, w_up, w_down, num_experts_per_tok=2,
            capacity_factor=None, activation=jax.nn.silu, dispatch="sparse",
            mesh=None):
    """Token-choice MoE feed-forward.

    x:        [B, S, E]
    router_w: [E, num_experts]
    w_gate/w_up: [num_experts, E, F]; w_down: [num_experts, F, E]
    mesh:     pass the device mesh explicitly so the sparse path can pin
              its expert buffers to the 'expert' axis even when the step
              is traced outside a `with mesh:` block; falls back to the
              ambient mesh context when omitted.

    Returns (out [B, S, E], aux_loss scalar).
    """
    B, S, E = x.shape
    num_experts = router_w.shape[1]
    k = num_experts_per_tok
    tokens = x.reshape(B * S, E)

    router_logits = jnp.einsum(
        "te,en->tn", tokens.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    weights, idx = top_k_router(router_logits, num_experts, k, dtype=x.dtype)
    one_hot = jax.nn.one_hot(idx, num_experts, dtype=x.dtype)  # [t, k, n]
    aux = _load_balancing_loss(router_logits, one_hot)

    if dispatch == "sparse":
        out = _sparse_dispatch_ffn(
            tokens, weights, idx, w_gate, w_up, w_down, num_experts, k,
            capacity_factor, activation,
            mesh if mesh is not None else _active_mesh(),
        )
    elif dispatch == "dense":
        out = _dense_dispatch_ffn(
            tokens, weights, idx, one_hot, w_gate, w_up, w_down, num_experts,
            k, capacity_factor, activation,
        )
    elif dispatch == "gmm":
        if capacity_factor is not None:
            raise ValueError(
                "dispatch='gmm' is dropless — capacity_factor must be None"
            )
        active = mesh if mesh is not None else _active_mesh()
        if active is not None and "expert" in active.axis_names:
            # silently all-gathering every expert's weights (and fp32
            # grads) onto every chip would defeat the expert axis the
            # user asked for — the capacity path is the EP story
            raise ValueError(
                "dispatch='gmm' runs experts single-shard; on an "
                "expert-parallel mesh use dispatch='sparse'"
            )
        out = _gmm_dispatch_ffn(
            tokens, weights, idx, w_gate, w_up, w_down, num_experts, k,
            activation,
        )
    else:
        raise ValueError("dispatch must be 'sparse', 'dense' or 'gmm', "
                         "got %r" % (dispatch,))
    return out.reshape(B, S, E), aux


def _sparse_dispatch_ffn(tokens, weights, idx, w_gate, w_up, w_down,
                         num_experts, k, capacity_factor, activation, mesh):
    """Capacity-bucketed dispatch: O(k·T·capacity_factor) expert FLOPs.

    Slot order is token-major (slot t·k+j precedes t'·k+j' iff t<t' or
    (t==t', j<j')); since top-k indices are distinct per token, each token
    holds at most one slot per expert, so per-expert arrival order equals
    token order — the same drop decisions as the dense oracle's
    token-axis cumsum."""
    T, E = tokens.shape
    N = num_experts
    C = expert_capacity(T, N, k, capacity_factor)

    e_flat = idx.reshape(T * k)                      # expert id per slot
    w_flat = weights.reshape(T * k)                  # combine weight per slot
    slot_one_hot = jax.nn.one_hot(e_flat, N, dtype=jnp.int32)  # [T*k, N]
    # 0-based arrival position of each slot within its expert
    pos = jnp.cumsum(slot_one_hot, axis=0) - 1       # [T*k, N]
    pos_flat = jnp.take_along_axis(pos, e_flat[:, None], axis=1)[:, 0]
    keep = pos_flat < C
    # dropped slots scatter out of range; mode="drop" discards them with
    # static shapes (positions are unique per expert, so add == set)
    safe_pos = jnp.where(keep, pos_flat, C)
    t_flat = jnp.arange(T * k) // k                  # owning token per slot

    x_buf = jnp.zeros((N, C, E), tokens.dtype).at[e_flat, safe_pos].add(
        tokens[t_flat], mode="drop"
    )
    x_buf = _constrain_expert_axis(x_buf, mesh)      # all-to-all boundary in

    gate = activation(jnp.einsum("nce,nef->ncf", x_buf, w_gate,
                                 preferred_element_type=jnp.float32))
    up = jnp.einsum("nce,nef->ncf", x_buf, w_up,
                    preferred_element_type=jnp.float32)
    y_buf = jnp.einsum("ncf,nfe->nce", (gate * up).astype(tokens.dtype),
                       w_down, preferred_element_type=jnp.float32)
    y_buf = _constrain_expert_axis(y_buf.astype(tokens.dtype), mesh)

    # combine: gather each slot's expert output back (all-to-all boundary
    # out); out-of-range gathers clamp but are zeroed by the keep mask
    y_slots = y_buf[e_flat, safe_pos]                # [T*k, E]
    y_slots = jnp.where(keep[:, None], y_slots, 0) * w_flat[:, None]
    return y_slots.reshape(T, k, E).sum(axis=1)


def _gmm_dispatch_ffn(tokens, weights, idx, w_gate, w_up, w_down,
                      num_experts, k, activation):
    """Dropless dispatch through the pallas grouped matmul: sort slots
    into expert-contiguous 128-row tiles, run the three expert matmuls as
    gmm, gather-combine. Exact top-k output (bit-comparable to the dense
    oracle without capacity)."""
    from .gmm import gather_rows, gmm, make_group_layout, scatter_rows

    T, E = tokens.shape
    e_flat = idx.reshape(T * k)
    w_flat = weights.reshape(T * k)
    t_flat = jnp.arange(T * k) // k

    layout = make_group_layout(e_flat, num_experts)
    x_pad = scatter_rows(tokens[t_flat], layout)
    tg = layout["tile_group"]
    gate = activation(gmm(x_pad, w_gate, tg))
    up = gmm(x_pad, w_up, tg)
    y_pad = gmm((gate * up).astype(tokens.dtype), w_down, tg)
    y_slots = gather_rows(y_pad, layout) * w_flat[:, None]
    return y_slots.reshape(T, k, E).sum(axis=1)


def _dense_dispatch_ffn(tokens, weights, idx, one_hot, w_gate, w_up, w_down,
                        num_experts, k, capacity_factor, activation):
    """Reference oracle: every expert sees every token (one-hot einsums)."""
    T, E = tokens.shape
    # combine matrix: [tokens, experts], rows sum to 1 over selected experts
    combine = jnp.einsum("tkn,tk->tn", one_hot, weights)

    if capacity_factor is not None:
        C = expert_capacity(T, num_experts, k, capacity_factor)
        # count capacity from the ROUTING mask (one_hot), not `combine > 0`:
        # a top-k slot whose softmax weight underflowed to exactly 0 still
        # occupies a capacity slot in the sparse path, and the oracle must
        # make identical drop decisions
        dispatch_mask = jnp.sum(one_hot, axis=1) > 0  # [t, n]
        # 1-based arrival position in token order
        position_in_expert = jnp.cumsum(dispatch_mask, axis=0) * dispatch_mask
        combine = jnp.where(position_in_expert <= C, combine, 0.0)

    # [n, t, E]: per-expert token batch
    h = jnp.einsum("te,tn->nte", tokens, combine != 0)
    gate = activation(jnp.einsum("nte,nef->ntf", h, w_gate,
                                 preferred_element_type=jnp.float32))
    up = jnp.einsum("nte,nef->ntf", h, w_up,
                    preferred_element_type=jnp.float32)
    expert_out = jnp.einsum("ntf,nfe->nte", (gate * up).astype(tokens.dtype),
                            w_down, preferred_element_type=jnp.float32)
    return jnp.einsum("nte,tn->te", expert_out.astype(tokens.dtype), combine)


def _load_balancing_loss(router_logits, one_hot):
    """Switch-style auxiliary loss: num_experts * Σ fraction_i * prob_i."""
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits, axis=-1)
    fraction = jnp.mean(one_hot.sum(axis=1), axis=0)
    prob_mean = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(fraction * prob_mean)
