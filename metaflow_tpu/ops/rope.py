"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling."""

import jax.numpy as jnp


def rope_frequencies(head_dim, max_seq_len, theta=500_000.0, dtype=jnp.float32,
                     llama3_scaling=False):
    """Precompute cos/sin tables [max_seq_len, head_dim//2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if llama3_scaling:
        inv_freq = _llama3_scale(inv_freq)
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def _llama3_scale(inv_freq, factor=8.0, low_freq_factor=1.0,
                  high_freq_factor=4.0, original_context=8192):
    """Llama-3.1 'NTK-by-parts' frequency scaling."""
    wavelen = 2.0 * jnp.pi / inv_freq
    low_wavelen = original_context / low_freq_factor
    high_wavelen = original_context / high_freq_factor
    scaled = inv_freq / factor
    smooth = (original_context / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_wavelen, scaled, inv_freq)
    mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
    return jnp.where(mid, smoothed, out)


def apply_rope(x, cos, sin, positions=None):
    """x: [..., seq, heads, head_dim]; cos/sin: [max_seq, head_dim//2].

    Uses the interleaved-half convention (rotate_half), matching Llama.
    """
    seq_len = x.shape[-3]
    if positions is None:
        c = cos[:seq_len][:, None, :]
        s = sin[:seq_len][:, None, :]
    else:
        c = cos[positions][..., None, :]
        s = sin[positions][..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
