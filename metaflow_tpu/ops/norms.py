"""Normalization ops. Elementwise chains like these fuse into neighbouring
matmuls under XLA; they are written in float32 accumulation regardless of
input dtype (bf16-safe)."""

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, eps=1e-6):
    """RMSNorm (Llama-style): x * w / rms(x)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias, eps=1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    out = x * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)
