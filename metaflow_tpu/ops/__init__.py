from .norms import rms_norm, layer_norm
from .rope import rope_frequencies, apply_rope
from .attention import attention, flash_attention, reference_attention
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses_attention import ulysses_attention, ulysses_attention_sharded
from .gmm import gather_rows, gmm, make_group_layout, scatter_rows
from .moe import moe_ffn, top_k_router

__all__ = [
    "rms_norm",
    "layer_norm",
    "rope_frequencies",
    "apply_rope",
    "attention",
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
    "moe_ffn",
    "gmm",
    "make_group_layout",
    "scatter_rows",
    "gather_rows",
    "top_k_router",
]
