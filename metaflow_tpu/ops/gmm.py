"""Grouped matrix multiply: the dropless-MoE kernel (megablocks pattern).

`y[i] = x[i] @ w[g(i)]` where rows of x are grouped (sorted + padded so
every `block_s`-row tile belongs to exactly ONE group). The pallas TPU
kernel streams row tiles through the MXU with the group's weight tile
selected per grid step via a scalar-prefetched tile→group table — no
`[groups, tokens]` one-hot, no capacity drops: compute scales with the
actual token count (plus ≤ groups·block_s rows of zero padding).

Backward: dx is the same kernel with transposed weights; dw accumulates
per-tile outer products into the group's weight-grad block, exploiting
the sorted layout (tiles of one group are consecutive, so the output
block is revisited across consecutive grid steps — the pallas TPU
accumulation idiom).

The reference delegates MoE entirely to user frameworks (SURVEY.md §5.7);
this is this repo's scalable-dispatch fast path alongside the
capacity-bucketed one in ops/moe.py.
"""

import functools
import os

import jax
import jax.numpy as jnp

from .. import knobs

try:  # pallas import is TPU/CPU-interpret capable; keep soft for portability
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover
    pl = None


def _pltpu():
    """LAZY import: jax.experimental.pallas.tpu touches the TPU plugin
    registry at import time — with the axon tunnel wedged that hangs, so
    it must never run at module import (only when a gmm actually
    executes, by which point the caller has committed to a backend)."""
    from jax.experimental.pallas import tpu as pltpu

    return pltpu

# default kernel tiles; env-overridable (TPUFLOW_* layering) so the
# on-chip MFU sweep can tune MXU block sizes without code edits —
# BLOCK_S is also the padding quantum of the grouped layout, so a run
# must use ONE consistent value end to end
BLOCK_S = knobs.get_int("TPUFLOW_GMM_BLOCK_S")
BLOCK_F = knobs.get_int("TPUFLOW_GMM_BLOCK_F")
BLOCK_D = knobs.get_int("TPUFLOW_GMM_BLOCK_D")


def _default_interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# grouped layout: sort slots by group, pad each group to a BLOCK_S multiple
# ---------------------------------------------------------------------------


def make_group_layout(group_ids, num_groups, block_s=BLOCK_S,
                      row_valid=None):
    """Static-shape grouped layout for `gmm`.

    group_ids: [n] int32 — the group of each row.
    row_valid: optional [n] int32/bool — rows marked 0 are PADDING the
      caller was forced to carry at static shape (e.g. gmm_ep's
      unwritten all-to-all buffer slots). They still get layout
      positions (AFTER their group's valid rows) but never mark a tile
      active, so the kernels skip their compute; their gathered outputs
      come from zeroed tiles. Without this, padding rows masquerade as
      real rows of their group and re-inflate the skipped work.
    Returns dict with:
      dest        [n]        destination row of each input row
      tile_group  [n_tiles]  group id of every block_s-row tile
      tile_active [n_tiles]  1 iff the tile holds >= 1 (valid) row
      padded_len             static total rows (multiple of block_s)

    Every group's rows land contiguously at a block_s-aligned offset, so
    each tile belongs to exactly one group; rows past a group's count are
    zero padding (they multiply into zeros and accumulate nothing).
    """
    n = group_ids.shape[0]
    counts = jnp.bincount(group_ids, length=num_groups)
    if row_valid is None:
        valid = jnp.ones((n,), jnp.int32)
        counts_valid = counts
    else:
        valid = row_valid.astype(jnp.int32)
        counts_valid = jnp.bincount(group_ids, weights=valid,
                                    length=num_groups).astype(jnp.int32)
    padded = ((counts + block_s - 1) // block_s) * block_s
    ends = jnp.cumsum(padded)
    offsets = ends - padded
    # rank of each row within its group via a stable argsort — O(n log
    # n), no [n, groups] one-hot materialized. Sort key puts each
    # group's VALID rows first (arrival-stable within each class) so
    # valid rows form a prefix and tile_active is a per-group prefix
    # predicate
    order = jnp.argsort(group_ids * 2 + (1 - valid), stable=True)
    excl = jnp.cumsum(counts) - counts  # rows in earlier groups
    rank = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32)
        - excl[group_ids[order]].astype(jnp.int32)
    )
    dest = offsets[group_ids] + rank

    # static upper bound on total padded rows
    padded_len = -(-n // block_s) * block_s + num_groups * block_s
    n_tiles = padded_len // block_s
    tile_start = jnp.arange(n_tiles, dtype=jnp.int32) * block_s
    # tile t belongs to the first group whose padded range ends past it;
    # tiles beyond every group clamp to the last group — they hold only
    # zero rows, so the extra matmuls produce zeros
    tile_group = jnp.minimum(
        jnp.searchsorted(ends, tile_start, side="right"),
        num_groups - 1,
    ).astype(jnp.int32)
    # a tile is ACTIVE iff it holds at least one VALID row: valid rows
    # of group g occupy the prefix [offset_g, offset_g+counts_valid_g).
    # The kernels skip the MXU work of inactive tiles — this keeps the
    # padded static layout's compute proportional to the ACTUAL row
    # count (the dropless point; for gmm_ep's exact mode the worst-case
    # a2a buffers are mostly invalid rows, so skipping approaches a
    # P-fold FLOPs saving on a balanced P-way expert mesh)
    tile_active = (
        tile_start < (offsets + counts_valid)[tile_group]
    ).astype(jnp.int32)
    return {"dest": dest, "tile_group": tile_group,
            "tile_active": tile_active, "padded_len": padded_len}


def scatter_rows(rows, layout):
    """[n, D] → padded [padded_len, D] grouped layout (zeros elsewhere)."""
    out = jnp.zeros((layout["padded_len"], rows.shape[1]), rows.dtype)
    return out.at[layout["dest"]].set(rows)


def gather_rows(padded, layout):
    return padded[layout["dest"]]


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _gmm_fwd_kernel(tg_ref, ta_ref, x_ref, w_ref, y_ref):
    i = pl.program_id(0)

    # inactive tiles hold only zero padding: skip their MXU work (the
    # output block must still be WRITTEN — on hardware it is otherwise
    # uninitialized memory, not zeros). != 0 / == 0 are TOTAL: a block
    # left unwritten by non-exhaustive branches would be garbage HBM
    @pl.when(ta_ref[i] != 0)
    def _():
        y_ref[...] = jnp.dot(
            x_ref[...], w_ref[0],
            preferred_element_type=jnp.float32,
        ).astype(y_ref.dtype)

    @pl.when(ta_ref[i] == 0)
    def _():
        y_ref[...] = jnp.zeros_like(y_ref)


def _gmm_call(x, w, tile_group, tile_active, block_s, block_f, interpret):
    if pl is None:
        raise ImportError(
            "jax.experimental.pallas is unavailable in this jax install — "
            "gmm dispatch needs it (use dispatch='sparse' instead)")
    S, D = x.shape
    G, Dw, F = w.shape
    assert D == Dw, (D, Dw)
    block_f = min(block_f, F)
    if S % block_s or F % block_f:
        raise ValueError(
            "gmm needs S %% block_s == 0 and F %% block_f == 0 "
            "(S=%d bs=%d, F=%d bf=%d)" % (S, block_s, F, block_f))
    grid = (S // block_s, F // block_f)
    return pl.pallas_call(
        _gmm_fwd_kernel,
        grid_spec=_pltpu().PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_s, D), lambda i, j, tg, ta: (i, 0)),
                pl.BlockSpec((1, D, block_f),
                             lambda i, j, tg, ta: (tg[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((block_s, block_f),
                                   lambda i, j, tg, ta: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((S, F), x.dtype),
        interpret=interpret,
    )(tile_group, tile_active, x, w)


def _gmm_dw_kernel(tg_ref, ta_ref, x_ref, dy_ref, dw_ref):
    i = pl.program_id(2)
    first_of_group = jnp.logical_or(
        i == 0, tg_ref[i] != tg_ref[jnp.maximum(i - 1, 0)]
    )
    active = ta_ref[i] != 0
    # a group's real rows are a PREFIX of its tiles, so its first tile
    # is active whenever the group has any rows (empty groups own no
    # tiles and are masked by `visited` downstream): initialize on the
    # first (necessarily active) tile, accumulate on later active ones,
    # and skip the MXU entirely for padding tiles — the revisited block
    # persists untouched across skipped grid steps

    @pl.when(active)
    def _():
        tile = jnp.dot(
            x_ref[...].T, dy_ref[...], preferred_element_type=jnp.float32
        ).astype(dw_ref.dtype)

        @pl.when(first_of_group)
        def _():
            dw_ref[0] = tile

        @pl.when(jnp.logical_not(first_of_group))
        def _():
            dw_ref[0] = dw_ref[0] + tile


def _gmm_dw_call(x, dy, tile_group, tile_active, num_groups, block_s,
                 block_d, block_f, interpret):
    S, D = x.shape
    _, F = dy.shape
    block_d = min(block_d, D)
    block_f = min(block_f, F)
    if D % block_d or F % block_f:
        raise ValueError(
            "gmm dw needs D %% block_d == 0 and F %% block_f == 0 "
            "(D=%d bd=%d, F=%d bf=%d)" % (D, block_d, F, block_f))
    # i (row tiles) INNERMOST: for a fixed (d, f) the output block
    # dw[tg[i], d, f] is revisited across the consecutive i of one group
    grid = (D // block_d, F // block_f, S // block_s)
    return pl.pallas_call(
        _gmm_dw_kernel,
        grid_spec=_pltpu().PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_s, block_d),
                             lambda d, f, i, tg, ta: (i, d)),
                pl.BlockSpec((block_s, block_f),
                             lambda d, f, i, tg, ta: (i, f)),
            ],
            out_specs=pl.BlockSpec((1, block_d, block_f),
                                   lambda d, f, i, tg, ta: (tg[i], d, f)),
        ),
        out_shape=jax.ShapeDtypeStruct((num_groups, D, F), jnp.float32),
        interpret=interpret,
    )(tile_group, tile_active, x, dy)


# ---------------------------------------------------------------------------
# public op with custom vjp
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _gmm_prim(x, w, tile_group, tile_active, block_s, block_f, interpret):
    """custom_vjp primal — all args resolved/positional (custom_vjp
    cannot bind keyword-only params); the public gmm() wrapper below is
    the only caller."""
    return _gmm_call(x, w, tile_group, tile_active, block_s, block_f,
                     interpret)


def gmm(x, w, tile_group, *, tile_active=None, block_s=BLOCK_S,
        block_f=BLOCK_F, interpret=None):
    """y[i·bs:(i+1)·bs] = x[i·bs:(i+1)·bs] @ w[tile_group[i]].

    x: [S, D] grouped+padded rows (S % block_s == 0 — make_group_layout);
    w: [G, D, F]; tile_group: [S // block_s] int32;
    tile_active: [S // block_s] int32 (make_group_layout's
    `tile_active`) — tiles marked 0 hold only zero padding and SKIP
    their MXU work in forward, dx and dw (compute stays proportional to
    real rows, the dropless point). None = treat every tile as active.

    tile_active/block_s/block_f/interpret are KEYWORD-ONLY: tile_active
    was inserted before block_s at one point, so a stale positional
    caller `gmm(x, w, tg, 64)` meaning block_s=64 would silently pass 64
    as the tile mask — keyword-only turns that into an immediate
    TypeError instead.
    """
    if tile_active is None:
        tile_active = jnp.ones_like(tile_group)
    if interpret is None:
        interpret = _default_interpret()
    _check_bwd_blocks(w, block_f)
    return _gmm_prim(x, w, tile_group, tile_active, block_s, block_f,
                     interpret)


def _check_bwd_blocks(w, block_f):
    """The backward pass tiles D as a feature dim (dx) and as a reduced
    dim (dw); misconfigured shapes must fail at forward time, not when
    gradients are first taken."""
    D = w.shape[1]
    if D % min(block_f, D):
        raise ValueError(
            "gmm needs D %% min(block_f, D) == 0 (D=%d, block_f=%d): the "
            "dx backward kernel tiles D with that block" % (D, block_f))
    if D % min(BLOCK_D, D):
        raise ValueError(
            "gmm needs D %% min(%d, D) == 0 (D=%d): the dw backward "
            "kernel tiles D with that block" % (BLOCK_D, D))


def _gmm_fwd(x, w, tile_group, tile_active, block_s, block_f, interpret):
    if tile_active is None:
        tile_active = jnp.ones_like(tile_group)
    if interpret is None:
        interpret = _default_interpret()
    # under jax.grad custom_vjp routes HERE, not through the primal — the
    # misconfigured-D fail-fast must fire in the differentiated case too
    _check_bwd_blocks(w, block_f)
    y = _gmm_call(x, w, tile_group, tile_active, block_s, block_f,
                  interpret)
    return y, (x, w, tile_group, tile_active)


def _gmm_bwd(block_s, block_f, interpret, residuals, dy):
    x, w, tile_group, tile_active = residuals
    if interpret is None:
        interpret = _default_interpret()
    # dx: the same grouped matmul against w^T
    dx = _gmm_call(
        dy, jnp.swapaxes(w, 1, 2), tile_group, tile_active, block_s,
        min(block_f, w.shape[1]), interpret,
    ).astype(x.dtype)
    dw = _gmm_dw_call(
        x, dy, tile_group, tile_active, w.shape[0], block_s,
        min(BLOCK_D, w.shape[1]), block_f, interpret,
    )
    # a group whose tiles were all SKIPPED (zero real rows — including
    # the trailing clamped tiles assigned to the last group) never
    # writes its dw block — on real TPU that block is uninitialized
    # memory, not zeros (interpret mode hides this). Mask to groups
    # with at least one ACTIVE tile. where, not multiply: the unvisited
    # block may be NaN-filled (interpret) or arbitrary bits (hardware)
    visited = jnp.zeros((w.shape[0],), jnp.int32).at[tile_group].max(
        tile_active)
    dw = jnp.where(visited.astype(bool)[:, None, None], dw, 0) \
        .astype(w.dtype)
    return dx, dw, None, None


_gmm_prim.defvjp(_gmm_fwd, _gmm_bwd)


def gmm_reference(x, w, tile_group, block_s=BLOCK_S):
    """XLA oracle: one-hot tile→group selection (tests only)."""
    S, D = x.shape
    tiles = x.reshape(S // block_s, block_s, D)
    w_per_tile = w[tile_group]  # [n_tiles, D, F]
    y = jnp.einsum("tbd,tdf->tbf", tiles, w_per_tile,
                   preferred_element_type=jnp.float32)
    return y.reshape(S, w.shape[-1]).astype(x.dtype)
