"""Attention: XLA reference path + pallas TPU flash-attention forward.

The flash kernel follows the standard online-softmax blockwise algorithm
(grid over [batch*heads, q blocks]; inner fori_loop over k blocks with
running max/denominator). A custom_vjp recomputes attention blockwise with
the saved LSE on the backward pass, so the S×S score matrix is never
materialized in HBM in either direction.

Public entry: `attention(q, k, v, causal=..., impl='auto')` with GQA support
(num kv heads may divide num q heads).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

import os

from .. import knobs

# 128 is the MXU tile floor; the defaults are overridable for tuning
# sweeps (bench) and odd shapes. Combinations where one block size
# divides the other keep the causal live-block arithmetic exact.
BLOCK_Q = knobs.get_int("TPUFLOW_FLASH_BLOCK_Q")
BLOCK_K = knobs.get_int("TPUFLOW_FLASH_BLOCK_K")
NEG_INF = -1e30


def _broadcast_gqa(k, num_q_heads):
    """[B, S, Hkv, D] -> [B, S, Hq, D] by repeating kv heads."""
    num_kv = k.shape[-2]
    if num_kv == num_q_heads:
        return k
    reps = num_q_heads // num_kv
    return jnp.repeat(k, reps, axis=-2)


def shard_map_novma(fn, mesh, in_specs, out_specs):
    """shard_map with check_vma=False — pallas_call inside shard_map
    trips the vma checker's dynamic_slice rule; sharding correctness is
    still enforced by the in/out specs. Shared by the sequence-parallel
    attention variants (ring_attention.py, ulysses_attention.py)."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


def reference_attention(q, k, v, causal=True, scale=None):
    """XLA attention: [B, S, H, D] layout. Materializes S×S scores — fine for
    moderate sequence lengths; XLA fuses mask+softmax into the matmuls."""
    B, Sq, H, D = q.shape
    k = _broadcast_gqa(k, H)
    v = _broadcast_gqa(v, H)
    scale = scale or (1.0 / math.sqrt(D))
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        Sk = k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool), k=Sk - Sq)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# ---------------------------------------------------------------------------
# pallas flash forward
# ---------------------------------------------------------------------------


def _online_softmax_loop(q, k_ref, v_ref, qi, causal, block_k, seq_len,
                         scale):
    """The flash online-softmax inner loop shared by the normalized
    (single-device) and unnormalized (ring block) forward kernels.

    q: [block_q, D] in the INPUT dtype (bf16) — every MXU dot keeps bf16
    operands with f32 accumulation (the fp32 MXU path on TPU is several
    times slower, and the XLA reference computes the same bf16×bf16→f32
    contraction). The scale is applied to the f32 scores, not to q, so no
    precision is lost to a bf16 pre-scale. Returns (m, l, acc) in f32."""
    block_q, D = q.shape
    m = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc = jnp.zeros((block_q, D), dtype=jnp.float32)

    if causal:
        # only k blocks at or before the diagonal contribute
        num_kb_live = (qi * block_q) // block_k + pl.cdiv(block_q, block_k)
    else:
        num_kb_live = seq_len // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * correction + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    return jax.lax.fori_loop(0, num_kb_live, body, (m, l, acc))


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                      block_k, seq_len):
    # blocks carry a leading size-1 (batch*head) dim:
    # q_ref: [1, BLOCK_Q, D]; k_ref/v_ref: [1, S, D]
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q = q.shape[0]
    m, l, acc = _online_softmax_loop(q, k_ref, v_ref, qi, causal, block_k,
                                     seq_len, scale)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lse layout is [1, 8, S]: sublane dim padded to the fp32 tile minimum,
    # each q-block program writes its sequence slice (row 0 is the payload)
    lse_ref[0, :, pl.ds(qi * block_q, block_q)] = jnp.broadcast_to(
        (m + jnp.log(l)).reshape(1, -1), (8, block_q)
    )


try:  # pallas import is TPU/CPU-interpret capable; keep soft for portability
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False


def _flash_forward(q, k, v, causal, scale, interpret=False):
    """q,k,v: [BH, S, D] (heads folded into batch). Returns (out, lse).
    Block sizes come from the module-level BLOCK_Q/BLOCK_K (env-tunable);
    flash_attention validates them before any kernel runs."""
    BH, S, D = q.shape
    block_q = min(BLOCK_Q, S)
    block_k = min(BLOCK_K, S)
    grid = (BH, S // block_q)

    kernel = functools.partial(
        _flash_fwd_kernel,
        causal=causal,
        scale=scale,
        block_k=block_k,
        seq_len=S,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, S), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, S), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, 0, :]


def _fold_heads(x):
    # [B, S, H, D] -> [B*H, S, D]
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _unfold_heads(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, scale, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, interpret)
    return out


def _flash_attention_fwd(q, k, v, causal, scale, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, *, causal, scale, block_k, seq_len):
    """dq for one q block: iterate k blocks (≤ diagonal when causal)."""
    qi = pl.program_id(1)
    q = q_ref[0]
    g = g_ref[0]
    block_q, D = q.shape
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
    delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]

    if causal:
        num_kb = (qi * block_q) // block_k + pl.cdiv(block_q, block_k)
    else:
        num_kb = seq_len // block_k

    def body(kb, dq):
        # all MXU dots take bf16 operands with f32 accumulation; softmax
        # statistics and ds stay f32 on the VPU (see _online_softmax_loop)
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                            s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jnp.dot(ds.astype(k.dtype), k,
                            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_kb, body, jnp.zeros((block_q, D), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, causal, scale, block_q,
                          seq_len):
    """dk/dv for one k block: iterate q blocks (≥ diagonal when causal)."""
    ki = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    block_k, D = k.shape
    num_qb = seq_len // block_q
    first_qb = (ki * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        g = g_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                            s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                            s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        pb = p.astype(g.dtype)
        dv = dv + jnp.dot(pb.T, g, preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        first_qb, num_qb, body,
        (jnp.zeros((block_k, D), jnp.float32),
         jnp.zeros((block_k, D), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward_pallas(q, k, v, g, out, lse, causal, scale, interpret):
    """Pallas backward via the shared blockwise kernels (flash_block_bwd):
    dq grid over q blocks, dk/dv grid over k blocks."""
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [BH, S]
    dq, dk, dv = flash_block_bwd(q, k, v, g, lse, delta, scale, causal,
                                 interpret)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_attention_bwd(causal, scale, interpret, res, g):
    """Backward dispatch: pallas kernels when available, else the XLA
    blockwise-recompute fallback (both use the saved LSE, no S×S tensor)."""
    q, k, v, out, lse = res
    if HAS_PALLAS:
        return _flash_backward_pallas(q, k, v, g, out, lse, causal, scale,
                                      interpret)
    return _flash_attention_bwd_xla(causal, scale, res, g)


def _flash_attention_bwd_xla(causal, scale, res, g):
    """Blockwise recompute backward using the saved LSE (no S×S tensor)."""
    q, k, v, out, lse = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    BH, S, D = q.shape
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [BH, S]

    block = min(BLOCK_Q, S)
    nb = S // block

    q_pos_all = jnp.arange(S)

    def scan_q(carry, qb):
        dk, dv = carry
        qs = jax.lax.dynamic_slice_in_dim(qf, qb * block, block, axis=1)
        gs = jax.lax.dynamic_slice_in_dim(gf, qb * block, block, axis=1)
        lses = jax.lax.dynamic_slice_in_dim(lse, qb * block, block, axis=1)
        deltas = jax.lax.dynamic_slice_in_dim(delta, qb * block, block, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qs * scale, kf,
                       preferred_element_type=jnp.float32)
        if causal:
            qpos = qb * block + q_pos_all[:block]
            mask = qpos[:, None] >= q_pos_all[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - lses[..., None])
        dp = jnp.einsum("bqd,bkd->bqk", gs, vf,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - deltas[..., None]) * scale
        dq_b = jnp.einsum("bqk,bkd->bqd", ds, kf,
                          preferred_element_type=jnp.float32)
        dk = dk + jnp.einsum("bqk,bqd->bkd", ds, qs,
                             preferred_element_type=jnp.float32)
        dv = dv + jnp.einsum("bqk,bqd->bkd", p, gs,
                             preferred_element_type=jnp.float32)
        return (dk, dv), dq_b

    (dk, dv), dq_blocks = jax.lax.scan(
        scan_q, (jnp.zeros_like(kf), jnp.zeros_like(vf)), jnp.arange(nb)
    )
    dq = dq_blocks.transpose(1, 0, 2, 3).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q, k, v, causal=True, scale=None, interpret=False):
    """Pallas flash attention; q,k,v: [B, S, H, D] (kv heads may be fewer).

    Requires S to be a multiple of the 128 block size (the `attention`
    dispatcher falls back to the XLA path otherwise)."""
    if not HAS_PALLAS:
        raise RuntimeError(
            "flash_attention requires pallas (jax.experimental.pallas); "
            "use attention(impl='auto') for an XLA fallback"
        )
    B, S, H, D = q.shape
    block_q, block_k = _check_blocks(S)
    k = _broadcast_gqa(k, H)
    v = _broadcast_gqa(v, H)
    scale = scale or (1.0 / math.sqrt(D))
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    out = _flash_attention(qf, kf, vf, causal, scale, interpret)
    return _unfold_heads(out, B, H)


# ---------------------------------------------------------------------------
# blockwise building blocks for ring attention (ops/ring_attention.py)
# ---------------------------------------------------------------------------


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-axes (vma) annotation —
    required for pallas_call outputs under shard_map with check_vma."""
    try:
        vma = jax.typeof(like).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_block_fwd_kernel(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                            causal, scale, block_k, seq_len):
    """Flash forward WITHOUT final normalization, emitting the online-softmax
    stats (m, l) — the ring combiner merges contributions across ring hops.
    causal=True means the same-offset diagonal mask (q and k blocks are the
    same sequence shard); causal=False means every k position contributes."""
    qi = pl.program_id(1)
    q = q_ref[0]
    block_q = q.shape[0]
    m, l, acc = _online_softmax_loop(q, k_ref, v_ref, qi, causal, block_k,
                                     seq_len, scale)
    acc_ref[0] = acc
    m_ref[0, :, pl.ds(qi * block_q, block_q)] = jnp.broadcast_to(
        m.reshape(1, -1), (8, block_q)
    )
    l_ref[0, :, pl.ds(qi * block_q, block_q)] = jnp.broadcast_to(
        l.reshape(1, -1), (8, block_q)
    )


def blocks_aligned(S):
    """True when seq len S satisfies the flash-kernel contract with the
    effective block sizes: S divisible by both blocks (a fori_loop bound
    of seq_len // block_k silently drops the k tail otherwise) and mutual
    block divisibility (the causal live-block count is exact only then).
    Single source of truth for both the kernels and the auto-dispatchers
    here and in ring_attention."""
    bq, bk = min(BLOCK_Q, S), min(BLOCK_K, S)
    return (S % bq == 0 and S % bk == 0
            and (bq % bk == 0 or bk % bq == 0))


def _check_blocks(S):
    """Effective (block_q, block_k) for seq len S; raises on a
    blocks_aligned violation — raising beats returning wrong attention
    output with no error. The decision is blocks_aligned itself (one
    predicate for dispatchers and kernels); only the message is derived
    here."""
    block_q = min(BLOCK_Q, S)
    block_k = min(BLOCK_K, S)
    if not blocks_aligned(S):
        if S % block_q or S % block_k:
            raise ValueError(
                "flash block kernels require seq len divisible by the "
                "%d/%d block sizes (got %d); use the xla impl or pad the "
                "sequence" % (BLOCK_Q, BLOCK_K, S)
            )
        raise ValueError(
            "flash attention block sizes must divide one another (got "
            "q=%d, k=%d via TPUFLOW_FLASH_BLOCK_Q/K)" % (block_q, block_k)
        )
    return block_q, block_k


def flash_block_fwd(q, k, v, scale, causal_diag, interpret=False):
    """One ring step's unnormalized contribution.

    q, k, v: [BH, S, D] (heads folded). Returns (acc f32 [BH,S,D],
    m f32 [BH,S], l f32 [BH,S])."""
    BH, S, D = q.shape
    block_q, block_k = _check_blocks(S)
    acc, m, l = pl.pallas_call(
        functools.partial(
            _flash_block_fwd_kernel,
            causal=causal_diag,
            scale=scale,
            block_k=block_k,
            seq_len=S,
        ),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, S), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 8, S), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            _sds((BH, S, D), jnp.float32, q),
            _sds((BH, 8, S), jnp.float32, q),
            _sds((BH, 8, S), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v)
    return acc, m[:, 0, :], l[:, 0, :]


def flash_block_bwd(q, k, v, g, lse, delta, scale, causal_diag,
                    interpret=False):
    """One ring step's gradient contribution given the GLOBAL lse/delta.

    Same kernels as the single-device flash backward — the global stats make
    each blockwise p exact, so contributions just sum across ring hops.
    Returns (dq, dk, dv) in f32, shapes [BH, S, D]."""
    BH, S, D = q.shape
    block_q, block_k = _check_blocks(S)
    lse_t = jnp.broadcast_to(lse[:, None, :], (BH, 8, S))
    delta_t = jnp.broadcast_to(delta[:, None, :], (BH, 8, S))
    stats_spec = pl.BlockSpec((1, 8, S), lambda b, i: (b, 0, 0))
    full_spec = pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, causal=causal_diag, scale=scale,
            block_k=block_k, seq_len=S,
        ),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            full_spec,
            full_spec,
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            stats_spec,
            stats_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=_sds((BH, S, D), jnp.float32, q),
        interpret=interpret,
    )(q, k, v, g, lse_t, delta_t)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, causal=causal_diag, scale=scale,
            block_q=block_q, seq_len=S,
        ),
        grid=(BH, S // block_k),
        in_specs=[
            full_spec,
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            full_spec,
            stats_spec,
            stats_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((BH, S, D), jnp.float32, q),
            _sds((BH, S, D), jnp.float32, q),
        ],
        interpret=interpret,
    )(q, k, v, g, lse_t, delta_t)
    return dq, dk, dv


def attention(q, k, v, causal=True, scale=None, impl="auto"):
    """Dispatch: pallas flash on TPU when shapes tile cleanly, XLA otherwise."""
    if impl == "auto":
        S, D = q.shape[1], q.shape[3]
        on_tpu = jax.default_backend() == "tpu"
        aligned = blocks_aligned(S) and D % 128 == 0
        impl = "flash" if (HAS_PALLAS and on_tpu and aligned) else "xla"
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal, scale=scale)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=True)
    return reference_attention(q, k, v, causal=causal, scale=scale)
