"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context path (SURVEY.md §5.7): the sequence is sharded across the
'sequence' mesh axis; each device holds a [B, S/N, H, D] shard of q/k/v. K/V
blocks rotate around the ring via lax.ppermute while each device accumulates
blockwise attention with an online softmax — compute overlaps the collective,
total memory stays O(S/N), and the ppermute hops ride neighbouring ICI links.

Use inside shard_map (ring_attention_sharded builds it for a mesh).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, q_offset, k_offset, causal):
    """One blockwise attention contribution + online-softmax stats.

    q: [B, Sq, H, D]; k/v: [B, Sk, Hkv, D] — GQA broadcast happens HERE,
    after the ring hop, so ppermute only ever moves kv-head-width blocks.
    Returns (unnormalized out [B,Sq,H,D] in f32, m [B,H,Sq], l [B,H,Sq]).
    """
    H = q.shape[2]
    if k.shape[2] != H:
        reps = H // k.shape[2]
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(Sq)[:, None]
        k_pos = k_offset + jnp.arange(Sk)[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out, m, l


def _ring_attention_local(q, k, v, axis_name, causal=True, scale=None):
    """Body run per-device under shard_map."""
    B, S_local, H, D = q.shape
    scale = scale or (1.0 / math.sqrt(D))
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q_offset = my_idx * S_local

    # derive the accumulators from q so they carry q's varying-axes (vma)
    # annotation — a plain jnp.zeros would be 'unvarying' and fail the scan
    # carry type check under shard_map
    zero_q = q.astype(jnp.float32) * 0.0
    acc = zero_q
    m_run = zero_q[..., 0].transpose(0, 2, 1) + NEG_INF
    l_run = zero_q[..., 0].transpose(0, 2, 1)

    def step(carry, r):
        acc, m_run, l_run, k_cur, v_cur = carry
        # k block currently held came from device (my_idx - r) mod N
        src = (my_idx - r) % axis_size
        k_offset = src * S_local
        out_b, m_b, l_b = _block_attn(
            q, k_cur, v_cur, scale, q_offset, k_offset, causal
        )
        m_new = jnp.maximum(m_run, m_b)
        c_run = jnp.exp(m_run - m_new)
        c_b = jnp.exp(m_b - m_new)
        l_new = l_run * c_run + l_b * c_b
        acc = acc * c_run.transpose(0, 2, 1)[..., None] + \
            out_b * c_b.transpose(0, 2, 1)[..., None]
        # rotate k/v to the next device (overlaps with next iteration's
        # compute under XLA latency hiding)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    (acc, m_run, l_run, _, _), _ = jax.lax.scan(
        step, (acc, m_run, l_run, k, v), jnp.arange(axis_size)
    )
    out = acc / l_run.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(mesh, axis_name="sequence", causal=True,
                           scale=None):
    """Build a sharded ring-attention fn for [B, S, H, D] inputs with S split
    over `axis_name` (batch over data axes when present)."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes or None, axis_name, None, None)

    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )


def ring_attention(q, k, v, mesh, axis_name="sequence", causal=True,
                   scale=None):
    return ring_attention_sharded(mesh, axis_name, causal, scale)(q, k, v)
