"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context path (SURVEY.md §5.7): the sequence is sharded across the
'sequence' mesh axis; each device holds a [B, S/N, H, D] shard of q/k/v. K/V
blocks rotate around the ring via lax.ppermute while each device accumulates
blockwise attention with an online softmax — compute overlaps the collective,
total memory stays O(S/N), and the ppermute hops ride neighbouring ICI links.

Two inner-block implementations:
- 'flash' (default on TPU): the pallas flash kernels (ops/attention.py) run
  each ring step's block unnormalized, emitting online-softmax stats that
  the ring combiner merges — no S_local x S_local score tensor ever exists.
  The backward is a second ring pass: dk/dv accumulators travel WITH their
  rotating k/v blocks and arrive home after N hops (the standard ring-flash
  backward), with all blockwise probabilities made exact by the global LSE.
- 'xla': einsum blocks (materializes per-hop scores; CPU/debug fallback).

Ring-causal masking is static per branch: a hop's source shard is either
entirely before my shard (full attention), my own shard (diagonal causal
mask), or after it (skipped) — lax.switch picks the branch, so the pallas
kernels compile once per variant with no dynamic offsets.

Use inside shard_map (ring_attention_sharded builds it for a mesh).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import knobs

from .attention import (
    BLOCK_K,
    BLOCK_Q,
    HAS_PALLAS,
    _broadcast_gqa,
    _fold_heads,
    _unfold_heads,
    blocks_aligned,
    flash_block_bwd,
    flash_block_fwd,
)

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# xla inner block (fallback / debug)
# ---------------------------------------------------------------------------


def _block_attn(q, k, v, scale, q_offset, k_offset, causal):
    """One blockwise attention contribution + online-softmax stats.

    q: [B, Sq, H, D]; k/v: [B, Sk, Hkv, D] — GQA broadcast happens HERE,
    after the ring hop, so ppermute only ever moves kv-head-width blocks.
    Returns (unnormalized out [B,Sq,H,D] in f32, m [B,H,Sq], l [B,H,Sq]).
    """
    H = q.shape[2]
    if k.shape[2] != H:
        reps = H // k.shape[2]
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(Sq)[:, None]
        k_pos = k_offset + jnp.arange(Sk)[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out, m, l


def _ring_attention_local_xla(q, k, v, axis_name, causal=True, scale=None):
    """Body run per-device under shard_map (einsum inner block)."""
    B, S_local, H, D = q.shape
    scale = scale or (1.0 / math.sqrt(D))
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q_offset = my_idx * S_local

    # derive the accumulators from q so they carry q's varying-axes (vma)
    # annotation — a plain jnp.zeros would be 'unvarying' and fail the scan
    # carry type check under shard_map
    zero_q = q.astype(jnp.float32) * 0.0
    acc = zero_q
    m_run = zero_q[..., 0].transpose(0, 2, 1) + NEG_INF
    l_run = zero_q[..., 0].transpose(0, 2, 1)

    def step(carry, r):
        acc, m_run, l_run, k_cur, v_cur = carry
        # k block currently held came from device (my_idx - r) mod N
        src = (my_idx - r) % axis_size
        k_offset = src * S_local
        out_b, m_b, l_b = _block_attn(
            q, k_cur, v_cur, scale, q_offset, k_offset, causal
        )
        m_new = jnp.maximum(m_run, m_b)
        c_run = jnp.exp(m_run - m_new)
        c_b = jnp.exp(m_b - m_new)
        l_new = l_run * c_run + l_b * c_b
        acc = acc * c_run.transpose(0, 2, 1)[..., None] + \
            out_b * c_b.transpose(0, 2, 1)[..., None]
        # rotate k/v to the next device (overlaps with next iteration's
        # compute under XLA latency hiding)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    (acc, m_run, l_run, _, _), _ = jax.lax.scan(
        step, (acc, m_run, l_run, k, v), jnp.arange(axis_size)
    )
    out = acc / l_run.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# pallas flash inner block with a ring backward pass
# ---------------------------------------------------------------------------


def _ring_branch_index(src, my_idx):
    """0 = diagonal (own shard: causal mask), 1 = full (earlier shard),
    2 = skip (later shard contributes nothing under causality)."""
    return jnp.where(src == my_idx, 0, jnp.where(src < my_idx, 1, 2))


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, interpret):
    B, S, H, D = q.shape
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    qf = _fold_heads(q)  # [BH, S, D]

    zero = qf.astype(jnp.float32) * 0.0
    acc = zero
    m_run = zero[..., 0] + NEG_INF  # [BH, S]
    l_run = zero[..., 0]

    def step(carry, r):
        acc, m_run, l_run, k_cur, v_cur = carry
        src = (my_idx - r) % axis_size
        kb = _fold_heads(_broadcast_gqa(k_cur, H))
        vb = _fold_heads(_broadcast_gqa(v_cur, H))

        def diag(_):
            return flash_block_fwd(qf, kb, vb, scale, True, interpret)

        def full(_):
            return flash_block_fwd(qf, kb, vb, scale, False, interpret)

        def skip(_):
            return acc * 0.0, m_run * 0.0 + NEG_INF, l_run * 0.0

        if causal:
            acc_b, m_b, l_b = jax.lax.switch(
                _ring_branch_index(src, my_idx), [diag, full, skip], None
            )
        else:
            acc_b, m_b, l_b = full(None)

        m_new = jnp.maximum(m_run, m_b)
        c_run = jnp.exp(m_run - m_new)
        c_b = jnp.exp(m_b - m_new)
        l_new = l_run * c_run + l_b * c_b
        acc = acc * c_run[..., None] + acc_b * c_b[..., None]
        p = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, p)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, p)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    (acc, m_run, l_run, _, _), _ = jax.lax.scan(
        step, (acc, m_run, l_run, k, v), jnp.arange(axis_size)
    )
    out = (acc / l_run[..., None]).astype(q.dtype)  # [BH, S, D]
    lse = m_run + jnp.log(l_run)  # [BH, S]
    return _unfold_heads(out, B, H), lse


def _reduce_gqa_grad(d_folded, B, H, Hkv):
    """[B*H, S, D] broadcast-head grads -> [B, S, Hkv, D] by summing the
    repeated query heads back onto their kv head."""
    BH, S, D = d_folded.shape
    reps = H // Hkv
    d = d_folded.reshape(B, Hkv, reps, S, D).sum(axis=2)  # [B, Hkv, S, D]
    return d.transpose(0, 2, 1, 3)  # [B, S, Hkv, D]


def _ring_flash_bwd_impl(q, k, v, out, lse, g, axis_name, causal, scale,
                         interpret):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    qf = _fold_heads(q)
    gf = _fold_heads(g).astype(jnp.float32)
    of = _fold_heads(out).astype(jnp.float32)
    delta = jnp.sum(gf * of, axis=-1)  # [BH, S]

    dq = qf.astype(jnp.float32) * 0.0
    dk_acc = k.astype(jnp.float32) * 0.0  # travels with k_cur
    dv_acc = v.astype(jnp.float32) * 0.0

    def step(carry, r):
        dq, dk_acc, dv_acc, k_cur, v_cur = carry
        src = (my_idx - r) % axis_size
        kb = _fold_heads(_broadcast_gqa(k_cur, H))
        vb = _fold_heads(_broadcast_gqa(v_cur, H))

        def diag(_):
            return flash_block_bwd(qf, kb, vb, gf, lse, delta, scale, True,
                                   interpret)

        def full(_):
            return flash_block_bwd(qf, kb, vb, gf, lse, delta, scale, False,
                                   interpret)

        def skip(_):
            z = dq * 0.0
            return z, z, z

        if causal:
            dq_b, dk_b, dv_b = jax.lax.switch(
                _ring_branch_index(src, my_idx), [diag, full, skip], None
            )
        else:
            dq_b, dk_b, dv_b = full(None)

        dq = dq + dq_b
        # this hop's dk/dv belong to the kv block currently held: accumulate
        # into the buffers that rotate WITH the block — after N hops every
        # block is home carrying its full gradient
        dk_acc = dk_acc + _reduce_gqa_grad(dk_b, B, H, Hkv)
        dv_acc = dv_acc + _reduce_gqa_grad(dv_b, B, H, Hkv)
        p = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, p)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, p)
        dk_nxt = jax.lax.ppermute(dk_acc, axis_name, p)
        dv_nxt = jax.lax.ppermute(dv_acc, axis_name, p)
        return (dq, dk_nxt, dv_nxt, k_nxt, v_nxt), None

    (dq, dk_acc, dv_acc, _, _), _ = jax.lax.scan(
        step, (dq, dk_acc, dv_acc, k, v), jnp.arange(axis_size)
    )
    return (
        _unfold_heads(dq, B, H).astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, scale, interpret):
    out, _lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                     interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale, interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, interpret, res, g):
    q, k, v, out, lse = res
    return _ring_flash_bwd_impl(q, k, v, out, lse, g, axis_name, causal,
                                scale, interpret)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def _ring_attention_local_flash(q, k, v, axis_name, causal=True, scale=None,
                                interpret=False):
    scale = scale or (1.0 / math.sqrt(q.shape[-1]))
    return _ring_flash(q, k, v, axis_name, causal, scale, interpret)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _resolve_impl(impl, S_local):
    if impl == "auto":
        impl = knobs.get_str("TPUFLOW_RING_IMPL")
    # same predicate flash_block_fwd/bwd enforce — single source of truth
    aligned = blocks_aligned(S_local)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = "flash" if (HAS_PALLAS and on_tpu and aligned) else "xla"
    if impl in ("flash", "flash_interpret") and not aligned:
        # an explicitly requested flash impl must not silently drop the
        # unaligned tail (grid floor-division would leave rows unwritten)
        raise ValueError(
            "ring flash attention needs the per-device sequence shard "
            "(%d) to be a multiple of both block sizes (q=%d, k=%d via "
            "TPUFLOW_FLASH_BLOCK_Q/K), with one block dividing the "
            "other; use impl='xla' or pad the sequence"
            % (S_local, min(BLOCK_Q, S_local), min(BLOCK_K, S_local))
        )
    return impl


def ring_attention_sharded(mesh, axis_name="sequence", causal=True,
                           scale=None, impl="auto"):
    """Build a sharded ring-attention fn for [B, S, H, D] inputs with S split
    over `axis_name` (batch over data axes when present).

    impl: 'auto' | 'flash' | 'flash_interpret' | 'xla' (or env
    TPUFLOW_RING_IMPL). 'flash' needs the per-device sequence shard to be
    a multiple of the pallas block size (BLOCK_Q, 128).
    """
    from .attention import shard_map_novma

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes or None, axis_name, None, None)

    def dispatch(q, k, v):
        S_local = q.shape[1]
        chosen = _resolve_impl(impl, S_local)
        if chosen in ("flash", "flash_interpret"):
            return _ring_attention_local_flash(
                q, k, v, axis_name, causal=causal, scale=scale,
                interpret=(chosen == "flash_interpret"),
            )
        return _ring_attention_local_xla(
            q, k, v, axis_name, causal=causal, scale=scale
        )

    return shard_map_novma(dispatch, mesh, in_specs=(spec, spec, spec),
                           out_specs=spec)


def ring_attention(q, k, v, mesh, axis_name="sequence", causal=True,
                   scale=None, impl="auto"):
    return ring_attention_sharded(mesh, axis_name, causal, scale, impl)(
        q, k, v
    )
