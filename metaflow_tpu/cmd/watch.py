"""`tpuflow watch` — live watchtower over a run's telemetry stream.

Tails the run's `_telemetry/` part files incrementally
(telemetry.TelemetryTail: a path-cursor delta over list_content — each
refresh loads only part files that appeared since the last one, instead
of the full re-read `read_run_records` does) and renders a rolling view:

  train  tok/s, MFU, input-stall fraction, worst-rank straggler skew
  serve  queue depth, slot occupancy, rolling p50/p99 TTFT and
         inter-token latency, delivered tok/s
  fleet  replicas ready, flaps (deaths), restart rate

`--once` renders a single frame and exits (tests / cron). `--check`
additionally evaluates the configured SLO rules (slo.load_rules: JSON
file or TPUFLOW_SLO_* env) against the live metrics and exits non-zero
on any breach — or on a pinned `slo.breach` event already persisted by
the fleet supervisor — so CI can gate on a run's health.
"""

import json
import time
from collections import deque

from .. import slo as slo_rules_mod
from .. import telemetry

SNAPSHOT_VERSION = 1


def _mean(vals):
    return sum(vals) / len(vals) if vals else 0.0


def _pctl(vals, q):
    if not vals:
        return 0.0
    ordered = sorted(vals)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return float(ordered[idx])


class WatchState(object):
    """Rolling aggregation of a telemetry record stream. Bounded
    windows: a watch session over a week-long run must not grow."""

    def __init__(self, window=256):
        self.records_total = 0
        self.last_ts = 0.0
        # train
        self._step_ms = deque(maxlen=window)
        self._stall_ms = deque(maxlen=window)
        self._tok_s = deque(maxlen=window)
        self._mfu = deque(maxlen=window)
        self._rank_ms = {}            # rank -> deque of recent step ms
        self.last_step_num = None
        # serve
        self.queue_depth = None
        self.occupancy = None
        self._ttft_ms = deque(maxlen=window)
        self._itl_ms = deque(maxlen=window * 4)
        self._served = deque(maxlen=window * 2)   # (ts, new_tokens)
        # prefix cache (radix KV reuse)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.prefix_prompt_tokens = 0
        self.prefix_evictions = 0
        # paged KV pool + speculative decoding
        self.kv_occupancy = None
        self.kv_cow_pages = None
        self.kv_shares = 0
        self.kv_exhausted = 0
        self.spec_accept_rate = None
        # multi-tenant admission (serve.tenant.* + tenant-tagged
        # request events): tenant id -> rolling counters + TTFT window
        self._tenants = {}
        self._tenant_window = window
        # fleet
        self.replicas_ready = None
        self.replica_flaps = 0
        self._restart_ts = deque(maxlen=64)
        self.scale_outs = 0
        self.scale_ins = 0
        self.last_rollout = None      # latest fleet.rollout event data
        # incidents
        self.desync_count = 0
        self.flush_failures = 0
        self.hang_count = 0
        self.last_hang = None         # latest hang.detected event data
        self.breach_events = []       # persisted slo.breach records

    def _tenant(self, tid):
        t = self._tenants.get(tid)
        if t is None:
            t = self._tenants[tid] = {
                "admitted": 0, "throttled": 0, "shed": 0,
                "queue_depth": None,
                "_ttft": deque(maxlen=self._tenant_window)}
        return t

    def ingest(self, records):
        for rec in records:
            self.records_total += 1
            ts = rec.get("ts", 0.0)
            if ts > self.last_ts:
                self.last_ts = ts
            name = rec.get("name", "")
            rtype = rec.get("type")
            data = rec.get("data") or {}
            if rtype == "timer" and name.endswith(".step") \
                    and rec.get("step_num") is not None:
                ms = rec.get("ms")
                if ms is not None:
                    self._step_ms.append(ms)
                    self._rank_ms.setdefault(
                        rec.get("rank") or 0,
                        deque(maxlen=32)).append(ms)
                self.last_step_num = rec.get("step_num")
                if data.get("input_stall_ms") is not None:
                    self._stall_ms.append(data["input_stall_ms"])
                if data.get("tokens_per_sec") is not None:
                    self._tok_s.append(data["tokens_per_sec"])
                if data.get("mfu") is not None:
                    self._mfu.append(data["mfu"])
            elif rtype == "gauge":
                if name == "serve.queue_depth":
                    self.queue_depth = rec.get("value")
                elif name == "serve.batch_occupancy":
                    self.occupancy = rec.get("value")
                elif name == "fleet.replicas_ready":
                    self.replicas_ready = rec.get("value")
                elif name == "serve.kv.page_occupancy":
                    self.kv_occupancy = rec.get("value")
                elif name == "serve.kv.cow_pages":
                    self.kv_cow_pages = rec.get("value")
                elif name == "serve.spec.accept_rate":
                    self.spec_accept_rate = rec.get("value")
                elif name == "serve.tenant.queue_depth":
                    self._tenant(
                        data.get("tenant")
                        or "default")["queue_depth"] = rec.get("value")
            elif rtype == "event":
                if name == "serve.request.first_token":
                    if data.get("ttft_ms") is not None:
                        self._ttft_ms.append(data["ttft_ms"])
                        if data.get("tenant"):
                            self._tenant(data["tenant"])["_ttft"].append(
                                data["ttft_ms"])
                elif name == "serve.request.finished":
                    new = data.get("new_tokens") or 0
                    self._served.append((ts, new))
                    ttft = data.get("ttft_ms")
                    total = data.get("total_ms")
                    if ttft is not None and total is not None and new > 1:
                        self._itl_ms.append((total - ttft) / (new - 1))
                elif name == "serve.prefix.hit":
                    self.prefix_hits += 1
                    self.prefix_hit_tokens += \
                        data.get("matched_tokens") or 0
                    self.prefix_prompt_tokens += \
                        data.get("prompt_tokens") or 0
                elif name == "serve.prefix.miss":
                    self.prefix_misses += 1
                    self.prefix_prompt_tokens += \
                        data.get("prompt_tokens") or 0
                elif name == "serve.prefix.evict":
                    self.prefix_evictions += data.get("nodes") or 0
                elif name == "serve.tenant.admitted":
                    self._tenant(
                        data.get("tenant") or "default")["admitted"] += 1
                elif name == "serve.tenant.throttled":
                    self._tenant(
                        data.get("tenant") or "default")["throttled"] += 1
                elif name == "serve.tenant.shed":
                    self._tenant(
                        data.get("tenant") or "default")["shed"] += 1
                elif name == "fleet.request.shed":
                    # only tenant-scoped router denials attribute here;
                    # anonymous capacity sheds stay fleet-level
                    if data.get("tenant"):
                        self._tenant(data["tenant"])["shed"] += 1
                elif name == "serve.kv.page_shared":
                    self.kv_shares += 1
                elif name == "serve.kv.exhausted":
                    self.kv_exhausted += 1
                elif name == "fleet.replica.dead":
                    self.replica_flaps += 1
                elif name == "fleet.replica.restart":
                    self._restart_ts.append(ts)
                elif name == "fleet.scale_out":
                    self.scale_outs += 1
                elif name == "fleet.scale_in":
                    self.scale_ins += 1
                elif name == "fleet.rollout":
                    self.last_rollout = data
                elif name == "sanitize.desync":
                    self.desync_count += 1
                elif name == "hang.detected":
                    self.hang_count += 1
                    self.last_hang = data
                elif name == "slo.breach":
                    self.breach_events.append(rec)
            elif rtype == "counter" and name == "telemetry.flush_failed":
                self.flush_failures += rec.get("inc") or 1

    def metrics(self):
        """The SLO rule vocabulary (slo.ENV_RULES) + render inputs.
        Latency percentiles are present only once samples exist, so an
        idle server is not 'in breach of 0ms'."""
        m = {
            "records": self.records_total,
            "replica_flaps": self.replica_flaps,
            "desync_count": float(self.desync_count),
            "flush_failures": self.flush_failures,
            "hang_count": float(self.hang_count),
        }
        # restart rate over the final observed minute (record-clock, so
        # it works identically on live and finished runs)
        if self.last_ts:
            recent = [t for t in self._restart_ts
                      if self.last_ts - t <= 60.0]
            m["replica_restart_rate_per_min"] = float(len(recent))
        if self._step_ms:
            m["step_ms"] = round(_mean(self._step_ms), 3)
            if self._stall_ms:
                m["input_stall_frac"] = round(
                    _mean(self._stall_ms) / max(1e-9,
                                                _mean(self._step_ms)), 4)
        if self._tok_s:
            m["train_tokens_per_sec"] = round(_mean(self._tok_s), 1)
        if self._mfu:
            m["mfu"] = round(_mean(self._mfu), 4)
        if len(self._rank_ms) > 1:
            means = sorted(_mean(d) for d in self._rank_ms.values())
            median = means[len(means) // 2]
            if median > 0:
                m["straggler_skew"] = round(means[-1] / median, 3)
        if self._ttft_ms:
            m["p50_ttft_ms"] = round(_pctl(self._ttft_ms, 0.50), 3)
            m["p99_ttft_ms"] = round(_pctl(self._ttft_ms, 0.99), 3)
        if self._itl_ms:
            m["p50_itl_ms"] = round(_pctl(self._itl_ms, 0.50), 3)
            m["p99_itl_ms"] = round(_pctl(self._itl_ms, 0.99), 3)
        if len(self._served) > 1:
            span = self._served[-1][0] - self._served[0][0]
            if span > 0:
                m["serve_tokens_per_sec"] = round(
                    sum(n for _t, n in self._served) / span, 1)
        looked_up = self.prefix_hits + self.prefix_misses
        if looked_up:
            m["prefix_hit_rate"] = round(self.prefix_hits / looked_up, 4)
            m["prefix_tokens_skipped_frac"] = round(
                self.prefix_hit_tokens
                / max(1, self.prefix_prompt_tokens), 4)
        if self.kv_occupancy is not None:
            m["kv_page_occupancy"] = round(float(self.kv_occupancy), 4)
        if self.spec_accept_rate is not None:
            m["spec_accept_rate"] = round(float(self.spec_accept_rate), 4)
        # per-tenant TTFT percentiles use the SAME metric names the
        # fleet SLO loop exposes, so slo.tenant_rules() applies the
        # TPUFLOW_SLO_TENANT_P99_TTFT_MS bound to watch --check too
        for tid, t in self._tenants.items():
            if t["_ttft"]:
                m["tenant.%s.p50_ttft_ms" % tid] = round(
                    _pctl(t["_ttft"], 0.50), 3)
                m["tenant.%s.p99_ttft_ms" % tid] = round(
                    _pctl(t["_ttft"], 0.99), 3)
        return m

    def tenant_rollup(self):
        """Per-tenant admission counters for the snapshot/frame."""
        return {
            tid: {"admitted": t["admitted"],
                  "throttled": t["throttled"],
                  "shed": t["shed"],
                  "queue_depth": t["queue_depth"]}
            for tid, t in sorted(self._tenants.items())}

    def snapshot(self, run_id, breaches=()):
        """One machine-readable frame: the same data render_frame
        prints, as one JSON document per poll (`tpuflow watch --json`).
        Schema pinned in tests/schema_validate.py::WATCH_SNAPSHOT_SCHEMA."""
        return {
            "v": SNAPSHOT_VERSION,
            "run_id": str(run_id),
            "records": self.records_total,
            "last_ts": self.last_ts,
            "last_step_num": self.last_step_num,
            "metrics": self.metrics(),
            "serve": {
                "queue_depth": self.queue_depth,
                "occupancy": self.occupancy,
            },
            "tenants": self.tenant_rollup(),
            "prefix": {
                "hits": self.prefix_hits,
                "misses": self.prefix_misses,
                "evictions": self.prefix_evictions,
            },
            "kv": {
                "occupancy": self.kv_occupancy,
                "cow_pages": self.kv_cow_pages,
                "shares": self.kv_shares,
                "exhausted": self.kv_exhausted,
                "spec_accept_rate": self.spec_accept_rate,
            },
            "fleet": {
                "replicas_ready": self.replicas_ready,
                "replica_flaps": self.replica_flaps,
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "rollout": self.last_rollout,
            },
            "incidents": {
                "desync": self.desync_count,
                "flush_failures": self.flush_failures,
                "hangs": self.hang_count,
                "last_hang": self.last_hang,
            },
            "breaches": [dict(b) for b in breaches],
            "breach_events": [rec.get("data") or {}
                              for rec in self.breach_events],
        }


def render_frame(state, run_id, breaches=(), echo=print):
    m = state.metrics()
    head = "watch %s  %d record(s)" % (run_id, state.records_total)
    if state.last_step_num is not None:
        head += "  step %s" % state.last_step_num
    echo(head)
    if "step_ms" in m:
        line = "  train: %.1f ms/step" % m["step_ms"]
        if "train_tokens_per_sec" in m:
            line += "  %.0f tok/s" % m["train_tokens_per_sec"]
        if "mfu" in m:
            line += "  mfu %.1f%%" % (m["mfu"] * 100)
        if "input_stall_frac" in m:
            line += "  stall %.1f%%" % (m["input_stall_frac"] * 100)
        if "straggler_skew" in m:
            line += "  skew x%.2f" % m["straggler_skew"]
        echo(line)
    if state.queue_depth is not None or "p50_ttft_ms" in m:
        line = "  serve: queue %s  occupancy %s" % (
            state.queue_depth if state.queue_depth is not None else "-",
            ("%.2f" % state.occupancy)
            if state.occupancy is not None else "-")
        if "p50_ttft_ms" in m:
            line += "  ttft p50/p99 %.1f/%.1f ms" % (
                m["p50_ttft_ms"], m["p99_ttft_ms"])
        if "p50_itl_ms" in m:
            line += "  itl p50/p99 %.1f/%.1f ms" % (
                m["p50_itl_ms"], m["p99_itl_ms"])
        if "serve_tokens_per_sec" in m:
            line += "  %.0f tok/s" % m["serve_tokens_per_sec"]
        echo(line)
    for tid, t in state.tenant_rollup().items():
        line = "  tenant %s: admitted %d  throttled %d  shed %d" % (
            tid, t["admitted"], t["throttled"], t["shed"])
        if t["queue_depth"] is not None:
            line += "  queue %s" % t["queue_depth"]
        p50 = m.get("tenant.%s.p50_ttft_ms" % tid)
        p99 = m.get("tenant.%s.p99_ttft_ms" % tid)
        if p50 is not None and p99 is not None:
            line += "  ttft p50/p99 %.1f/%.1f ms" % (p50, p99)
        echo(line)
    if "prefix_hit_rate" in m or state.prefix_evictions:
        echo("  prefix: hit rate %.0f%%  prefill skipped %.0f%%  "
             "evictions %d" % (
                 m.get("prefix_hit_rate", 0.0) * 100,
                 m.get("prefix_tokens_skipped_frac", 0.0) * 100,
                 state.prefix_evictions))
    if state.kv_occupancy is not None or state.kv_exhausted:
        line = "  kv: pages %.0f%%" % (
            (state.kv_occupancy or 0.0) * 100)
        if state.kv_cow_pages is not None:
            line += "  cow %d" % int(state.kv_cow_pages)
        line += "  shares %d  exhausted %d" % (state.kv_shares,
                                               state.kv_exhausted)
        if state.spec_accept_rate is not None:
            line += "  spec accept %.0f%%" % (
                state.spec_accept_rate * 100)
        echo(line)
    if state.replicas_ready is not None or state.replica_flaps:
        line = "  fleet: ready %s  flaps %d  restarts/min %s" % (
            state.replicas_ready
            if state.replicas_ready is not None else "-",
            state.replica_flaps,
            m.get("replica_restart_rate_per_min", 0.0))
        if state.scale_outs or state.scale_ins:
            line += "  scale +%d/-%d" % (state.scale_outs,
                                         state.scale_ins)
        echo(line)
    if state.last_rollout is not None:
        ro = state.last_rollout
        echo("  rollout: gen %s %s%s" % (
            ro.get("fleet_generation"), ro.get("phase"),
            ("  (%s replaced, %s shed)"
             % (ro.get("replaced"), ro.get("shed_requests")))
            if ro.get("phase") == "done" else ""))
    if state.desync_count or state.flush_failures or state.hang_count:
        echo("  incidents: desync %d  flush_failed %d  hangs %d"
             % (state.desync_count, state.flush_failures,
                state.hang_count))
    if state.last_hang is not None:
        h = state.last_hang
        echo("  hang.detected: %s rank %s stalled at step %s "
             "(%.0fs past a %.0fs deadline) — gang killed for elastic "
             "retry" % (
                 h.get("pathspec"), h.get("laggard_rank"),
                 h.get("step_num"),
                 max(0.0, (h.get("progress_age_s") or 0.0)
                     - (h.get("deadline_s") or 0.0)),
                 h.get("deadline_s") or 0.0))
    for b in breaches:
        echo("  SLO BREACH: %s %s=%s > %s" % (
            b["rule"], b["metric"], b["value"], b["threshold"]))
    for rec in state.breach_events:
        d = rec.get("data") or {}
        echo("  slo.breach event: %s %s=%s > %s (%s)" % (
            d.get("rule"), d.get("metric"), d.get("value"),
            d.get("threshold"), d.get("source", "?")))


def watch(flow_datastore, run_id, once=False, check=False, interval=2.0,
          slo_path=None, echo=print, max_frames=None, as_json=False):
    """Tail a run. Returns the process exit code: 0, or 1 when --check
    and an SLO breach was observed (live-evaluated or persisted).
    as_json: emit one machine-readable JSON snapshot per poll instead
    of the rendered frame (external dashboards)."""
    tail = telemetry.TelemetryTail(flow_datastore, run_id)
    rules = slo_rules_mod.load_rules(slo_path)
    state = WatchState()
    frames = 0
    breaches = []
    while True:
        state.ingest(tail.poll())
        metrics = state.metrics()
        # per-tenant SLO bounds are synthesized from the live tenant
        # population each poll (tenants appear as traffic arrives)
        breaches = slo_rules_mod.evaluate(
            rules + slo_rules_mod.tenant_rules(metrics), metrics)
        if as_json:
            echo(json.dumps(state.snapshot(run_id, breaches),
                            sort_keys=True))
        else:
            render_frame(state, run_id, breaches, echo)
        frames += 1
        if once or (max_frames is not None and frames >= max_frames):
            break
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            break
    if check and (breaches or state.breach_events):
        return 1
    return 0
