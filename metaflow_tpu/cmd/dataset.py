"""`tpuflow dataset build|info|list`: manage sharded on-datastore corpora.

Packs a raw token file into the shard-blob + manifest format of
metaflow_tpu/data/shards.py, through the flow's configured datastore —
so a corpus built once on a fast box streams into every training gang
host via StreamingTokenBatches. See docs/data.md.

    python -m metaflow_tpu dataset build MyFlow wiki \
        --input tokens.npy --shard-tokens 4194304
    python -m metaflow_tpu dataset info MyFlow wiki
    python -m metaflow_tpu dataset list MyFlow
"""

import json
import os

import numpy as np

from ..data.shards import (
    DatasetError,
    append_corpus,
    build_corpus,
    list_datasets,
    load_manifest,
    manifest_revision,
)


def open_flow_datastore(flow_name, datastore=None, datastore_root=None):
    from .. import metaflow_config as cfg
    from ..datastore import STORAGE_BACKENDS, FlowDataStore

    storage_impl = STORAGE_BACKENDS[datastore or cfg.default_datastore()]
    return FlowDataStore(flow_name, storage_impl, ds_root=datastore_root)


def load_tokens(input_path, dtype=None):
    """A 1-D token array from a corpus file: .npy (memory-mapped, so
    multi-GB corpora shard at bounded RSS) or a raw binary dump
    (--dtype required to decode it). --dtype on a .npy is applied
    per-shard inside build_corpus, never as a whole-array cast that
    would pull the memmap into RAM."""
    if not os.path.exists(input_path):
        raise DatasetError("input file %s does not exist" % input_path)
    if input_path.endswith(".npy"):
        tokens = np.load(input_path, mmap_mode="r")
    else:
        if dtype is None:
            raise DatasetError(
                "raw binary input needs --dtype (e.g. int32) to decode %s"
                % input_path)
        tokens = np.memmap(input_path, dtype=np.dtype(dtype), mode="r")
    return tokens.reshape(-1)


def build_dataset(flow_name, name, input_path, shard_tokens, dtype=None,
                  datastore=None, datastore_root=None, overwrite=False,
                  echo=print):
    fds = open_flow_datastore(flow_name, datastore, datastore_root)
    tokens = load_tokens(input_path, dtype=dtype)
    manifest = build_corpus(fds, name, tokens, shard_tokens=shard_tokens,
                            overwrite=overwrite, dtype=dtype)
    echo("built dataset %s/%s: %d tokens in %d shard(s) of %d tokens "
         "(%s), %.1f MB"
         % (flow_name, name, manifest["total_tokens"],
            manifest["n_shards"], manifest["shard_tokens"],
            manifest["dtype"],
            sum(s["bytes"] for s in manifest["shards"]) / 2**20))
    return manifest


def append_dataset(flow_name, name, input_path, dtype=None,
                   generation=None, datastore=None, datastore_root=None,
                   echo=print):
    """`tpuflow dataset build --append`: append a token file's contents
    to an EXISTING corpus as new shards (packed at the manifest's own
    shard_tokens) and bump the manifest's append revision. Readers
    holding the old manifest stream exactly the token order they started
    with; reloading readers see the growth at their next epoch boundary.
    --generation stamps the new shards for the online replay freshness
    window."""
    fds = open_flow_datastore(flow_name, datastore, datastore_root)
    tokens = load_tokens(input_path, dtype=dtype)
    manifest = append_corpus(fds, name, tokens, generation=generation,
                             dtype=dtype)
    echo("appended %d tokens to dataset %s/%s: now %d tokens in %d "
         "shard(s), revision %d%s"
         % (tokens.size, flow_name, name, manifest["total_tokens"],
            manifest["n_shards"], manifest_revision(manifest),
            "" if generation is None
            else ", generation %d" % int(generation)))
    return manifest


def dataset_info(flow_name, name, datastore=None, datastore_root=None,
                 as_json=False, echo=print):
    fds = open_flow_datastore(flow_name, datastore, datastore_root)
    manifest = load_manifest(fds, name)
    if as_json:
        echo(json.dumps(manifest, indent=2, sort_keys=True))
        return manifest
    echo("dataset %s/%s" % (flow_name, name))
    echo("  dtype        %s" % manifest["dtype"])
    echo("  total tokens %d" % manifest["total_tokens"])
    echo("  shards       %d x %d tokens"
         % (manifest["n_shards"], manifest["shard_tokens"]))
    echo("  bytes        %d" % sum(s["bytes"] for s in manifest["shards"]))
    for i, shard in enumerate(manifest["shards"]):
        echo("  shard %-5d %8d tokens  %s" % (i, shard["tokens"],
                                              shard["sha256"][:16]))
    return manifest


def dataset_list(flow_name, datastore=None, datastore_root=None,
                 echo=print):
    fds = open_flow_datastore(flow_name, datastore, datastore_root)
    names = list_datasets(fds)
    if not names:
        echo("no datasets built for flow %s" % flow_name)
        return names
    for name in names:
        manifest = load_manifest(fds, name, missing_ok=True)
        if manifest is None:
            echo("%-24s (no manifest)" % name)
        else:
            echo("%-24s %12d tokens  %4d shard(s)  %s"
                 % (name, manifest["total_tokens"], manifest["n_shards"],
                    manifest["dtype"]))
    return names
