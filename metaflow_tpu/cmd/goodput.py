"""`tpuflow goodput` — the run's chip-second breakdown, reconciled.

Derives the goodput ledger (metaflow_tpu/goodput.py) from a run's
persisted telemetry, renders the wall-clock-reconciled per-category
breakdown, and names the dominant loss — the run-level generalization
of the INPUT-BOUND / PIPELINE-BOUND verdicts `tpuflow metrics` prints
per subsystem. `--json` dumps the full ledger (the same document
`goodput.save_ledger` persists); `--openmetrics` prints the run-scope
exporter's OpenMetrics text instead.
"""

import json

from .. import goodput as goodput_mod
from .. import telemetry

# categories always rendered in this order (taxonomy order, losses
# grouped after productive work)
_RENDER_ORDER = goodput_mod.CATEGORIES + (goodput_mod.UNATTRIBUTED,)

_LABELS = {
    goodput_mod.PRODUCTIVE_STEP: "productive step compute",
    goodput_mod.COMPILE: "XLA compile",
    goodput_mod.INPUT_STALL: "input stall",
    goodput_mod.TRANSFER_STALL: "MPMD transfer stall",
    goodput_mod.UPDATE: "optimizer update",
    goodput_mod.CHECKPOINT_BLOCKED: "checkpoint blocked",
    goodput_mod.RESTORE_REPLAY: "restore + replayed work",
    goodput_mod.CAPACITY_WAIT: "capacity wait (parked)",
    goodput_mod.SERVE_PREFILL: "serve prefill",
    goodput_mod.SERVE_DECODE: "serve decode",
    goodput_mod.SERVE_IDLE: "serve idle",
    goodput_mod.UNATTRIBUTED: "unattributed",
}


def _category_rows(ledger):
    cats = dict(ledger["categories"])
    cats[goodput_mod.UNATTRIBUTED] = ledger["unattributed_chip_s"]
    observed = ledger["observed_chip_s"] or 1.0
    rows = []
    for cat in _RENDER_ORDER:
        seconds = cats.get(cat, 0.0)
        if seconds <= 0:
            continue
        rows.append((cat, seconds, seconds / observed))
    return rows


def render_ledger(ledger, echo=print):
    run = ledger.get("run_id") or "?"
    echo("goodput %s  wall %.1fs  chip-time %.1fs over %d lane(s)"
         % (run, ledger["wall_clock_s"], ledger["observed_chip_s"],
            len(ledger["lanes"])))
    for cat, seconds, frac in _category_rows(ledger):
        bar = "#" * max(1, int(round(frac * 40))) if seconds else ""
        echo("  %-22s %9.1fs  %5.1f%%  %s"
             % (_LABELS.get(cat, cat), seconds, frac * 100, bar))
    echo("  reconciliation: %.1f%% attributed (tolerance %.0f%%) -> %s"
         % (ledger["coverage"] * 100, ledger["tolerance"] * 100,
            "OK" if ledger["reconciled"] else "UNRECONCILED"))
    echo("  goodput: %.1f%% of chip-time productive"
         % (ledger["goodput_frac"] * 100))
    if ledger.get("parked"):
        total = sum(p["delay_s"] * max(1, p["world"])
                    for p in ledger["parked"])
        echo("  parked: %d capacity wait(s), %.1f chip-second(s) withheld"
             % (len(ledger["parked"]), total))
    verdict = loss_verdict(ledger)
    if verdict:
        echo("  verdict: %s" % verdict)


def loss_verdict(ledger):
    """One-line dominant-loss verdict, or None for a loss-free run."""
    dominant = ledger.get("dominant_loss")
    if not dominant or ledger.get("dominant_loss_s", 0.0) <= 0:
        return None
    observed = ledger["observed_chip_s"] or 1.0
    frac = ledger["dominant_loss_s"] / observed
    return ("dominant loss is %s (%s): %.1fs, %.1f%% of chip-time"
            % (dominant, _LABELS.get(dominant, dominant),
               ledger["dominant_loss_s"], frac * 100))


def show_goodput(flow_datastore, run_id, as_json=False,
                 openmetrics=False, persist=False, echo=print):
    """CLI entry. Returns 0, or 1 when the run has no telemetry or the
    ledger fails to reconcile within tolerance (CI gates on this)."""
    records = telemetry.read_run_records(flow_datastore, run_id)
    if not records:
        echo("no telemetry records for run %s" % run_id)
        return 1
    ledger = goodput_mod.derive_ledger(records, run_id=run_id)
    if persist:
        path = goodput_mod.save_ledger(flow_datastore, run_id, ledger)
        if path and not (as_json or openmetrics):
            echo("ledger persisted to %s" % path)
    if openmetrics:
        echo(goodput_mod.render_openmetrics(
            goodput_mod.ledger_metric_families(ledger)), )
    elif as_json:
        echo(json.dumps(ledger, indent=2, sort_keys=True))
    else:
        render_ledger(ledger, echo)
    return 0 if ledger["reconciled"] else 1
