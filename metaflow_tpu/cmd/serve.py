"""`tpuflow serve FLOW/RUN`: serve a trained run's checkpoint over HTTP.

train -> checkpoint -> serve in one framework: the checkpoint comes off
the run's datastore through inference/loading.load_run_checkpoint, the
mesh/sharding reuses the training rule table (spmd/sharding.py), and the
continuous-batching engine + scheduler + HTTP server come from
metaflow_tpu/serving/. Telemetry lands in the SERVED run's
`_telemetry/` prefix (step `_serve`), so `tpuflow metrics FLOW/RUN`
shows serving TTFT/latency/occupancy next to the run's training
records.
"""

import json
import os

from .. import knobs
from ..exception import TpuFlowException


def build_config(restored, config_json=None, model="llama"):
    """Resolve the model config for a restored checkpoint pytree.

    Priority: --config-json (a file path or inline JSON object of
    LlamaConfig/MixtralConfig field overrides) > a 'cfg'/'config' dict
    the checkpoint itself carries. The named `model` family supplies the
    dataclass."""
    if model == "mixtral":
        from ..models.mixtral import MixtralConfig as config_cls
    elif model == "llama":
        from ..models.llama import LlamaConfig as config_cls
    else:
        raise TpuFlowException("unknown model family %r" % (model,))
    fields = None
    if config_json:
        if os.path.exists(config_json):
            with open(config_json) as f:
                fields = json.load(f)
        else:
            try:
                fields = json.loads(config_json)
            except ValueError:
                raise TpuFlowException(
                    "--config-json is neither a file nor valid JSON: %r"
                    % (config_json,))
    elif isinstance(restored, dict):
        for key in ("cfg", "config"):
            if isinstance(restored.get(key), dict):
                fields = dict(restored[key])
                break
    if fields is None:
        raise TpuFlowException(
            "no model config: pass --config-json (LlamaConfig fields as "
            "JSON) or checkpoint a 'cfg' dict next to the params")
    if not isinstance(fields, dict):
        raise TpuFlowException("model config must be a JSON object")
    known = {f.name for f in config_cls.__dataclass_fields__.values()}
    unknown = sorted(set(fields) - known)
    if unknown:
        raise TpuFlowException(
            "unknown %s field(s): %s" % (config_cls.__name__,
                                         ", ".join(unknown)))
    return config_cls(**fields)


def extract_params(restored, params_key="params"):
    """The weight pytree inside a checkpoint: restored[params_key] when
    present, else the whole tree (a bare-params checkpoint)."""
    if isinstance(restored, dict) and params_key in restored:
        return restored[params_key]
    return restored


def build_engine(params, cfg, slots=8, max_seq_len=None, prefill_chunk=64,
                 mesh_spec=None, attn_impl="auto", paged=False,
                 page_tokens=None, spec_k=None):
    """Shard params over a mesh (the training rule table) and build the
    engine: the slot engine, or (paged=True / TPUFLOW_PAGED=1) the
    paged-KV engine with optional speculative decoding. mesh_spec:
    None, or a MeshSpec factory name ('dp'|'fsdp'|'fsdp_tp')."""
    from ..serving import PagedEngine, SlotEngine

    mesh = None
    if mesh_spec:
        import jax

        from ..spmd import MeshSpec, create_mesh, shard_tree

        factory = getattr(MeshSpec, mesh_spec, None)
        if factory is None:
            raise TpuFlowException(
                "unknown mesh spec %r (want dp, fsdp or fsdp_tp)"
                % (mesh_spec,))
        mesh = create_mesh(factory() if mesh_spec != "fsdp_tp"
                           else factory(min(2, len(jax.devices()))))
        # the rule tree must come from the checkpoint's model family: a
        # Mixtral tree has router/expert axes the Llama table lacks
        from ..models import llama as llama_mod
        from ..models import mixtral as mixtral_mod

        model_mod = (mixtral_mod
                     if isinstance(cfg, mixtral_mod.MixtralConfig)
                     else llama_mod)
        params = shard_tree(params, model_mod.logical_axes(cfg), mesh)
    if paged or knobs.get_bool("TPUFLOW_PAGED"):
        return PagedEngine(params, cfg, max_slots=slots,
                           max_seq_len=max_seq_len,
                           prefill_chunk=prefill_chunk, mesh=mesh,
                           attn_impl=attn_impl, page_tokens=page_tokens,
                           spec_k=spec_k)
    return SlotEngine(params, cfg, max_slots=slots,
                      max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
                      mesh=mesh, attn_impl=attn_impl)


def build_prefix_cache(engine, prefix_cache_mb=None):
    """The prefix cache matched to the engine: a zero-copy
    PagedPrefixIndex over the paged engine's own pool, a host-side
    RadixPrefixCache otherwise. Same opt-in contract either way:
    no byte budget (flag or TPUFLOW_PREFIX_CACHE_MB), no cache."""
    from ..serving import PagedPrefixIndex, RadixPrefixCache

    pool = getattr(engine, "pool", None)
    if pool is not None:
        if prefix_cache_mb is None:
            return PagedPrefixIndex.from_env(pool)
        if int(prefix_cache_mb) <= 0:
            return None
        pages = max(1, (int(prefix_cache_mb) << 20)
                    // max(1, pool.page_bytes()))
        return PagedPrefixIndex(pool,
                                max_pages=min(pages, pool.usable_pages))
    if prefix_cache_mb is None:
        return RadixPrefixCache.from_env()
    return (RadixPrefixCache(int(prefix_cache_mb) << 20)
            if int(prefix_cache_mb) > 0 else None)


def _init_serve_telemetry(flow_name, run_id, task_prefix="server"):
    """Record serving telemetry into the served run's datastore under a
    synthetic `_serve` step, riding the existing FlightRecorder. The
    fleet router records as task `fleet-<pid>` next to the replicas'
    `replica<i>-<pid>` tasks."""
    from .. import telemetry
    from .. import metaflow_config as cfg
    from ..datastore import STORAGE_BACKENDS, FlowDataStore

    if not telemetry.enabled():
        return None
    try:
        storage = STORAGE_BACKENDS[cfg.default_datastore()]
        fds = FlowDataStore(flow_name, storage)
        return telemetry.init_recorder(
            fds, run_id, "_serve",
            "%s-%d" % (task_prefix, os.getpid()))
    except Exception:
        return None  # serving must come up even if telemetry cannot


def _resolve_flow_run(flow_run, run_id):
    """FLOW/RUN (or FLOW + --run-id) -> (flow_name, run_id), falling
    back to the latest successful run so telemetry lands under the real
    run id."""
    if run_id is None:
        flow_name, _, run_id = flow_run.rpartition("/")
        if not flow_name:
            flow_name, run_id = flow_run, None
    else:
        flow_name = flow_run
    if run_id is None:
        from ..inference.loading import _latest_successful_run_id

        run_id = _latest_successful_run_id(flow_name, None)
        if run_id is None:
            raise TpuFlowException(
                "No successful run of %s to serve." % flow_name)
    return flow_name, run_id


def serve_fleet(flow_run, run_id=None, step_name=None, ckpt_step=None,
                params_key="params", config_json=None, model="llama",
                host="127.0.0.1", port=8000, replicas=2, slots=8,
                max_seq_len=None, prefill_chunk=64, max_queue=64,
                mesh_spec=None, attn_impl="auto", prefill_workers=0,
                prefix_cache_mb=None, paged=False, page_tokens=None,
                spec_k=None, echo=print, block=True):
    """`tpuflow serve FLOW/RUN --replicas N`: fork N replica workers
    (each loading the run's checkpoint through load_run_checkpoint) and
    front them with the health-checked failover router
    (serving/fleet.py). `--prefill-workers K` adds K dedicated prefill
    replicas (disaggregated prefill/decode, docs/serving.md#disagg).
    Returns the running ServingFleet when block=False (tests);
    otherwise serves until SIGTERM/SIGINT, draining the whole fleet
    before exit."""
    from .. import telemetry
    from ..devtools import chaos as chaos_mod
    from ..serving import FleetConfig, ServingFleet, \
        SubprocessReplicaSpawner

    flow_name, run_id = _resolve_flow_run(flow_run, run_id)
    replica_args = [
        "--flow", flow_name, "--run-id", str(run_id),
        "--params-key", params_key, "--model", model,
        "--slots", str(slots), "--prefill-chunk", str(prefill_chunk),
        "--max-queue", str(max_queue), "--attn-impl", attn_impl,
    ]
    if step_name:
        replica_args += ["--step-name", step_name]
    if ckpt_step is not None:
        replica_args += ["--ckpt-step", str(ckpt_step)]
    if config_json:
        replica_args += ["--config-json", config_json]
    if max_seq_len is not None:
        replica_args += ["--max-seq-len", str(max_seq_len)]
    if mesh_spec:
        replica_args += ["--mesh", mesh_spec]
    if prefix_cache_mb is not None:
        replica_args += ["--prefix-cache-mb", str(prefix_cache_mb)]
    if paged:
        replica_args += ["--paged"]
    if page_tokens is not None:
        replica_args += ["--page-tokens", str(page_tokens)]
    if spec_k is not None:
        replica_args += ["--spec-k", str(spec_k)]
    config = FleetConfig.from_env()
    spawner = SubprocessReplicaSpawner(
        replica_args, spawn_timeout_s=config.spawn_timeout_s)
    _init_serve_telemetry(flow_name, run_id, task_prefix="fleet")
    fleet = ServingFleet(
        spawner, replicas, config=config, host=host, port=port,
        chaos=chaos_mod.fleet_from_env(replicas), echo=echo,
        prefill_workers=int(prefill_workers))
    fleet.start()
    echo("fleet: serving %s/%s on http://%s:%d (%d replicas x %d "
         "slots%s)" % (flow_name, run_id, fleet.host, fleet.port,
                       replicas, slots,
                       ", %d prefill workers" % prefill_workers
                       if prefill_workers else ""))
    echo("  POST /v1/generate  {\"tokens\": [...], \"max_new_tokens\":"
         " N, \"stream\": true, \"session\": \"...\"}")
    if not block:
        return fleet
    try:
        fleet.serve_forever()
    finally:
        telemetry.close_recorder()
    echo("fleet drained — all replicas stopped")


def reload_fleet(flow_run, run_id=None, step_name=None, ckpt_step=None,
                 host="127.0.0.1", port=8000, echo=print,
                 timeout_s=600.0):
    """`tpuflow serve FLOW/RUN --reload`: roll a RUNNING fleet (at
    --host/--port) onto a new checkpoint generation. POSTs
    /v1/admin/reload with the replica-arg updates, then polls
    /v1/admin/rollout until the surge rollout (spawn replacement ->
    ready -> drain old -> retire, one replica at a time) finishes.
    Returns the final rollout record; raises on abort/timeout."""
    import time
    from http.client import HTTPConnection

    flow_name, run_id = _resolve_flow_run(flow_run, run_id)
    args_update = {"--flow": flow_name, "--run-id": str(run_id)}
    if step_name:
        args_update["--step-name"] = step_name
    if ckpt_step is not None:
        args_update["--ckpt-step"] = str(ckpt_step)

    def _call(method, path, body=None):
        conn = HTTPConnection(host, port, timeout=30)
        try:
            headers = {"Content-Type": "application/json"} if body \
                else {}
            conn.request(method, path,
                         body=json.dumps(body).encode() if body
                         else None, headers=headers)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode() or "{}")
        finally:
            conn.close()

    status, ack = _call("POST", "/v1/admin/reload",
                        {"args_update": args_update})
    if status != 202:
        raise TpuFlowException(
            "fleet refused reload (%d): %s" % (status, ack))
    target = int(ack.get("fleet_generation", 0))
    echo("rollout: fleet -> generation %d (%s/%s)"
         % (target, flow_name, run_id))
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        _, ro = _call("GET", "/v1/admin/rollout")
        last = ro.get("last") or {}
        if (not ro.get("active")
                and int(ro.get("fleet_generation", 0)) >= target):
            echo("rollout: done — replaced %s replica(s), shed %s, "
                 "%.0f ms" % (last.get("replaced"),
                              last.get("shed_requests"),
                              float(last.get("ms") or 0.0)))
            return last
        time.sleep(0.5)
    raise TpuFlowException("rollout did not finish within %.0fs"
                           % timeout_s)


def serve_federate(fleet_urls, host="127.0.0.1", port=8000, echo=print,
                   block=True):
    """`tpuflow serve --federate URL,URL`: run the thin federation
    front tier over already-running fleets. No checkpoint is loaded
    here — the front only forwards, polls fleet /healthz for capacity
    rollups, and spreads tenants across fleets
    (docs/serving.md#federation)."""
    from ..serving import FederationRouter

    urls = [u.strip() for u in fleet_urls.split(",") if u.strip()]
    if not urls:
        raise TpuFlowException("--federate needs at least one fleet URL")
    router = FederationRouter(urls, host=host, port=port)
    router.start()
    echo("federating %d fleet(s) on http://%s:%d" % (len(urls),
                                                     router.host,
                                                     router.port))
    for i, url in enumerate(urls):
        echo("  fleet %d: %s" % (i, url))
    echo("  POST /v1/generate  {\"tokens\": [...], \"tenant\": \"...\"}")
    if not block:
        return router
    try:
        router._stop.wait()
    except KeyboardInterrupt:
        pass
    router.close()


def serve(flow_run, run_id=None, step_name=None, ckpt_step=None,
          params_key="params", config_json=None, model="llama",
          host="127.0.0.1", port=8000, replicas=1, slots=8,
          max_seq_len=None, prefill_chunk=64, max_queue=64,
          mesh_spec=None, attn_impl="auto", prefill_workers=0,
          prefix_cache_mb=None, paged=False, page_tokens=None,
          spec_k=None, reload_checkpoint=False, federate=None,
          echo=print, block=True):
    """Load FLOW/RUN's checkpoint and serve it. Returns the running
    ServingServer when block=False (tests); otherwise serves until
    SIGTERM/SIGINT, draining in-flight requests before exit. With
    --replicas N > 1 (or --prefill-workers K > 0) the work moves to the
    fleet tier (serve_fleet): forked replica workers behind the
    failover router. With --reload, no server starts: the named
    checkpoint is rolled onto the ALREADY-RUNNING fleet at
    --host/--port via a zero-shed rolling upgrade."""
    from .. import telemetry
    from ..inference import load_run_checkpoint
    from ..serving import Scheduler, ServingServer

    if federate:
        return serve_federate(federate, host=host, port=port, echo=echo,
                              block=block)

    if reload_checkpoint:
        return reload_fleet(flow_run, run_id=run_id,
                            step_name=step_name, ckpt_step=ckpt_step,
                            host=host, port=port, echo=echo)

    if int(replicas) > 1 or int(prefill_workers) > 0:
        return serve_fleet(
            flow_run, run_id=run_id, step_name=step_name,
            ckpt_step=ckpt_step, params_key=params_key,
            config_json=config_json, model=model, host=host, port=port,
            replicas=int(replicas), slots=slots,
            max_seq_len=max_seq_len, prefill_chunk=prefill_chunk,
            max_queue=max_queue, mesh_spec=mesh_spec,
            attn_impl=attn_impl, prefill_workers=int(prefill_workers),
            prefix_cache_mb=prefix_cache_mb, paged=paged,
            page_tokens=page_tokens, spec_k=spec_k, echo=echo,
            block=block)

    # resolve the run HERE (not only inside load_run_checkpoint) so
    # telemetry lands under the real run id, next to its training
    # records — never under a synthetic label
    flow_name, run_id = _resolve_flow_run(flow_run, run_id)
    restored = load_run_checkpoint(flow_name, run_id=run_id,
                                   step_name=step_name,
                                   ckpt_step=ckpt_step)
    cfg = build_config(restored, config_json=config_json, model=model)
    params = extract_params(restored, params_key=params_key)
    engine = build_engine(params, cfg, slots=slots,
                          max_seq_len=max_seq_len,
                          prefill_chunk=prefill_chunk,
                          mesh_spec=mesh_spec, attn_impl=attn_impl,
                          paged=paged, page_tokens=page_tokens,
                          spec_k=spec_k)
    _init_serve_telemetry(flow_name, run_id)
    cache = build_prefix_cache(engine, prefix_cache_mb)
    scheduler = Scheduler(engine, max_queue=max_queue,
                          prefix_cache=cache)
    server = ServingServer(scheduler, host=host, port=port)
    if hasattr(engine, "pool"):
        echo("serving %s/%s on http://%s:%d  (paged: %d slots, %d pages "
             "x %d tokens, spec_k=%d, attn=%s)"
             % (flow_name, run_id, server.host, server.port,
                engine.max_slots, engine.pool.usable_pages,
                engine.page_tokens, engine.spec_k, engine.attn_impl))
    else:
        echo("serving %s/%s on http://%s:%d  (%d slots x %d positions, "
             "attn=%s)" % (flow_name, run_id, server.host,
                           server.port, engine.max_slots,
                           engine.max_seq_len, engine.attn_impl))
    echo("  POST /v1/generate  {\"tokens\": [...], \"max_new_tokens\": N,"
         " \"stream\": true}")
    if not block:
        server.start()
        return server
    try:
        server.serve_forever()
    finally:
        telemetry.close_recorder()
    echo("drained — all in-flight requests finished")
