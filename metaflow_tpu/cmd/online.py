"""`tpuflow online FLOW`: run the closed actor-learner loop at any
scale — by default a self-contained test-scale run: a tiny Llama actor
behind the continuous-batching scheduler, a seeded prompt sampler, the
flow's datastore as the replay corpus, and a learner gang of one.

Every leg is the production path (SlotEngine, StreamingTokenBatches,
AsyncCheckpointManager, the chaos hooks), so a seeded run of this
command is the end-to-end generate->score->pack->train->re-serve proof,
and — because every stage is deterministic or idempotent — a SIGKILLed
run re-invoked with the same arguments resumes with an exact loss
trajectory and a byte-identical replay corpus. See docs/online.md.

    python -m metaflow_tpu online OnlineFlow --rounds 4 --seed 0
    TPUFLOW_CHAOS=step:0 python -m metaflow_tpu online OnlineFlow ...
"""

import json
import os

from .. import knobs, telemetry
from ..exception import TpuFlowException


def run_online(flow_name, dataset="replay", run_id="online",
               rounds=None, rollouts=None, steps_per_round=None,
               push_every=None, max_lag=None, max_new_tokens=None,
               seq_len=32, batch_size=4, prompt_len=8, seed=0,
               vocab_size=128, dim=32, n_layers=1, n_heads=2,
               fresh_generations=None, concurrent=False,
               checkpoint_name="online", reward="length",
               datastore=None, datastore_root=None, json_out=None,
               echo=print):
    """Wire actor + replay + learner and run the loop; returns the
    loop's summary dict (also written to --json-out for harnesses)."""
    import jax
    import numpy as np

    from ..models import llama
    from ..online import (ActorPool, LogProbScorer, OnlineLoop,
                          PromptSampler, ReplayReader, ReplayWriter,
                          diversity_reward, length_reward)
    from ..serving import Scheduler, SlotEngine
    from ..spmd import MeshSpec, create_mesh
    from ..training import default_optimizer, make_trainer, shard_batch
    from ..training.checkpoint import AsyncCheckpointManager
    from .dataset import open_flow_datastore

    fds = open_flow_datastore(flow_name, datastore, datastore_root)
    rec = None
    if telemetry.enabled():
        rec = telemetry.init_recorder(fds, run_id, "_online",
                                      "loop-%d" % os.getpid())

    cfg = llama.LlamaConfig.tiny(vocab_size=int(vocab_size),
                                 dim=int(dim), n_layers=int(n_layers),
                                 n_heads=int(n_heads))
    mesh = create_mesh(MeshSpec.dp())
    ckpt = AsyncCheckpointManager(fds, name=checkpoint_name)
    state, step_fn, _shardings = make_trainer(
        jax.random.PRNGKey(int(seed)), cfg, mesh, llama,
        optimizer=default_optimizer(lr=1e-2, warmup_steps=1,
                                    total_steps=1000),
        checkpoint=ckpt)

    # the actor serves COPIES of the learner weights: the jitted train
    # step donates its state, so handing the engine the live buffers
    # would leave it decoding from deleted arrays after the first step
    def snapshot_params(st):
        return jax.tree_util.tree_map(np.asarray,
                                      jax.device_get(st["params"]))

    max_new = (knobs.get_int("TPUFLOW_ONLINE_MAX_NEW_TOKENS")
               if max_new_tokens is None else int(max_new_tokens))
    engine = SlotEngine(snapshot_params(state), cfg,
                        max_slots=min(8, max(1, int(rollouts or 8))),
                        max_seq_len=int(prompt_len) + max_new + 8)
    scheduler = Scheduler(engine)
    if reward == "length":
        reward_fn = length_reward
    elif reward == "diversity":
        reward_fn = diversity_reward
    elif reward == "logprob":
        reward_fn = LogProbScorer(snapshot_params(state), cfg)
    else:
        raise TpuFlowException(
            "unknown reward %r (want length, diversity or logprob)"
            % (reward,))
    actor = ActorPool(scheduler=scheduler, reward_fn=reward_fn,
                      max_new_tokens=max_new)

    writer = ReplayWriter(fds, dataset, int(seq_len),
                          windows_per_shard=max(1, int(batch_size)))
    reader = ReplayReader(fds, dataset, int(batch_size), int(seq_len),
                          seed=int(seed),
                          fresh_generations=fresh_generations)
    sampler = PromptSampler(cfg.vocab_size, int(prompt_len),
                            seed=int(seed))

    def learner_step(st, tokens):
        batch = shard_batch({"tokens": tokens}, mesh)
        with mesh:
            st, metrics = step_fn(st, batch)
        return st, float(metrics["loss"])

    loop = OnlineLoop(actor, writer, reader, sampler, learner_step,
                      state, snapshot_params, checkpoint=ckpt,
                      rounds=rounds, rollouts=rollouts,
                      steps_per_round=steps_per_round,
                      push_every=push_every, max_lag=max_lag,
                      concurrent=concurrent, echo=echo)
    try:
        summary = loop.run()
    finally:
        if rec is not None:
            telemetry.close_recorder()
    echo("online: done — %d step(s), generation %d, %d rollout(s) "
         "kept, %d stale, %d shed"
         % (summary["steps"], summary["generation"],
            summary["kept_rollouts"], summary["dropped_stale"],
            summary["shed_requests"]))
    if json_out:
        with open(json_out, "w") as f:
            json.dump(summary, f, sort_keys=True)
    return summary
