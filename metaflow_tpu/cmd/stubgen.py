"""Type stub (.pyi) generator for the public API.

Reference behavior: metaflow/cmd/develop/stub_generator.py (walks live
modules, emits a stubs package for IDE/type-checker support). Minimal
equivalent: introspect signatures + docstrings of the public surface.

    python -m metaflow_tpu.cmd.stubgen [out_dir]
"""

import inspect
import os
import sys


def _fmt_signature(obj):
    try:
        sig = inspect.signature(obj)
    except (ValueError, TypeError):
        return "(*args, **kwargs)"
    parts = []
    for p in sig.parameters.values():
        s = p.name
        if p.kind == p.VAR_POSITIONAL:
            s = "*" + s
        elif p.kind == p.VAR_KEYWORD:
            s = "**" + s
        elif p.default is not p.empty:
            s += "=..."
        parts.append(s)
    return "(%s)" % ", ".join(parts)


def _doc_line(obj):
    doc = inspect.getdoc(obj)
    if not doc:
        return ""
    first = doc.split("\n", 1)[0].replace('"""', "'''")
    return '\n    """%s"""' % first


def _class_stub(name, cls):
    lines = ["class %s:" % name]
    doc = _doc_line(cls)
    if doc:
        lines[0] += doc.replace("\n    ", "\n    ", 1)
    members = []
    for attr_name, attr in sorted(vars(cls).items()):
        if attr_name.startswith("_") and attr_name != "__init__":
            continue
        if isinstance(attr, property):
            members.append("    @property")
            members.append("    def %s(self): ..." % attr_name)
        elif inspect.isfunction(attr):
            members.append(
                "    def %s%s: ..." % (attr_name, _fmt_signature(attr))
            )
        elif isinstance(attr, (staticmethod, classmethod)):
            fn = attr.__func__
            deco = ("    @staticmethod" if isinstance(attr, staticmethod)
                    else "    @classmethod")
            members.append(deco)
            members.append(
                "    def %s%s: ..." % (attr_name, _fmt_signature(fn))
            )
    if not members:
        members = ["    ..."]
    return "\n".join(lines + members)


def generate(out_dir):
    import metaflow_tpu

    blocks = [
        '"""Auto-generated type stubs for metaflow_tpu '
        '(python -m metaflow_tpu.cmd.stubgen)."""',
        "from typing import Any",
        "",
    ]
    for name in sorted(metaflow_tpu.__all__):
        obj = getattr(metaflow_tpu, name)
        if inspect.isclass(obj):
            blocks.append(_class_stub(name, obj))
        elif callable(obj):
            doc = _doc_line(obj)
            if doc:
                blocks.append("def %s%s:%s\n    ..."
                              % (name, _fmt_signature(obj), doc))
            else:
                blocks.append("def %s%s: ..." % (name, _fmt_signature(obj)))
        else:
            blocks.append("%s: Any" % name)
        blocks.append("")
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, "__init__.pyi")
    with open(out_path, "w") as f:
        f.write("\n".join(blocks))
    return out_path


if __name__ == "__main__":
    out = generate(sys.argv[1] if len(sys.argv) > 1 else "metaflow_tpu-stubs")
    print("wrote %s" % out)
