"""Type stub (.pyi) generator for the public API.

Reference behavior: metaflow/cmd/develop/stub_generator.py (walks live
modules, emits a stubs package with full docstrings for IDE/type-checker
support). This walks the public surface — the top-level package plus the
user-facing submodules — and emits one .pyi per module, mirroring the
package layout, with signatures (annotations preserved) and complete
docstring blocks so editor hover shows real documentation.

    python -m metaflow_tpu.cmd.stubgen [out_dir]
"""

import inspect
import os
import sys

# module name (import path suffix) -> emitted .pyi path inside the stubs dir
PUBLIC_MODULES = [
    ("", "__init__.pyi"),
    ("client", os.path.join("client", "__init__.pyi")),
    ("runner", os.path.join("runner", "__init__.pyi")),
    ("plugins.cards", os.path.join("plugins", "cards", "__init__.pyi")),
    ("training", os.path.join("training", "__init__.pyi")),
    ("spmd", os.path.join("spmd", "__init__.pyi")),
    ("ops.attention", os.path.join("ops", "attention.pyi")),
    ("ops.ring_attention", os.path.join("ops", "ring_attention.pyi")),
    ("models.llama", os.path.join("models", "llama.pyi")),
    ("devtools", os.path.join("devtools", "__init__.pyi")),
]

# `current` members injected at runtime by decorators (via
# current._update_env) — invisible to plain introspection of the Current
# class, but the whole point of typed stubs is that `current.checkpoint.`
# completes in an IDE (reference: stub_generator.py's "Add To Current"
# docstring injection). Each entry: member name -> (module holding the
# value's class, class name, injecting decorator).
CURRENT_DYNAMIC_MEMBERS = [
    ("parallel", "metaflow_tpu.current", "Parallel", "@parallel / @tpu"),
    ("tpu", "metaflow_tpu.plugins.tpu.tpu_decorator", "TpuInfo", "@tpu"),
    ("checkpoint", "metaflow_tpu.plugins.tpu.checkpoint_decorator",
     "Checkpointer", "@checkpoint"),
    ("card", "metaflow_tpu.plugins.cards.card_decorator", "CardCollector",
     "@card"),
    ("trigger", "metaflow_tpu.events", "Trigger",
     "@trigger / @trigger_on_finish"),
    ("preemption", "metaflow_tpu.plugins.tpu.preemption",
     "PreemptionHandler", "the task runner (always present in steps)"),
    ("project_name", None, "str", "@project"),
    ("branch_name", None, "str", "@project"),
    ("project_flow_name", None, "str", "@project"),
    ("is_production", None, "bool", "@project"),
]


def _fmt_annotation(ann):
    if ann is inspect.Parameter.empty:
        return None
    if isinstance(ann, type):
        return ann.__name__
    return str(ann).replace("typing.", "")


def _fmt_signature(obj, drop_first=False):
    try:
        sig = inspect.signature(obj)
    except (ValueError, TypeError):
        return "(*args: Any, **kwargs: Any) -> Any"
    parts = []
    params = list(sig.parameters.values())
    for i, p in enumerate(params):
        s = p.name
        if p.kind == p.VAR_POSITIONAL:
            s = "*" + s
        elif p.kind == p.VAR_KEYWORD:
            s = "**" + s
        ann = _fmt_annotation(p.annotation)
        if ann and not (drop_first and i == 0):
            s += ": %s" % ann
        if p.default is not p.empty:
            s += " = ..."
        parts.append(s)
    ret = _fmt_annotation(sig.return_annotation)
    return "(%s)%s" % (", ".join(parts), " -> %s" % ret if ret else "")


def _doc_block(obj, indent="    "):
    """The full docstring as an indented triple-quoted block ('' if none)."""
    doc = inspect.getdoc(obj)
    if not doc:
        return ""
    doc = doc.replace('"""', "'''")
    if "\n" in doc:
        body = ("\n" + indent).join(doc.split("\n"))
        return '%s"""%s\n%s"""' % (indent, body, indent)
    return '%s"""%s"""' % (indent, doc)


def _fn_stub(name, fn, indent="", deco=None, drop_first=None):
    """drop_first: suppress the first parameter's annotation (self/cls);
    defaults to 'is a class member' except for staticmethods, whose first
    parameter is a real argument."""
    if drop_first is None:
        drop_first = bool(indent) and deco != "@staticmethod"
    lines = []
    if deco:
        lines.append(indent + deco)
    sig = _fmt_signature(fn, drop_first=drop_first)
    doc = _doc_block(fn, indent + "    ")
    if doc:
        lines.append("%sdef %s%s:" % (indent, name, sig))
        lines.append(doc)
        lines.append(indent + "    ...")
    else:
        lines.append("%sdef %s%s: ..." % (indent, name, sig))
    return lines


def _class_stub(name, cls):
    lines = ["class %s:" % name]
    doc = _doc_block(cls)
    if doc:
        lines.append(doc)
    members = []
    for attr_name, attr in sorted(vars(cls).items()):
        if attr_name.startswith("_") and attr_name != "__init__":
            continue
        if isinstance(attr, property):
            members.extend(_fn_stub(attr_name, attr.fget or (lambda s: None),
                                    indent="    ", deco="@property"))
        elif inspect.isfunction(attr):
            members.extend(_fn_stub(attr_name, attr, indent="    "))
        elif isinstance(attr, (staticmethod, classmethod)):
            deco = ("@staticmethod" if isinstance(attr, staticmethod)
                    else "@classmethod")
            members.extend(_fn_stub(attr_name, attr.__func__, indent="    ",
                                    deco=deco))
    if not members:
        members = ["    ..."]
    return "\n".join(lines + members)


def _current_stub():
    """The Current class with BOTH its static properties and the
    decorator-injected dynamic members, plus stubs for the injected
    members' own classes (introspected live, so their method signatures
    and docstrings stay real)."""
    import importlib

    from ..current import Current

    blocks = []
    member_lines = []
    injected_classes = []
    for name, mod_name, cls_name, injector in CURRENT_DYNAMIC_MEMBERS:
        ann = cls_name
        if mod_name is not None:
            try:
                cls = getattr(importlib.import_module(mod_name), cls_name)
            except Exception:
                continue
            injected_classes.append((cls_name, cls))
        member_lines.append("    @property")
        member_lines.append("    def %s(self) -> %s:" % (name, ann))
        member_lines.append(
            '        """Injected by %s; raises AttributeError when that '
            "decorator is not active (guard with current.get(%r))."
            '"""' % (injector, name)
        )
        member_lines.append("        ...")

    for cls_name, cls in injected_classes:
        blocks.append(_class_stub(cls_name, cls))
        blocks.append("")

    cls_block = _class_stub("Current", Current)
    blocks.append(cls_block)
    blocks.extend(member_lines)
    blocks.append("")
    blocks.append("current: Current")
    return "\n".join(blocks)


def _module_stub(module):
    names = getattr(module, "__all__", None)
    is_top = module.__name__ == "metaflow_tpu"
    if names is None:
        names = [n for n in sorted(vars(module))
                 if not n.startswith("_")
                 and getattr(getattr(module, n), "__module__", "").startswith(
                     "metaflow_tpu")]
    blocks = []
    mdoc = _doc_block(module, indent="")
    blocks.append(mdoc or '"""Auto-generated stubs."""')
    blocks.append("from typing import Any")
    blocks.append("")
    for name in names:
        try:
            obj = getattr(module, name)
        except AttributeError:
            continue
        if is_top and name == "current":
            blocks.append(_current_stub())
        elif inspect.isclass(obj):
            blocks.append(_class_stub(name, obj))
        elif inspect.isfunction(obj) or callable(obj):
            fn = obj if inspect.isfunction(obj) else getattr(
                obj, "__call__", obj)
            blocks.append("\n".join(_fn_stub(name, fn)))
        else:
            blocks.append("%s: Any" % name)
        blocks.append("")
    return "\n".join(blocks)


def generate(out_dir):
    import importlib

    import metaflow_tpu

    written = []
    for suffix, rel_path in PUBLIC_MODULES:
        mod_name = "metaflow_tpu" + ("." + suffix if suffix else "")
        try:
            module = importlib.import_module(mod_name)
        except Exception:
            continue  # optional deps may be absent; stub what imports
        out_path = os.path.join(out_dir, rel_path)
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            f.write(_module_stub(module))
        written.append(out_path)
    # PEP 561: mark the stub tree as type information
    with open(os.path.join(out_dir, "py.typed"), "w") as f:
        f.write("")
    with open(os.path.join(out_dir, "GENERATED"), "w") as f:
        f.write("python -m metaflow_tpu.cmd.stubgen\n")
    return out_dir if len(written) > 1 else (written and written[0] or out_dir)


if __name__ == "__main__":
    out = generate(sys.argv[1] if len(sys.argv) > 1 else "metaflow_tpu-stubs")
    print("wrote %s" % out)
