"""`tpuflow metrics <run>`: aggregate a run's flight-recorder records.

Reads every telemetry record the run persisted to its datastore
(`_telemetry/` prefix — all tasks, all gang ranks, all hosts) and renders:

  - a summary: task table (duration/queue-time/rank/host), training
    throughput (per-step wall time, tokens/sec, MFU) aggregated across
    gang ranks, counters, compile stats, captured profiles
  - `--timeline`: the per-train-step series
  - `--spans N`: the N slowest timer spans of the run (why was it slow?)
  - `--json`: the raw aggregation for tooling

Entry points: `python -m metaflow_tpu metrics FLOW/RUN` (no flow file
needed) and `python flow.py metrics [RUN]` (flow context known).
"""

import json
import re
import statistics

from .. import telemetry

# per-stage MPMD step timers (training/mpmd_trainer.py instruments each
# stage's step with prefix "mpmd.stage<k>")
_MPMD_STEP_RE = re.compile(r"^mpmd\.stage(\d+)\.step$")


def _pathspec(rec):
    return "%s/%s/%s" % (rec["run_id"], rec["step"], rec["task_id"])


def aggregate(records, profiles=None):
    """Fold raw telemetry records into the per-run aggregation the
    renderers and --json consume."""
    tasks = {}
    timers = {}
    counters = {}
    events = {}
    train_steps = {}
    train_summary = {}
    ranks = set()
    hosts = set()
    traces = set()
    # fleet.* serving events (serving/fleet.py router telemetry)
    fleet_dispatch = {}
    fleet_shed = {}
    fleet_restarts = []
    fleet_failovers = 0
    fleet_deaths = 0
    fleet_chaos_kills = 0
    fleet_scale = {"out": 0, "in": 0}
    fleet_rollouts = []
    # gang hang watchdog (elastic/watchdog.py) + chaos fault kinds
    hang_detections = []
    chaos_hangs = 0
    chaos_slows = 0
    # serve.prefix.* radix-cache events (serving/prefix_cache.py)
    prefix = {"hits": 0, "misses": 0, "hit_tokens": 0,
              "prompt_tokens": 0, "evictions": 0, "evicted_tokens": 0,
              "evicted_bytes": 0}
    # serve.kv.* paged-pool events + pool/spec gauges (serving/paged.py)
    kv = {"page_allocs": 0, "pages_allocated": 0, "page_frees": 0,
          "pages_freed": 0, "page_shares": 0, "pages_shared": 0,
          "shared_tokens": 0, "exhausted": 0}
    kv_gauges = {}  # last-seen occupancy / cow_pages / spec accept rate
    # serve.tenant.* admission events (serving/scheduler.py) plus
    # tenant-tagged request lifecycle events, keyed by tenant id
    tenant_stats = {}
    # fleet.cache_route.* cache-aware dispatch events (serving/fleet.py)
    cache_route = {"hits": 0, "misses": 0, "matched_tokens": 0,
                   "prompt_tokens": 0}
    # MPMD per-stage pipeline gangs (spmd/mpmd.py + mpmd_trainer.py):
    # each rank runs ONE stage, so per-stage series key on the stage id
    # in the timer name, never averaged across ranks
    mpmd_stages = {}
    mpmd_transfer = {}
    mpmd_plan = {}

    def _tenant(tid):
        return tenant_stats.setdefault(str(tid), {
            "admitted": 0, "throttled": 0, "throttles": {}, "shed": 0,
            "prompt_tokens": 0, "generated_tokens": 0, "_ttft": []})

    for rec in records:
        name = rec.get("name", "")
        rtype = rec.get("type", "")
        key = (rec.get("step", ""), str(rec.get("task_id", "")))
        if rec.get("step") != "_runtime":
            task = tasks.setdefault(key, {
                "step": rec.get("step"), "task_id": rec.get("task_id"),
                "rank": rec.get("rank", 0), "host": rec.get("host", ""),
                "attempts": 0, "duration_ms": None, "queue_seconds": None,
                "ok": None,
            })
            task["attempts"] = max(task["attempts"],
                                   rec.get("attempt", 0) + 1)
            ranks.add(rec.get("rank", 0))
            hosts.add(rec.get("host", ""))
        if rec.get("trace"):
            traces.add(rec["trace"])

        if rtype == "timer":
            t = timers.setdefault(name, {"count": 0, "total_ms": 0.0,
                                         "max_ms": 0.0, "failures": 0,
                                         "_samples": []})
            ms = float(rec.get("ms", 0.0))
            t["count"] += 1
            t["total_ms"] += ms
            t["max_ms"] = max(t["max_ms"], ms)
            t["_samples"].append(ms)
            if rec.get("ok") is False:
                t["failures"] += 1
            if name == "task.duration" and key in tasks:
                tasks[key]["duration_ms"] = ms
                tasks[key]["ok"] = rec.get("ok")
            if name.endswith(".step") and "step_num" in rec:
                data = rec.get("data") or {}
                # aggregation key: (flow step, gang identity, step_num).
                # Gang worker task ids derive from their control task
                # ('<control>-node-<i>'), so ranks of ONE gang share a
                # base and merge; foreach siblings / other train steps
                # have different bases and must NOT be averaged together
                base = str(rec.get("task_id", "")).split("-node-")[0]
                s = train_steps.setdefault(
                    (rec.get("step", ""), base, rec["step_num"]), {
                        "ms": [], "tokens_per_sec": [], "mfu": [],
                        "input_stall_ms": [], "optimizer_update_ms": [],
                        "ranks": set(), "compile": False})
                s["ms"].append(ms)
                s["ranks"].add(rec.get("rank", 0))
                if data.get("compile"):
                    s["compile"] = True
                if "tokens_per_sec" in data:
                    s["tokens_per_sec"].append(data["tokens_per_sec"])
                if "mfu" in data:
                    s["mfu"].append(data["mfu"])
                if "input_stall_ms" in data:
                    s["input_stall_ms"].append(data["input_stall_ms"])
                if "optimizer_update_ms" in data:
                    s["optimizer_update_ms"].append(
                        data["optimizer_update_ms"])
                mpmd_m = _MPMD_STEP_RE.match(name)
                if mpmd_m:
                    st = mpmd_stages.setdefault(int(mpmd_m.group(1)), {
                        "samples": [], "stall_ms": []})
                    st["samples"].append((ms, bool(data.get("compile"))))
                    if ("transfer_stall_ms" in data
                            and not data.get("compile")):
                        st["stall_ms"].append(data["transfer_stall_ms"])
        elif rtype == "counter":
            counters[name] = counters.get(name, 0) + rec.get("inc", 1)
        elif rtype == "gauge":
            if name == "task.queue_seconds" and key in tasks:
                tasks[key]["queue_seconds"] = rec.get("value")
            if name.startswith("train.summary."):
                train_summary.setdefault(
                    name[len("train.summary."):], []).append(
                        rec.get("value"))
            if name == "serve.kv.page_occupancy":
                kv_gauges["occupancy"] = rec.get("value")
            elif name == "serve.kv.cow_pages":
                kv_gauges["cow_pages"] = rec.get("value")
            elif name == "serve.spec.accept_rate":
                kv_gauges["spec_accept_rate"] = rec.get("value")
            if name.startswith("train.memory."):
                # per-step memory-split gauges normalize onto the same
                # keys the summary gauges use (memory_params_bytes, ...)
                suffix = name[len("train.memory."):]
                train_summary.setdefault(
                    "memory_%s" % suffix, []).append(rec.get("value"))
        elif rtype == "event":
            events[name] = events.get(name, 0) + 1
            if name.startswith("serve.prefix."):
                data = rec.get("data") or {}
                if name == "serve.prefix.hit":
                    prefix["hits"] += 1
                    prefix["hit_tokens"] += int(
                        data.get("matched_tokens", 0))
                    prefix["prompt_tokens"] += int(
                        data.get("prompt_tokens", 0))
                elif name == "serve.prefix.miss":
                    prefix["misses"] += 1
                    prefix["prompt_tokens"] += int(
                        data.get("prompt_tokens", 0))
                elif name == "serve.prefix.evict":
                    prefix["evictions"] += int(data.get("nodes", 0))
                    prefix["evicted_tokens"] += int(
                        data.get("tokens", 0))
                    prefix["evicted_bytes"] += int(data.get("bytes", 0))
            if name.startswith("serve.kv."):
                data = rec.get("data") or {}
                if name == "serve.kv.page_alloc":
                    kv["page_allocs"] += 1
                    kv["pages_allocated"] += int(data.get("pages", 0))
                elif name == "serve.kv.page_free":
                    kv["page_frees"] += 1
                    kv["pages_freed"] += int(data.get("pages", 0))
                elif name == "serve.kv.page_shared":
                    kv["page_shares"] += 1
                    kv["pages_shared"] += int(data.get("pages", 0))
                    kv["shared_tokens"] += int(data.get("tokens", 0))
                elif name == "serve.kv.exhausted":
                    kv["exhausted"] += 1
            if name.startswith("serve.tenant."):
                data = rec.get("data") or {}
                t = _tenant(data.get("tenant") or "default")
                if name == "serve.tenant.admitted":
                    t["admitted"] += 1
                    t["prompt_tokens"] += int(
                        data.get("prompt_tokens", 0))
                elif name == "serve.tenant.throttled":
                    t["throttled"] += 1
                    reason = str(data.get("reason", "unknown"))
                    t["throttles"][reason] = \
                        t["throttles"].get(reason, 0) + 1
                elif name == "serve.tenant.shed":
                    t["shed"] += 1
            if name in ("serve.request.first_token",
                        "serve.request.finished"):
                # tenant-tagged request lifecycle: per-tenant TTFT
                # distribution + generated-token attribution
                data = rec.get("data") or {}
                if data.get("tenant"):
                    t = _tenant(data["tenant"])
                    if (name == "serve.request.first_token"
                            and "ttft_ms" in data):
                        t["_ttft"].append(float(data["ttft_ms"]))
                    elif name == "serve.request.finished":
                        t["generated_tokens"] += int(
                            data.get("new_tokens", 0))
            if name.startswith("fleet.cache_route."):
                data = rec.get("data") or {}
                if name == "fleet.cache_route.hit":
                    cache_route["hits"] += 1
                    cache_route["matched_tokens"] += int(
                        data.get("matched_tokens", 0))
                    cache_route["prompt_tokens"] += int(
                        data.get("prompt_tokens", 0))
                elif name == "fleet.cache_route.miss":
                    cache_route["misses"] += 1
                    cache_route["prompt_tokens"] += int(
                        data.get("prompt_tokens", 0))
            if name == "mpmd.transfer":
                data = rec.get("data") or {}
                t = mpmd_transfer.setdefault(
                    int(data.get("stage", rec.get("rank", 0))), {
                        "frames_sent": 0, "frames_recv": 0,
                        "bytes_sent": 0, "bytes_recv": 0,
                        "stall_ms": 0.0, "double_buffer": None})
                for k in ("frames_sent", "frames_recv", "bytes_sent",
                          "bytes_recv"):
                    t[k] += int(data.get(k, 0))
                t["stall_ms"] += float(data.get("stall_ms", 0.0))
                if "double_buffer" in data:
                    t["double_buffer"] = bool(data["double_buffer"])
            elif name == "mpmd.stage.trace":
                data = rec.get("data") or {}
                for k in ("num_microbatches", "num_virtual_stages",
                          "num_stages", "n_layers", "n_cycles"):
                    if k in data:
                        mpmd_plan[k] = data[k]
            if name == "hang.detected":
                data = rec.get("data") or {}
                hang_detections.append({
                    "pathspec": data.get("pathspec"),
                    "laggard_rank": data.get("laggard_rank"),
                    "step_num": data.get("step_num"),
                    "progress_age_s": data.get("progress_age_s"),
                    "deadline_s": data.get("deadline_s"),
                    # time-to-detection: how long past the deadline the
                    # stall ran before the watchdog caught it (poll
                    # cadence + dump wait)
                    "detect_lag_s": round(
                        max(0.0, (data.get("progress_age_s") or 0.0)
                            - (data.get("deadline_s") or 0.0)), 3),
                    "forensics": data.get("forensics"),
                })
            elif name == "chaos.hang":
                chaos_hangs += 1
            elif name == "chaos.slow":
                chaos_slows += 1
            if name.startswith(("fleet.", "chaos.replica_kill")):
                data = rec.get("data") or {}
                if name == "fleet.request.dispatch":
                    r = data.get("replica")
                    if r is not None:
                        fleet_dispatch[int(r)] = \
                            fleet_dispatch.get(int(r), 0) + 1
                elif name == "fleet.request.failover":
                    fleet_failovers += 1
                elif name == "fleet.request.shed":
                    reason = str(data.get("reason", "unknown"))
                    fleet_shed[reason] = fleet_shed.get(reason, 0) + 1
                    if data.get("tenant"):
                        # router-level denial charged to the tenant it
                        # was scoped to (budget / priority headroom)
                        _tenant(data["tenant"])["shed"] += 1
                elif name == "fleet.replica.restart":
                    fleet_restarts.append({
                        "ts": rec.get("ts"),
                        "replica": data.get("replica"),
                        "attempt": data.get("attempt"),
                        "delay_s": data.get("delay_s"),
                    })
                elif name == "fleet.replica.dead":
                    fleet_deaths += 1
                elif name == "chaos.replica_kill":
                    fleet_chaos_kills += 1
                elif name == "fleet.scale_out":
                    fleet_scale["out"] += 1
                elif name == "fleet.scale_in":
                    fleet_scale["in"] += 1
                elif name == "fleet.rollout":
                    if data.get("phase") in ("done", "abort"):
                        fleet_rollouts.append({
                            "fleet_generation":
                                data.get("fleet_generation"),
                            "phase": data.get("phase"),
                            "replaced": data.get("replaced"),
                            "shed_requests": data.get("shed_requests"),
                            "ms": data.get("ms"),
                        })

    # finalize timer stats
    for t in timers.values():
        samples = sorted(t.pop("_samples"))
        t["p50_ms"] = round(samples[len(samples) // 2], 3)
        t["total_ms"] = round(t["total_ms"], 3)
        t["max_ms"] = round(t["max_ms"], 3)

    # training series: aggregate ACROSS gang ranks per (group, step_num)
    # — every rank times the same global step, so wall time is the mean
    # (ranks disagree only by host jitter) and tokens/sec / MFU are rank
    # means of the same global quantity. Distinct groups (foreach
    # siblings, multiple train steps) stay separate rows.
    groups = sorted({(step, base) for step, base, _n in train_steps})
    timeline = []
    for step, base, step_num in sorted(train_steps):
        s = train_steps[(step, base, step_num)]
        row = {"step_num": step_num,
               "ms": round(statistics.mean(s["ms"]), 3),
               "ranks": len(s["ranks"])}
        if len(groups) > 1:
            row["group"] = "%s/%s" % (step, base)
        if s["compile"]:
            row["compile"] = True
        if s["tokens_per_sec"]:
            row["tokens_per_sec"] = round(
                statistics.mean(s["tokens_per_sec"]), 1)
        if s["mfu"]:
            row["mfu"] = round(statistics.mean(s["mfu"]), 4)
        if s["input_stall_ms"]:
            # worst rank: a gang step waits for its SLOWEST host's input
            row["input_stall_ms"] = round(max(s["input_stall_ms"]), 3)
        if s["optimizer_update_ms"]:
            row["optimizer_update_ms"] = round(
                statistics.mean(s["optimizer_update_ms"]), 3)
        timeline.append(row)

    train = {}
    if timeline:
        steady = [r for r in timeline if not r.get("compile")]
        pick = steady or timeline
        train = {
            "steps": len(timeline),
            "groups": len(groups),
            "ranks": sorted(set().union(
                *(row["ranks"] for row in train_steps.values()))),
            "mean_step_ms": round(
                statistics.mean(r["ms"] for r in pick), 3),
            "p50_step_ms": round(
                statistics.median(r["ms"] for r in pick), 3),
        }
        tps = [r["tokens_per_sec"] for r in pick if "tokens_per_sec" in r]
        if tps:
            train["tokens_per_sec"] = round(statistics.mean(tps), 1)
        mfus = [r["mfu"] for r in pick if "mfu" in r]
        if mfus:
            train["mfu"] = round(statistics.mean(mfus), 4)
        stalls = [r["input_stall_ms"] for r in pick
                  if "input_stall_ms" in r]
        if stalls:
            train["input_stall_ms"] = round(statistics.mean(stalls), 3)
            mean_ms = train["mean_step_ms"]
            if mean_ms:
                # the input-bound verdict: fraction of each step the host
                # spent waiting on data instead of dispatching
                train["input_stall_frac"] = round(
                    train["input_stall_ms"] / mean_ms, 4)
        updates = [r["optimizer_update_ms"] for r in pick
                   if "optimizer_update_ms" in r]
        if updates:
            train["optimizer_update_ms"] = round(
                statistics.mean(updates), 3)
            if train["mean_step_ms"]:
                # how much of each step the weight update costs — the
                # number the ZeRO sharded-update path shrinks
                train["optimizer_update_frac"] = round(
                    train["optimizer_update_ms"] / train["mean_step_ms"], 4)
        for key_name, values in train_summary.items():
            vals = [v for v in values if isinstance(v, (int, float))]
            if not vals:
                continue
            if key_name in ("compile_ms", "device_memory_peak_bytes"):
                train["%s_max" % key_name] = max(vals)
            elif key_name.startswith("memory_"):
                train["%s_max" % key_name] = max(vals)
            elif key_name == "compiles":
                train["compiles_total"] = int(sum(vals))
            elif (key_name == "optimizer_update_ms"
                  and "optimizer_update_ms" not in train):
                train["optimizer_update_ms"] = round(
                    statistics.mean(vals), 3)

    fleet = {}
    if (fleet_dispatch or fleet_failovers or fleet_shed
            or fleet_restarts or fleet_deaths or fleet_chaos_kills
            or fleet_scale["out"] or fleet_scale["in"]
            or fleet_rollouts):
        fleet_restarts.sort(key=lambda r: (r["ts"] is None, r["ts"]))
        fleet = {
            "requests_per_replica": {
                str(k): fleet_dispatch[k] for k in sorted(fleet_dispatch)},
            "dispatched": sum(fleet_dispatch.values()),
            "failovers": fleet_failovers,
            "shed": dict(sorted(fleet_shed.items())),
            "shed_total": sum(fleet_shed.values()),
            "replica_deaths": fleet_deaths,
            "chaos_kills": fleet_chaos_kills,
            "restarts": fleet_restarts,
            "scale_outs": fleet_scale["out"],
            "scale_ins": fleet_scale["in"],
            "rollouts": fleet_rollouts,
        }

    hangs = {}
    if hang_detections or chaos_hangs or chaos_slows:
        lags = [h["detect_lag_s"] for h in hang_detections
                if h.get("detect_lag_s") is not None]
        hangs = {
            "count": len(hang_detections),
            "chaos_hangs": chaos_hangs,
            "chaos_slows": chaos_slows,
            "detections": hang_detections,
        }
        if lags:
            hangs["mean_detect_lag_s"] = round(statistics.mean(lags), 3)
            hangs["max_detect_lag_s"] = round(max(lags), 3)

    # MPMD per-stage section: one row per pipeline stage; the slowest
    # stage is the bubble — when the OTHER stages spend >= 10% of their
    # step blocked on the wire, the run is PIPELINE-BOUND on it (the
    # MPMD mirror of the INPUT-BOUND verdict)
    mpmd = {}
    if mpmd_stages or mpmd_transfer:
        stage_rows = []
        for k in sorted(set(mpmd_stages) | set(mpmd_transfer)):
            row = {"stage": k}
            st = mpmd_stages.get(k)
            if st and st["samples"]:
                steady = [ms for ms, comp in st["samples"] if not comp]
                pick = steady or [ms for ms, _comp in st["samples"]]
                row["steps"] = len(st["samples"])
                row["mean_step_ms"] = round(statistics.mean(pick), 3)
                if st["stall_ms"]:
                    row["transfer_stall_ms"] = round(
                        statistics.mean(st["stall_ms"]), 3)
                    if row["mean_step_ms"]:
                        row["transfer_stall_frac"] = round(
                            row["transfer_stall_ms"]
                            / row["mean_step_ms"], 4)
            compiles = counters.get(
                "mpmd.stage%d.compile_cache_miss" % k)
            if compiles is not None:
                row["compiles"] = int(compiles)
            t = mpmd_transfer.get(k)
            if t:
                row.update({
                    "frames_sent": t["frames_sent"],
                    "frames_recv": t["frames_recv"],
                    "bytes_sent": t["bytes_sent"],
                    "bytes_recv": t["bytes_recv"],
                    "wire_stall_ms": round(t["stall_ms"], 3),
                })
                if t["double_buffer"] is not None:
                    row["double_buffer"] = t["double_buffer"]
            stage_rows.append(row)
        mpmd = {"stages": stage_rows}
        if mpmd_plan:
            mpmd["plan"] = dict(mpmd_plan)
        timed = [r for r in stage_rows if "mean_step_ms" in r]
        if timed:
            slowest = max(timed, key=lambda r: r["mean_step_ms"])
            mpmd["bottleneck_stage"] = slowest["stage"]
            others = [r for r in timed
                      if r["stage"] != slowest["stage"]]
            mpmd["pipeline_bound"] = any(
                r.get("transfer_stall_frac", 0) >= 0.1 for r in others)

    prefix_cache = {}
    looked_up = prefix["hits"] + prefix["misses"]
    if looked_up or prefix["evictions"]:
        prefix_cache = dict(prefix)
        prefix_cache["hit_rate"] = round(
            prefix["hits"] / looked_up, 4) if looked_up else 0.0
        # FLOPs proxy: fraction of admitted prompt tokens whose prefill
        # was skipped because their KV came out of the radix cache
        prefix_cache["prefill_tokens_skipped_frac"] = round(
            prefix["hit_tokens"] / max(1, prefix["prompt_tokens"]), 4)

    kv_pages = {}
    if any(kv.values()) or kv_gauges:
        kv_pages = dict(kv)
        kv_pages.update(kv_gauges)
        # leak detector: every reserved page must come back on some
        # terminal path — nonzero here after a drained run is a leak
        kv_pages["pages_outstanding"] = (kv["pages_allocated"]
                                         - kv["pages_freed"])

    tenants = {}
    for tid in sorted(tenant_stats):
        t = tenant_stats[tid]
        samples = sorted(t.pop("_ttft"))
        row = dict(t)
        if samples:
            row["ttft_p50_ms"] = round(
                samples[len(samples) // 2], 3)
            # nearest-rank p99 — same estimator the fleet SLO loop uses
            row["ttft_p99_ms"] = round(
                samples[min(len(samples) - 1,
                            int(0.99 * (len(samples) - 1) + 0.5))], 3)
        tenants[tid] = row

    routing = {}
    routed = cache_route["hits"] + cache_route["misses"]
    if routed:
        routing = dict(cache_route)
        routing["warm_rate"] = round(cache_route["hits"] / routed, 4)
        # prefill FLOPs the router steered onto an already-warm replica
        routing["routed_tokens_frac"] = round(
            cache_route["matched_tokens"]
            / max(1, cache_route["prompt_tokens"]), 4)

    task_rows = sorted(
        tasks.values(),
        key=lambda t: (t["step"], str(t["task_id"])))
    return {
        "records": len(records),
        "tasks": task_rows,
        "ranks": sorted(ranks),
        "hosts": sorted(hosts),
        "trace_ids": sorted(traces),
        "timers": {k: timers[k] for k in sorted(timers)},
        "counters": dict(sorted(counters.items())),
        "events": dict(sorted(events.items())),
        "train": train,
        "mpmd": mpmd,
        "fleet": fleet,
        "tenants": tenants,
        "cache_route": routing,
        "hangs": hangs,
        "prefix_cache": prefix_cache,
        "kv_pages": kv_pages,
        "timeline": timeline,
        "profiles": list(profiles or []),
    }


def slowest_spans(records, limit=10):
    """The N slowest individual timer records, with their origin."""
    spans = [r for r in records if r.get("type") == "timer"]
    spans.sort(key=lambda r: r.get("ms", 0.0), reverse=True)
    return [
        {"name": r["name"], "ms": r.get("ms"),
         "task": "%s/%s" % (r.get("step"), r.get("task_id")),
         "rank": r.get("rank", 0), "ok": r.get("ok", True),
         "step_num": r.get("step_num")}
        for r in spans[:limit]
    ]


def load_run(flow_datastore, run_id):
    """(records, profiles) of one run — the raw inputs to aggregate()."""
    records = telemetry.read_run_records(flow_datastore, run_id)
    profiles = telemetry.list_run_profiles(flow_datastore, run_id)
    return records, profiles


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_ms(ms):
    if ms is None:
        return "-"
    if ms >= 60_000:
        return "%.1fmin" % (ms / 60_000)
    if ms >= 1000:
        return "%.2fs" % (ms / 1000)
    return "%.0fms" % ms


def render_summary(run_id, agg, echo=print):
    echo("Run %s: %d telemetry records, %d task(s), rank(s) %s, "
         "host(s) %s"
         % (run_id, agg["records"], len(agg["tasks"]),
            ",".join(map(str, agg["ranks"])) or "-",
            ",".join(agg["hosts"]) or "-"))
    if agg["trace_ids"]:
        echo("trace: %s" % ", ".join(agg["trace_ids"]))
    if agg["tasks"]:
        echo("")
        echo("  %-24s %-5s %-9s %-9s %-8s %s"
             % ("task", "rank", "duration", "queued", "attempts", "ok"))
        for t in agg["tasks"]:
            queued = ("%.2fs" % t["queue_seconds"]
                      if t["queue_seconds"] is not None else "-")
            echo("  %-24s %-5s %-9s %-9s %-8d %s"
                 % ("%s/%s" % (t["step"], t["task_id"]), t["rank"],
                    _fmt_ms(t["duration_ms"]), queued, t["attempts"],
                    {True: "ok", False: "FAIL", None: "-"}[t["ok"]]))
    train = agg["train"]
    if train:
        echo("")
        note = ""
        if train.get("groups", 1) > 1:
            note = (" over %d separate training groups — per-group "
                    "series: --timeline" % train["groups"])
        echo("training (aggregated across %d rank(s)%s):"
             % (len(train.get("ranks") or [0]), note))
        line = ("  %d steps, %s/step (p50 %s)"
                % (train["steps"], _fmt_ms(train["mean_step_ms"]),
                   _fmt_ms(train["p50_step_ms"])))
        if "tokens_per_sec" in train:
            line += ", %.0f tokens/s" % train["tokens_per_sec"]
        if "mfu" in train:
            line += ", MFU %.1f%%" % (train["mfu"] * 100)
        if "input_stall_ms" in train:
            line += ", input stall %s/step" % _fmt_ms(
                train["input_stall_ms"])
            if train.get("input_stall_frac", 0) >= 0.1:
                line += " (INPUT-BOUND %.0f%%)" % (
                    train["input_stall_frac"] * 100)
        if "optimizer_update_ms" in train:
            line += ", opt update %s/step" % _fmt_ms(
                train["optimizer_update_ms"])
            if train.get("optimizer_update_frac"):
                line += " (%.0f%%)" % (train["optimizer_update_frac"] * 100)
        echo(line)
        extras = []
        if "compiles_total" in train:
            extras.append("%d compile(s)" % train["compiles_total"])
        if "compile_ms_max" in train:
            extras.append("compile %s" % _fmt_ms(train["compile_ms_max"]))
        if "device_memory_peak_bytes_max" in train:
            extras.append("device mem peak %.1f MB"
                          % (train["device_memory_peak_bytes_max"] / 2**20))
        mem_split = [(label, train.get("memory_%s_bytes_max" % key))
                     for label, key in (("params", "params"),
                                        ("opt state", "opt_state"),
                                        ("activations", "activations"))]
        if any(v is not None for _l, v in mem_split):
            extras.append("per-device mem " + " + ".join(
                "%s %.1f MB" % (label, v / 2**20)
                for label, v in mem_split if v is not None))
        if extras:
            echo("  " + ", ".join(extras))
    mpmd = agg.get("mpmd") or {}
    if mpmd:
        echo("")
        plan = mpmd.get("plan") or {}
        note = ""
        if plan:
            note = " (M=%s V=%s S=%s over %s layers)" % (
                plan.get("num_microbatches"),
                plan.get("num_virtual_stages"),
                plan.get("num_stages"), plan.get("n_layers"))
        echo("mpmd pipeline (per-stage gangs)%s:" % note)
        for row in mpmd.get("stages") or []:
            line = "  stage %d:" % row["stage"]
            if "mean_step_ms" in row:
                line += " %s/step" % _fmt_ms(row["mean_step_ms"])
            if "transfer_stall_ms" in row:
                line += ", transfer stall %s/step" % _fmt_ms(
                    row["transfer_stall_ms"])
                if "transfer_stall_frac" in row:
                    line += " (%.0f%%)" % (
                        row["transfer_stall_frac"] * 100)
            if "compiles" in row:
                line += ", %d compile(s)" % row["compiles"]
            if "bytes_sent" in row:
                line += ", %.1f MB sent / %.1f MB recv" % (
                    row["bytes_sent"] / 2**20, row["bytes_recv"] / 2**20)
            if row.get("double_buffer") is False:
                line += " [sync transport]"
            if (mpmd.get("pipeline_bound")
                    and row["stage"] == mpmd.get("bottleneck_stage")):
                # the stage every other stage is stalling on
                line += "  <- PIPELINE-BOUND"
            echo(line)
    fleet = agg.get("fleet") or {}
    if fleet:
        echo("")
        echo("fleet (serving router):")
        per = fleet.get("requests_per_replica") or {}
        dist = ", ".join("replica%s=%d" % (r, per[r]) for r in sorted(
            per, key=int)) or "-"
        echo("  %d request(s) dispatched  [%s]"
             % (fleet.get("dispatched", 0), dist))
        line = ("  failovers %d, shed %d, replica deaths %d"
                % (fleet.get("failovers", 0), fleet.get("shed_total", 0),
                   fleet.get("replica_deaths", 0)))
        if fleet.get("chaos_kills"):
            line += ", chaos kills %d" % fleet["chaos_kills"]
        echo(line)
        if fleet.get("scale_outs") or fleet.get("scale_ins"):
            echo("  autoscaler: %d scale-out(s), %d scale-in(s)"
                 % (fleet.get("scale_outs", 0),
                    fleet.get("scale_ins", 0)))
        for ro in fleet.get("rollouts") or []:
            echo("  rollout gen %s: %s (%s replaced, %s shed, %s)"
                 % (ro.get("fleet_generation"), ro.get("phase"),
                    ro.get("replaced"), ro.get("shed_requests"),
                    _fmt_ms(ro.get("ms"))))
        if fleet.get("shed"):
            echo("  shed by reason: " + ", ".join(
                "%s=%d" % (k, v) for k, v in fleet["shed"].items()))
        if fleet.get("restarts"):
            echo("  restart backoff timeline:")
            for r in fleet["restarts"]:
                echo("    replica %s attempt %s: wait %ss"
                     % (r.get("replica"), r.get("attempt"),
                        r.get("delay_s")))
    routing = agg.get("cache_route") or {}
    if routing:
        echo("")
        echo("cache-aware routing (prefix-affinity dispatch):")
        echo("  %d warm / %d cold dispatch(es) (%.0f%% warm), %d of %d "
             "prompt tokens already cached on the chosen replica "
             "(%.0f%%)"
             % (routing["hits"], routing["misses"],
                routing["warm_rate"] * 100, routing["matched_tokens"],
                routing["prompt_tokens"],
                routing["routed_tokens_frac"] * 100))
    tenants = agg.get("tenants") or {}
    if tenants:
        echo("")
        echo("tenants (multi-tenant admission):")
        echo("  %-16s %8s %9s %5s %10s %10s %9s %9s"
             % ("tenant", "admitted", "throttled", "shed",
                "prompt_tok", "gen_tok", "ttft p50", "ttft p99"))
        for tid, t in tenants.items():
            echo("  %-16s %8d %9d %5d %10d %10d %9s %9s"
                 % (tid, t["admitted"], t["throttled"], t["shed"],
                    t["prompt_tokens"], t["generated_tokens"],
                    _fmt_ms(t.get("ttft_p50_ms")),
                    _fmt_ms(t.get("ttft_p99_ms"))))
            if t["throttles"]:
                echo("  %-16s throttled by reason: %s"
                     % ("", ", ".join(
                         "%s=%d" % (k, v) for k, v
                         in sorted(t["throttles"].items()))))
    hangs = agg.get("hangs") or {}
    if hangs:
        echo("")
        echo("hangs (gang watchdog):")
        line = "  %d hang(s) detected" % hangs.get("count", 0)
        if hangs.get("chaos_hangs") or hangs.get("chaos_slows"):
            line += "  (chaos: %d hang(s), %d straggler(s) injected)" % (
                hangs.get("chaos_hangs", 0), hangs.get("chaos_slows", 0))
        echo(line)
        if "mean_detect_lag_s" in hangs:
            echo("  time-to-detection past deadline: mean %.1fs, "
                 "max %.1fs" % (hangs["mean_detect_lag_s"],
                                hangs["max_detect_lag_s"]))
        for h in hangs.get("detections") or []:
            echo("  %s: rank %s stalled at step %s for %.0fs "
                 "(deadline %.0fs); forensics: %s"
                 % (h.get("pathspec"), h.get("laggard_rank"),
                    h.get("step_num"), h.get("progress_age_s") or 0.0,
                    h.get("deadline_s") or 0.0,
                    h.get("forensics") or "-"))
    prefix_cache = agg.get("prefix_cache") or {}
    if prefix_cache:
        echo("")
        echo("prefix cache (radix KV reuse):")
        echo("  %d hit(s) / %d miss(es) (hit rate %.0f%%), %d of %d "
             "prompt tokens served from cache (%.0f%% of prefill "
             "skipped)"
             % (prefix_cache["hits"], prefix_cache["misses"],
                prefix_cache["hit_rate"] * 100,
                prefix_cache["hit_tokens"],
                prefix_cache["prompt_tokens"],
                prefix_cache["prefill_tokens_skipped_frac"] * 100))
        if prefix_cache.get("evictions"):
            echo("  evicted %d node(s) / %d token(s) / %.1f MB under "
                 "byte budget"
                 % (prefix_cache["evictions"],
                    prefix_cache["evicted_tokens"],
                    prefix_cache["evicted_bytes"] / 2**20))
    kv_pages = agg.get("kv_pages") or {}
    if kv_pages:
        echo("")
        echo("paged KV pool:")
        echo("  %d reservation(s) (%d pages), %d release(s) (%d pages), "
             "%d outstanding"
             % (kv_pages["page_allocs"], kv_pages["pages_allocated"],
                kv_pages["page_frees"], kv_pages["pages_freed"],
                kv_pages["pages_outstanding"]))
        if kv_pages.get("page_shares"):
            echo("  %d zero-copy prefix attach(es): %d page(s) / %d "
                 "token(s) shared"
                 % (kv_pages["page_shares"], kv_pages["pages_shared"],
                    kv_pages["shared_tokens"]))
        if kv_pages.get("exhausted"):
            echo("  %d exhaustion episode(s) (admission backpressure)"
                 % kv_pages["exhausted"])
        if kv_pages.get("spec_accept_rate") is not None:
            echo("  speculative decode accept rate %.0f%%"
                 % (kv_pages["spec_accept_rate"] * 100))
    if agg["counters"]:
        echo("")
        echo("counters:")
        for name, total in agg["counters"].items():
            echo("  %-40s %s" % (name, total))
    interesting = [
        (name, t) for name, t in agg["timers"].items()
        if not name.endswith(".step")
    ]
    if interesting:
        echo("")
        echo("timers (aggregated):")
        echo("  %-40s %6s %10s %10s %10s %s"
             % ("name", "count", "total", "p50", "max", "failures"))
        for name, t in sorted(interesting,
                              key=lambda kv: -kv[1]["total_ms"]):
            echo("  %-40s %6d %10s %10s %10s %s"
                 % (name, t["count"], _fmt_ms(t["total_ms"]),
                    _fmt_ms(t["p50_ms"]), _fmt_ms(t["max_ms"]),
                    t["failures"] or ""))
    if agg["profiles"]:
        echo("")
        echo("profiler captures:")
        for p in agg["profiles"]:
            echo("  %s" % p)


def render_timeline(agg, echo=print):
    if not agg["timeline"]:
        echo("no per-step training records in this run")
        return
    grouped = any("group" in row for row in agg["timeline"])
    header = "%8s %10s %14s %8s %10s %10s %6s %s" % (
        "step", "wall", "tokens/s", "MFU", "stall", "opt", "ranks", "")
    echo(("%-24s " % "group") + header if grouped else header)
    for row in agg["timeline"]:
        line = "%8d %10s %14s %8s %10s %10s %6d %s" % (
            row["step_num"], _fmt_ms(row["ms"]),
            ("%.0f" % row["tokens_per_sec"]
             if "tokens_per_sec" in row else "-"),
            ("%.1f%%" % (row["mfu"] * 100) if "mfu" in row else "-"),
            (_fmt_ms(row["input_stall_ms"])
             if "input_stall_ms" in row else "-"),
            (_fmt_ms(row["optimizer_update_ms"])
             if "optimizer_update_ms" in row else "-"),
            row["ranks"], "compile" if row.get("compile") else "")
        echo(("%-24s " % row.get("group", "")) + line if grouped
             else line)


def render_spans(records, limit, echo=print):
    spans = slowest_spans(records, limit)
    if not spans:
        echo("no timer records in this run")
        return
    echo("%10s  %-40s %-22s %5s %s" % ("ms", "name", "task", "rank", "ok"))
    for s in spans:
        echo("%10.1f  %-40s %-22s %5d %s"
             % (s["ms"], s["name"], s["task"], s["rank"],
                "" if s["ok"] else "FAIL"))


def filter_records(records, step=None, rank=None):
    """Narrow records to one flow step and/or gang rank — multi-gang
    runs interleave everything, and a straggler hunt wants ONE rank's
    timeline. Matches the record's own step/rank fields (records from a
    different step/rank simply vanish from summary, timeline, spans)."""
    if step is not None:
        records = [r for r in records if r.get("step") == step]
    if rank is not None:
        records = [r for r in records if r.get("rank") == int(rank)]
    return records


def show_metrics(flow_datastore, run_id, as_json=False, timeline=False,
                 spans=0, step=None, rank=None, echo=print):
    """The shared CLI driver. Returns the aggregation dict."""
    records, profiles = load_run(flow_datastore, run_id)
    records = filter_records(records, step=step, rank=rank)
    agg = aggregate(records, profiles)
    if as_json:
        agg["slowest_spans"] = slowest_spans(records, spans or 10)
        echo(json.dumps(agg, indent=2, sort_keys=True, default=list))
        return agg
    if not records:
        if step is not None or rank is not None:
            echo("no telemetry records match the --step/--rank filter "
                 "for run %s" % run_id)
        else:
            echo("no telemetry records found for run %s (was the run "
                 "executed with TPUFLOW_TELEMETRY=0?)" % run_id)
        return agg
    if timeline:
        render_timeline(agg, echo=echo)
    elif spans:
        render_spans(records, spans, echo=echo)
    else:
        render_summary(run_id, agg, echo=echo)
    return agg
