"""`tpuflow trace` — reassemble request trace trees from telemetry.

The serving stack stamps W3C trace context (trace id + span id) into
every `serve.request.*` / `fleet.request.*` record (scheduler.py::_tdata,
fleet.py::handle_generate), so this module can rebuild the full
queued -> dispatch -> prefill -> first_token -> decode -> finished /
failover tree for each request FROM THE PERSISTED RECORDS ALONE — no
collector, no sidecar, works after the fact on any finished or crashed
run. A request that failed over mid-stream shows up as one tree: the
victim's delivered-prefix attempt and the successor's resume attempt are
parented under the same request root because both carry the same trace
id and dispatch-derived child spans.

Also computes a TTFT critical-path decomposition per request
(router queue / replica queue / prefill / first decode) that must sum to
the measured TTFT — the decomposition the Gemma-on-TPU serving
comparison uses to attribute tail latency — and exports Chrome/Perfetto
trace-event JSON (`--perfetto out.json`; open in ui.perfetto.dev).

Train runs need no extra plumbing: `persist.*` / `checkpoint.*` /
`elastic.*` spans already tee into the recorder as timer records, so a
run with no serving requests exports those as Perfetto slices instead
(one process per step/task, one thread per rank).
"""

import json

from .. import telemetry

# event families that belong to a request's tree
_REQUEST_PREFIXES = ("serve.request.", "fleet.request.")

# serve.prefill_chunk timers carry request_id too: they become the
# chunk-level child slices of the prefill phase
_CHUNK_TIMER = "serve.prefill_chunk"

# MPMD pipeline stages stamp a per-stage child span of the run
# traceparent into these (training/mpmd_trainer.py): the train-path
# analogue of a request subtree
_MPMD_TRANSFER = "mpmd.transfer"


def _data(rec):
    return rec.get("data") or {}


def build_request_traces(records):
    """Group request-path records into per-request trace trees.

    Returns a list (request order of first appearance) of dicts:
      request_id, trace, root_span, events (ts-sorted),
      attempts: [{span, replica, dispatch, events, failover, finished,
                  first_token, delivered, status}]
    Works with tracing disabled too (span-less records collapse into a
    single implicit attempt), but cross-replica attribution then needs
    the spans the router stamped."""
    trees, order = {}, []
    records = sorted(records, key=lambda r: r.get("ts", 0))
    for rec in records:
        name = rec.get("name", "")
        is_chunk = name == _CHUNK_TIMER
        if not (name.startswith(_REQUEST_PREFIXES) or is_chunk):
            continue
        rid = _data(rec).get("request_id")
        if rid is None:
            continue
        tree = trees.get(rid)
        if tree is None:
            tree = trees[rid] = {
                "request_id": rid, "trace": None, "root_span": None,
                "events": [], "attempts": [], "shed": None,
            }
            order.append(rid)
        tree["events"].append(rec)
        d = _data(rec)
        if d.get("trace") and not tree["trace"]:
            tree["trace"] = d["trace"]
        if name == "fleet.request.dispatch":
            if d.get("parent_span"):
                tree["root_span"] = d["parent_span"]
            tree["attempts"].append({
                "span": d.get("span"), "replica": d.get("replica"),
                "dispatch": d.get("dispatch"), "t_dispatch": rec.get("ts"),
                "events": [], "failover": None, "finished": None,
                "first_token": None, "delivered": None, "status": "open",
            })
    for tree in trees.values():
        _attach_events(tree)
    return [trees[rid] for rid in order]


def _attempt_for(tree, span):
    """The attempt a replica-side record belongs to: span match first,
    else the latest attempt (records land after their dispatch), else an
    implicit attempt for router-less single-server runs."""
    if span:
        for att in tree["attempts"]:
            if att["span"] == span:
                return att
    if tree["attempts"]:
        return tree["attempts"][-1]
    att = {"span": span, "replica": None, "dispatch": None,
           "t_dispatch": None, "events": [], "failover": None,
           "finished": None, "first_token": None, "delivered": None,
           "status": "open"}
    tree["attempts"].append(att)
    return att


def _attach_events(tree):
    for rec in tree["events"]:
        name = rec.get("name", "")
        d = _data(rec)
        if name == "fleet.request.dispatch":
            continue
        if name == "fleet.request.shed":
            tree["shed"] = rec
            continue
        att = _attempt_for(tree, d.get("span"))
        att["events"].append(rec)
        if not tree["root_span"] and not name.startswith("fleet.") \
                and d.get("span"):
            # no router: the serve-side span IS the request root
            tree["root_span"] = d["span"]
        if name == "fleet.request.failover":
            att["failover"] = rec
            att["delivered"] = d.get("delivered")
            att["status"] = "failover"
        elif name == "serve.request.first_token":
            att["first_token"] = rec
        elif name in ("serve.request.finished",
                      "serve.request.cancelled"):
            att["finished"] = rec
            if att["status"] == "open":
                att["status"] = d.get("reason") or "finished"


def _first_named(events, name, span=None):
    for rec in events:
        if rec.get("name") != name:
            continue
        if span is not None and _data(rec).get("span") not in (None, span):
            continue
        return rec
    return None


def ttft_decomposition(tree):
    """Critical-path split of time-to-first-token for one request.

    Components are measured INDEPENDENTLY of each other (cross-event
    timestamp deltas + the scheduler's own queue_ms), so their sum
    agreeing with the measured TTFT is a real consistency check, not an
    identity:

      router_queue_ms  dispatch event -> replica queued event
      replica_queue_ms scheduler queue_ms (t_admit - t_submit)
      prefill_ms       prefill event -> first_token event
      first_decode_ms  0.0 by construction: this engine delivers the
                       first token from the FINAL PREFILL CHUNK
                       (scheduler._prefill), not from a decode step

    measured_ttft_ms is dispatch->first_token when a router was involved
    (client-perceived), else the scheduler's own ttft_ms. Returns None
    when the request never produced a first token."""
    first_tok = _first_named(tree["events"], "serve.request.first_token")
    if first_tok is None:
        return None
    span = _data(first_tok).get("span")
    queued = _first_named(tree["events"], "serve.request.queued", span)
    prefill = _first_named(tree["events"], "serve.request.prefill", span)
    dispatch = _first_named(tree["events"], "fleet.request.dispatch", span)
    if queued is None or prefill is None:
        return None
    router_queue_ms = (
        max(0.0, (queued["ts"] - dispatch["ts"]) * 1000)
        if dispatch is not None else 0.0)
    replica_queue_ms = float(_data(prefill).get(
        "queue_ms", (prefill["ts"] - queued["ts"]) * 1000))
    prefill_ms = max(0.0, (first_tok["ts"] - prefill["ts"]) * 1000)
    first_decode_ms = 0.0
    total = router_queue_ms + replica_queue_ms + prefill_ms \
        + first_decode_ms
    if dispatch is not None:
        measured = (first_tok["ts"] - dispatch["ts"]) * 1000
    else:
        measured = float(_data(first_tok).get("ttft_ms") or 0.0)
    err_pct = (abs(total - measured) / measured * 100
               if measured > 0 else 0.0)
    return {
        "request_id": tree["request_id"],
        "router_queue_ms": round(router_queue_ms, 3),
        "replica_queue_ms": round(replica_queue_ms, 3),
        "prefill_ms": round(prefill_ms, 3),
        "first_decode_ms": round(first_decode_ms, 3),
        "sum_ms": round(total, 3),
        "measured_ttft_ms": round(measured, 3),
        "err_pct": round(err_pct, 2),
    }


def build_stage_spans(records):
    """Per-stage MPMD transfer spans: one row per pipeline stage,
    aggregated over that stage's `mpmd.transfer` records (stamped with
    the stage's child span of the run traceparent). Returns a
    stage-ordered list of dicts; [] for runs without MPMD records."""
    stages = {}
    for rec in sorted(records, key=lambda r: r.get("ts", 0)):
        if rec.get("name") != _MPMD_TRANSFER:
            continue
        d = _data(rec)
        stage = int(d.get("stage", 0))
        row = stages.get(stage)
        if row is None:
            row = stages[stage] = {
                "stage": stage, "trace": d.get("trace"),
                "span": d.get("span"), "steps": 0, "stall_ms": 0.0,
                "frames_sent": 0, "frames_recv": 0,
                "bytes_sent": 0, "bytes_recv": 0,
                "t_first": rec.get("ts"), "t_last": rec.get("ts"),
            }
        row["steps"] += 1
        row["stall_ms"] += float(d.get("stall_ms") or 0.0)
        for key in ("frames_sent", "frames_recv",
                    "bytes_sent", "bytes_recv"):
            row[key] += int(d.get(key) or 0)
        row["t_last"] = rec.get("ts")
    out = [stages[s] for s in sorted(stages)]
    for row in out:
        row["stall_ms"] = round(row["stall_ms"], 3)
    return out


def render_stage_spans(spans, echo=print):
    echo("mpmd stage transfer spans:")
    for row in spans:
        line = ("  stage %d: %d step(s), stall %.1fms, "
                "%d frame(s) out / %d in, %d B out / %d B in"
                % (row["stage"], row["steps"], row["stall_ms"],
                   row["frames_sent"], row["frames_recv"],
                   row["bytes_sent"], row["bytes_recv"]))
        if row.get("span"):
            line += "  span=%s" % row["span"]
        echo(line)


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace-event JSON
# ---------------------------------------------------------------------------


def _us(ts, t0):
    return round((ts - t0) * 1e6, 1)


def _slice(name, ts, dur_us, pid, tid, args=None):
    ev = {"name": name, "ph": "X", "ts": ts, "dur": max(1.0, dur_us),
          "pid": pid, "tid": tid}
    if args:
        ev["args"] = args
    return ev


def _meta(name, value, pid, tid):
    return {"name": name, "ph": "M", "ts": 0, "pid": pid, "tid": tid,
            "args": {"name": value}}


def perfetto_export(trees):
    """Trees -> Chrome trace-event JSON (one process per request, one
    thread per dispatch attempt). Entry shape is pinned as
    TRACE_RECORD_SCHEMA in tests/schema_validate.py."""
    out = []
    stamps = [r["ts"] for t in trees for r in t["events"] if "ts" in r]
    t0 = min(stamps) if stamps else 0.0
    for pid, tree in enumerate(trees, 1):
        evts = [r for r in tree["events"] if "ts" in r]
        if not evts:
            continue
        first, last = evts[0]["ts"], evts[-1]["ts"]
        out.append(_meta("process_name",
                         "request %s" % tree["request_id"], pid, 0))
        root_args = {"request_id": str(tree["request_id"])}
        if tree["trace"]:
            root_args["trace"] = tree["trace"]
        if tree["root_span"]:
            root_args["span"] = tree["root_span"]
        out.append(_slice("request %s" % tree["request_id"],
                          _us(first, t0), (last - first) * 1e6, pid, 0,
                          root_args))
        for tid, att in enumerate(tree["attempts"], 1):
            label = ("replica %s" % att["replica"]
                     if att["replica"] is not None else "serve")
            out.append(_meta("thread_name", label, pid, tid))
            a_evts = [r for r in att["events"] if "ts" in r]
            start = att["t_dispatch"] if att["t_dispatch"] is not None \
                else (a_evts[0]["ts"] if a_evts else first)
            end = a_evts[-1]["ts"] if a_evts else start
            args = {"status": att["status"]}
            if att["span"]:
                args["span"] = att["span"]
            if att["delivered"] is not None:
                args["delivered"] = att["delivered"]
            out.append(_slice("attempt %s" % (att["dispatch"] or 1),
                              _us(start, t0), (end - start) * 1e6,
                              pid, tid, args))
            out.extend(_phase_slices(att, a_evts, t0, pid, tid))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _phase_slices(att, a_evts, t0, pid, tid):
    """queue / prefill / decode sub-slices + instants for one attempt."""
    out = []

    def ev(name):
        return _first_named(a_evts, name)

    queued, prefill = ev("serve.request.queued"), \
        ev("serve.request.prefill")
    first_tok, fin = att["first_token"], att["finished"]
    if queued and prefill:
        out.append(_slice("queue", _us(queued["ts"], t0),
                          (prefill["ts"] - queued["ts"]) * 1e6, pid, tid))
    if prefill and first_tok:
        out.append(_slice("prefill", _us(prefill["ts"], t0),
                          (first_tok["ts"] - prefill["ts"]) * 1e6,
                          pid, tid))
    for rec in a_evts:
        if rec.get("name") == _CHUNK_TIMER and rec.get("ms") is not None:
            out.append(_slice(
                "prefill_chunk",
                _us(rec["ts"], t0) - rec["ms"] * 1000, rec["ms"] * 1000,
                pid, tid, {"tokens": _data(rec).get("tokens")}))
    if first_tok and fin:
        out.append(_slice("decode", _us(first_tok["ts"], t0),
                          (fin["ts"] - first_tok["ts"]) * 1e6, pid, tid,
                          {"new_tokens": _data(fin).get("new_tokens")}))
    if first_tok:
        out.append({"name": "first_token", "ph": "i",
                    "ts": _us(first_tok["ts"], t0), "pid": pid,
                    "tid": tid, "s": "t",
                    "args": {"ttft_ms": _data(first_tok).get("ttft_ms")}})
    if att["failover"]:
        out.append({"name": "failover", "ph": "i",
                    "ts": _us(att["failover"]["ts"], t0), "pid": pid,
                    "tid": tid, "s": "t",
                    "args": {"delivered": att["delivered"]}})
    return out


def perfetto_export_timers(records):
    """Fallback for runs with no serving requests: every timer record
    becomes a slice (process = step/task, thread = rank), so train-side
    persist.* / checkpoint.* / elastic.* spans open in Perfetto too."""
    timers = [r for r in records
              if r.get("type") == "timer" and r.get("ms") is not None]
    # MPMD transfer events render as stall slices on the stage's lane:
    # the interval the stage sat blocked on the transport, ending at the
    # record's timestamp
    transfers = [r for r in records
                 if r.get("name") == _MPMD_TRANSFER
                 and float(_data(r).get("stall_ms") or 0.0) > 0]
    out = []
    t0 = min((r["ts"] - r["ms"] / 1000.0 for r in timers), default=0.0)
    pids = {}

    def _pid(rec):
        key = "%s/%s" % (rec.get("step", "?"), rec.get("task_id", "?"))
        if key not in pids:
            pids[key] = len(pids) + 1
            out.append(_meta("process_name", key, pids[key], 0))
        return pids[key]

    for rec in timers:
        pid = _pid(rec)
        tid = int(rec.get("rank") or 0)
        out.append(_slice(rec.get("name", "span"),
                          _us(rec["ts"] - rec["ms"] / 1000.0, t0),
                          rec["ms"] * 1000, pid, tid,
                          _data(rec) or None))
    for rec in transfers:
        d = _data(rec)
        stall_ms = float(d.get("stall_ms") or 0.0)
        args = {"stage": d.get("stage"), "stall_ms": stall_ms}
        if d.get("span"):
            args["span"] = d["span"]
        out.append(_slice("mpmd.transfer_stall",
                          _us(rec["ts"] - stall_ms / 1000.0, t0),
                          stall_ms * 1000, _pid(rec),
                          int(rec.get("rank") or 0), args))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# rendering + entry point
# ---------------------------------------------------------------------------


def render_tree(tree, echo=print):
    head = "request %s" % tree["request_id"]
    if tree["trace"]:
        head += "  trace=%s" % tree["trace"][:16]
    echo(head)
    if tree["shed"] is not None:
        echo("  shed: %s" % _data(tree["shed"]).get("reason"))
    t_base = tree["events"][0]["ts"] if tree["events"] else 0.0
    for att in tree["attempts"]:
        where = ("replica %s" % att["replica"]
                 if att["replica"] is not None else "serve")
        line = "  attempt %s -> %s [%s]" % (
            att["dispatch"] or 1, where, att["status"])
        if att["status"] == "failover":
            line += " after %s token(s)" % (att["delivered"] or 0)
        elif att["finished"] is not None:
            line += ", %s token(s)" % _data(att["finished"]).get(
                "new_tokens")
        echo(line)
        for rec in att["events"]:
            name = rec.get("name", "").split(".")[-1]
            if rec.get("name") == _CHUNK_TIMER:
                name = "prefill_chunk(%s tok)" % _data(rec).get("tokens")
            echo("    +%8.1fms  %s" % ((rec["ts"] - t_base) * 1000, name))
    decomp = ttft_decomposition(tree)
    if decomp:
        echo("  ttft %.1fms = router %.1f + queue %.1f + prefill %.1f "
             "+ first_decode %.1f (sum %.1f, err %.1f%%)"
             % (decomp["measured_ttft_ms"], decomp["router_queue_ms"],
                decomp["replica_queue_ms"], decomp["prefill_ms"],
                decomp["first_decode_ms"], decomp["sum_ms"],
                decomp["err_pct"]))


def show_trace(flow_datastore, run_id, request=None, perfetto=None,
               as_json=False, echo=print):
    """CLI entry: assemble, render (or JSON-dump), optionally export.
    Returns the number of request trees rendered."""
    records = telemetry.read_run_records(flow_datastore, run_id)
    if not records:
        echo("no telemetry records for run %s" % run_id)
        return 0
    trees = build_request_traces(records)
    stage_spans = build_stage_spans(records) if request is None else []
    if request is not None:
        trees = [t for t in trees if str(t["request_id"]) == str(request)]
        if not trees:
            echo("no trace for request %s" % request)
            return 0
    if perfetto:
        doc = (perfetto_export(trees) if trees
               else perfetto_export_timers(records))
        with open(perfetto, "w") as f:
            json.dump(doc, f)
        echo("wrote %d trace events to %s"
             % (len(doc["traceEvents"]), perfetto))
    if not trees and not stage_spans:
        echo("no request traces in run %s (%d records; train-side timer "
             "spans export via --perfetto)" % (run_id, len(records)))
        return 0
    if as_json:
        payload = []
        for tree in trees:
            payload.append({
                "request_id": tree["request_id"],
                "trace": tree["trace"],
                "root_span": tree["root_span"],
                "attempts": [
                    {k: att[k] for k in ("span", "replica", "dispatch",
                                         "status", "delivered")}
                    for att in tree["attempts"]],
                "ttft": ttft_decomposition(tree),
            })
        doc = {"requests": payload}
        if stage_spans:
            doc["mpmd_stages"] = stage_spans
        echo(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for tree in trees:
            render_tree(tree, echo)
        if stage_spans:
            render_stage_spans(stage_spans, echo)
    return len(trees)
