"""`python -m metaflow_tpu knobs`: render and check the knob registry.

Four views over metaflow_tpu/knobs.py (the single source of truth for
every TPUFLOW_* environment knob):

    knobs               human-readable table, grouped by subsystem
    knobs --json        machine-readable registry dump (v1)
    knobs --markdown    the exact content of docs/knobs.md
    knobs --ordering    the deadline-ordering lattice edges
    knobs --check-env   validate the LIVE environment against the
                        lattice; exit 1 on any violation

--check-env is the operator-facing entry of the same check the pre-run
gate applies to every run (warn by default, fatal under
TPUFLOW_STRICT_CHECK=1): run it in CI over the environment a template
exports before the template ships.
"""

from .. import knobs


def show_knobs(as_json=False, markdown=False, ordering=False,
               check_env=False, echo=print):
    """Body of `python -m metaflow_tpu knobs`; returns the exit code."""
    if as_json:
        echo(knobs.render_json())
        return 0
    if markdown:
        echo(knobs.render_markdown().rstrip("\n"))
        return 0
    if ordering:
        echo("deadline-ordering lattice (lo <= hi):")
        for edge in knobs.ORDERING:
            suffix = "  [skipped when either side is 0]" \
                if edge.skip_if_zero else ""
            echo("  %s <= %s%s" % (edge.lo, edge.hi, suffix))
            echo("      %s" % edge.reason)
        return 0
    if check_env:
        violations = knobs.validate_env()
        overridden = [n for n in sorted(knobs.KNOBS) if knobs.is_set(n)]
        echo("%d knob(s) set in this environment"
             % len(overridden))
        for name in overridden:
            echo("  %s=%s" % (name, knobs.get_raw(name)))
        if violations:
            echo("%d ordering violation(s):" % len(violations))
            for violation in violations:
                echo("  %s" % violation.render())
            return 1
        echo("deadline ordering: ok (%d edge(s) checked)"
             % len(knobs.ORDERING))
        return 0

    for sub, entries in knobs.by_subsystem():
        echo("%s:" % sub)
        for knob in entries:
            star = "*" if knobs.is_set(knob.name) else " "
            echo("%s %-38s %-6s default=%-12s %s"
                 % (star, knob.name, knob.ktype,
                    knobs._default_str(knob), knob.doc))
    echo("")
    echo("* = set in the current environment. "
         "--json / --markdown / --ordering / --check-env for more.")
    return 0
