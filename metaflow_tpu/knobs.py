"""Declarative registry of every ``TPUFLOW_*`` environment knob.

This module is the single source of truth for knob names, types,
defaults, units, owning subsystems, and the cross-knob deadline
ordering lattice. Library code reads knobs through the typed
accessors (:func:`get_str` / :func:`get_int` / :func:`get_float` /
:func:`get_bool`) instead of raw ``os.environ`` lookups — the
`contracts` static-analysis pass (metaflow_tpu/analysis/contracts.py)
flags any raw ``TPUFLOW_*`` read outside this file, and
``tests/test_contracts.py`` keeps the library self-scan at zero
errors, so a default can no longer drift between two call sites.

Semantics, pinned so migration is behavior-preserving:

* unset OR empty-string value -> registry default (CI templates export
  ``VAR=`` to mean "use the default"; metaflow_config always treated
  empty as unset, and the registry extends that to every knob);
* malformed int/float -> registry default (the historical
  ``util.env_int`` degrade-don't-crash contract: a typo'd knob must
  never kill a gang at import time);
* bool: a set value counts as false only for ``0/false/no/off``
  (case-insensitive) — everything else is true, matching the dominant
  ``!= "0"`` convention at the old read sites;
* ``fallback=`` overrides the registry default at one call site for
  *computed* defaults (cpu counts, tmp dirs, "inherit the recv
  timeout"). Literal fallbacks that disagree with the registry are
  exactly the drift the contracts pass exists to catch — keep
  fallbacks dynamic.

``python -m metaflow_tpu knobs`` renders this registry (``--markdown``
regenerates docs/knobs.md byte-identically; ``--check-env`` runs the
ordering lattice against the live environment).
"""

import json
import os

_UNSET = object()

#: values (lowercased, stripped) that make a *set* bool knob false
_FALSEY = ("0", "false", "no", "off")

#: subsystem render order for docs/CLI — append, never reorder, or the
#: docs/knobs.md byte-identity test goes red
SUBSYSTEM_ORDER = (
    "config", "runtime", "datastore", "data", "training", "ops", "spmd",
    "progress", "elastic", "serving", "fleet", "slo", "telemetry",
    "analysis", "tpu", "conda", "chaos", "internal", "online", "tenancy",
)


class UnknownKnobError(KeyError):
    """Raised when an accessor is called with an unregistered name."""

    def __init__(self, name, suggestion=None):
        self.name = name
        self.suggestion = suggestion
        msg = "unregistered knob %r" % (name,)
        if suggestion:
            msg += " (did you mean %r?)" % (suggestion,)
        super(UnknownKnobError, self).__init__(msg)


class Knob(object):
    """One registered knob: declarative metadata, no behavior."""

    __slots__ = ("name", "ktype", "default", "unit", "subsystem", "doc")

    def __init__(self, name, ktype, default, unit, subsystem, doc):
        self.name = name
        self.ktype = ktype          # "str" | "int" | "float" | "bool" | "path"
        self.default = default      # typed, or None for "no default"
        self.unit = unit            # "s" | "ms" | "MB" | ... | ""
        self.subsystem = subsystem  # one of SUBSYSTEM_ORDER
        self.doc = doc              # one line, rendered into docs/knobs.md

    def to_dict(self):
        return {
            "name": self.name,
            "type": self.ktype,
            "default": self.default,
            "unit": self.unit,
            "subsystem": self.subsystem,
            "doc": self.doc,
        }


KNOBS = {}

#: dynamic knob families read by prefix iteration, not by literal name
PREFIXES = {
    "TPUFLOW_PARAM_": "flow parameter values injected per-pod by the "
                      "Argo compiler (--params-from-env)",
}


def _k(name, ktype, default, unit, subsystem, doc):
    assert name not in KNOBS, name
    assert subsystem in SUBSYSTEM_ORDER, subsystem
    KNOBS[name] = Knob(name, ktype, default, unit, subsystem, doc)


# --- config ----------------------------------------------------------------
_k("TPUFLOW_PROFILE", "str", "", "", "config",
   "active config profile name ('' = default profile)")
_k("TPUFLOW_HOME", "path", "~/.tpuflowconfig", "", "config",
   "directory holding config profiles")
_k("TPUFLOW_SERVICE_URL", "str", None, "", "config",
   "metadata REST service URL (via from_conf; METAFLOW_ fallback)")
_k("TPUFLOW_DEFAULT_DATASTORE", "str", "local", "", "config",
   "datastore backend when a flow does not pick one (via from_conf)")
_k("TPUFLOW_DEFAULT_METADATA", "str", "local", "", "config",
   "metadata provider when a flow does not pick one (via from_conf)")
_k("TPUFLOW_DATASTORE_SYSROOT_LOCAL", "path", None, "", "config",
   "local datastore root (default: ./.tpuflow; via from_conf)")
_k("TPUFLOW_DATASTORE_SYSROOT_GS", "str", None, "", "config",
   "gs:// datastore root for the gs backend (via from_conf)")
_k("TPUFLOW_USER", "str", None, "", "config",
   "username recorded in run metadata (falls back to USER et al.)")
_k("TPUFLOW_DEBUG", "bool", False, "", "config",
   "print tracebacks for framework exceptions")
_k("TPUFLOW_MONITOR", "str", "file", "", "config",
   "monitor sidecar backend")
_k("TPUFLOW_EVENT_LOGGER", "str", "file", "", "config",
   "event-logger sidecar backend")
_k("TPUFLOW_DISABLE_EXTENSIONS", "bool", False, "", "config",
   "skip loading metaflow_extensions packages")
_k("TPUFLOW_GS_ENDPOINT", "str", "https://storage.googleapis.com", "",
   "config", "GS JSON-API endpoint (point at a fake-gcs for tests)")
_k("TPUFLOW_ARGO_EVENTS_URL", "str", None, "", "config",
   "Argo Events webhook URL for @trigger publishing")
_k("TPUFLOW_KUBECTL", "str", "kubectl", "", "config",
   "kubectl binary used by the Argo deployer")
_k("TPUFLOW_OTEL_ENDPOINT", "str", None, "", "config",
   "OTLP endpoint enabling OpenTelemetry span export")

# --- runtime ---------------------------------------------------------------
_k("TPUFLOW_ELASTIC", "bool", True, "", "runtime",
   "route gang retries through the elastic supervisor (0 = legacy "
   "immediate re-fork)")
_k("TPUFLOW_FORK_WORKERS", "bool", True, "", "runtime",
   "fork local step workers instead of spawning fresh interpreters")
_k("TPUFLOW_GANG_FINALIZE_TIMEOUT", "float", 300.0, "s", "runtime",
   "deadline for gang-wide finalize barrier at task exit")
_k("TPUFLOW_GANG_NODE_WAIT_TIMEOUT_S", "float", 0.0, "s", "runtime",
   "deadline for multi-node gang peers to appear (0 = wait forever)")
_k("TPUFLOW_DAEMON_SOCKET", "path", None, "", "runtime",
   "devstack daemon control socket (default: per-uid tmp path)")
_k("TPUFLOW_DATATOOLS_ROOT", "path", None, "", "runtime",
   "root for datatools blob uploads (default: cwd)")
_k("TPUFLOW_INCLUDEFILE_MAX_MB", "int", 10240, "MB", "runtime",
   "size cap for IncludeFile uploads")
_k("TPUFLOW_ESCAPE_SOCKET", "path", None, "", "runtime",
   "env-escape server socket (set by the server process)")

# --- datastore -------------------------------------------------------------
_k("TPUFLOW_BLOB_CACHE", "bool", True, "", "datastore",
   "share the host-local CAS blob cache for non-local datastores")
_k("TPUFLOW_PERSIST_PIPELINE", "bool", True, "", "datastore",
   "overlap artifact persist with step execution")
_k("TPUFLOW_PERSIST_WORKERS", "int", None, "count", "datastore",
   "persist pipeline serializer threads (default: min(8, max(2, cpus)))")
_k("TPUFLOW_PERSIST_UPLOADS", "int", None, "count", "datastore",
   "persist pipeline upload threads (default: min(8, max(2, cpus)))")
_k("TPUFLOW_PERSIST_INFLIGHT_MB", "int", 0, "MB", "datastore",
   "persist pipeline in-flight byte budget (0 = built-in 512)")
_k("TPUFLOW_STORAGE_RETRIES", "int", 3, "count", "datastore",
   "retry budget for storage operations")
_k("TPUFLOW_STORAGE_TIMEOUT_S", "float", 0.0, "s", "datastore",
   "per-attempt deadline for blocking storage ops (0 = no deadline)")
_k("TPUFLOW_SCRATCH_DIR", "path", None, "", "datastore",
   "scratch spill directory for large blob staging")
_k("TPUFLOW_CLIENT_CACHE", "path", None, "", "datastore",
   "client-side artifact cache dir (default: $TMPDIR/tpuflow_cache)")

# --- data ------------------------------------------------------------------
_k("TPUFLOW_DATA_READAHEAD_MB", "float", 64.0, "MB", "data",
   "shard readahead budget per reader")
_k("TPUFLOW_DATA_WORKERS", "int", 8, "count", "data",
   "shard fetch worker threads")

# --- training --------------------------------------------------------------
_k("TPUFLOW_PEAK_TFLOPS", "float", None, "TFLOP/s", "training",
   "per-chip peak TFLOPs override for MFU accounting")
_k("TPUFLOW_DECODE_CHUNK", "int", 256, "tokens", "training",
   "decode microbatch chunk length")
_k("TPUFLOW_ZERO", "bool", False, "", "training",
   "ZeRO-style optimizer-state sharding over the data axis")

# --- ops -------------------------------------------------------------------
_k("TPUFLOW_FLASH_BLOCK_Q", "int", 128, "", "ops",
   "flash-attention query block size")
_k("TPUFLOW_FLASH_BLOCK_K", "int", 128, "", "ops",
   "flash-attention key/value block size")
_k("TPUFLOW_GMM_BLOCK_S", "int", 128, "", "ops",
   "grouped matmul block size along tokens")
_k("TPUFLOW_GMM_BLOCK_F", "int", 128, "", "ops",
   "grouped matmul block size along features")
_k("TPUFLOW_GMM_BLOCK_D", "int", 128, "", "ops",
   "grouped matmul block size along model dim")
_k("TPUFLOW_RING_IMPL", "str", "auto", "", "ops",
   "ring-attention implementation (auto|collective|manual)")

# --- spmd ------------------------------------------------------------------
_k("TPUFLOW_SANITIZE", "bool", False, "", "spmd",
   "enable the gang sanitizer (cross-rank divergence probes)")
_k("TPUFLOW_SANITIZE_EVERY", "int", 64, "steps", "spmd",
   "steps between sanitizer probes")
_k("TPUFLOW_SANITIZE_WINDOW", "int", 512, "steps", "spmd",
   "sanitizer rolling-window length")
_k("TPUFLOW_SANITIZE_TIMEOUT", "float", 30.0, "s", "spmd",
   "sanitizer collective barrier deadline")
_k("TPUFLOW_MPMD_RECV_TIMEOUT_S", "float", 60.0, "s", "spmd",
   "MPMD activation recv deadline per hop")
_k("TPUFLOW_MPMD_SEND_TIMEOUT_S", "float", None, "s", "spmd",
   "MPMD activation send deadline (default: inherit recv timeout)")
_k("TPUFLOW_MPMD_CONNECT_TIMEOUT_S", "float", 30.0, "s", "spmd",
   "MPMD stage link connect deadline")
_k("TPUFLOW_MPMD_LINK_LATENCY_MS", "float", 0.0, "ms", "spmd",
   "injected DCN link latency for tests/chaos")
_k("TPUFLOW_MPMD_SYNC", "bool", False, "", "spmd",
   "force synchronous (non-overlapped) MPMD exchange")

# --- progress --------------------------------------------------------------
_k("TPUFLOW_PROGRESS_EVERY_S", "float", 1.0, "s", "progress",
   "progress-beat write throttle per rank")
_k("TPUFLOW_HANG_DETECT", "bool", True, "", "progress",
   "enable the gang hang watchdog")
_k("TPUFLOW_HANG_FLOOR_S", "float", 60.0, "s", "progress",
   "minimum no-progress window before hang escalation")
_k("TPUFLOW_HANG_COMPILE_GRACE_S", "float", 600.0, "s", "progress",
   "hang deadline while a first compile is plausible")
_k("TPUFLOW_HANG_DEADLINE_MULT", "float", 8.0, "x", "progress",
   "hang deadline as a multiple of the step-time EMA")
_k("TPUFLOW_HANG_POLL_S", "float", 5.0, "s", "progress",
   "watchdog poll interval")
_k("TPUFLOW_HANG_KILL_GRACE_S", "float", 5.0, "s", "progress",
   "SIGTERM-to-SIGKILL grace when escalating a hang")
_k("TPUFLOW_HANG_DUMP_WAIT_S", "float", 0.5, "s", "progress",
   "wait after requesting stack dumps before killing")
_k("TPUFLOW_HANG_DUMP_SIGNAL", "int", 0, "signal", "progress",
   "signal number for all-thread stack dumps (0 = SIGQUIT)")
_k("TPUFLOW_HANG_SAME_STEP_MAX", "int", 2, "count", "progress",
   "hang escalations tolerated on one step before shrinking")

# --- elastic ---------------------------------------------------------------
_k("TPUFLOW_ELASTIC_RESIZE", "bool", True, "", "elastic",
   "allow the supervisor to shrink/grow the gang")
_k("TPUFLOW_ELASTIC_RETRIES", "int", 8, "count", "elastic",
   "supervisor relaunch budget")
_k("TPUFLOW_ELASTIC_SHRINK_AFTER", "int", 2, "count", "elastic",
   "consecutive capacity failures before shrinking")
_k("TPUFLOW_ELASTIC_GROW_EVERY_S", "float", 5.0, "s", "elastic",
   "parked-capacity recheck interval (grow probe cadence)")
_k("TPUFLOW_CAPACITY_ORACLE", "str", "none", "", "elastic",
   "capacity oracle spec (none | static:N | scripted:... | gce)")
_k("TPUFLOW_CAPACITY_HINT", "int", None, "count", "elastic",
   "externally supplied available-chip hint")
_k("TPUFLOW_RETRY_BACKOFF_BASE_S", "float", 0.2, "s", "elastic",
   "retry backoff base delay")
_k("TPUFLOW_RETRY_BACKOFF_CAP_S", "float", 60.0, "s", "elastic",
   "retry backoff delay cap")
_k("TPUFLOW_RETRY_BACKOFF_JITTER", "float", 0.5, "frac", "elastic",
   "retry backoff jitter fraction")
_k("TPUFLOW_RETRY_BACKOFF_SEED", "int", None, "", "elastic",
   "deterministic backoff jitter seed (tests)")

# --- serving ---------------------------------------------------------------
_k("TPUFLOW_PAGED", "bool", False, "", "serving",
   "serve with the paged KV-cache engine")
_k("TPUFLOW_KV_PAGE_TOKENS", "int", 16, "tokens", "serving",
   "tokens per KV page (paged engine allocation granule)")
_k("TPUFLOW_SPEC_K", "int", 0, "tokens", "serving",
   "speculative draft length (0 = disabled)")
_k("TPUFLOW_PREFIX_CACHE_MB", "float", 0.0, "MB", "serving",
   "prefix KV cache budget (0 = disabled)")
_k("TPUFLOW_SERVE_LATENCY_WINDOW", "int", 1024, "count", "serving",
   "latency percentile reservoir size")
_k("TPUFLOW_SERVE_STEP_DELAY_MS", "float", 0.0, "ms", "serving",
   "injected per-decode-step delay for tests/chaos")
_k("TPUFLOW_TRACE_REQUESTS", "bool", True, "", "serving",
   "per-request spans in the serving scheduler")

# --- fleet -----------------------------------------------------------------
_k("TPUFLOW_FLEET_MAX_INFLIGHT", "int", None, "count", "fleet",
   "fleet-wide in-flight request cap (default: replicas * slots)")
_k("TPUFLOW_FLEET_FAILOVER", "bool", True, "", "fleet",
   "redispatch requests off dead replicas")
_k("TPUFLOW_FLEET_RESTART", "bool", True, "", "fleet",
   "restart dead replicas")
_k("TPUFLOW_FLEET_MAX_RESTARTS", "int", 16, "count", "fleet",
   "replica restart budget per fleet")
_k("TPUFLOW_FLEET_HEALTH_INTERVAL_S", "float", 1.0, "s", "fleet",
   "replica health-probe interval")
_k("TPUFLOW_FLEET_HEALTH_FAILS", "int", 3, "count", "fleet",
   "consecutive probe failures before a replica is dead")
_k("TPUFLOW_FLEET_SPAWN_TIMEOUT_S", "float", 180.0, "s", "fleet",
   "replica spawn-to-ready deadline")
_k("TPUFLOW_FLEET_REDISPATCH_MAX", "int", 3, "count", "fleet",
   "failover redispatch attempts per request")
_k("TPUFLOW_FLEET_WAIT_S", "float", 15.0, "s", "fleet",
   "request wait-for-dispatch deadline")
_k("TPUFLOW_FLEET_AUTOSCALE", "bool", False, "", "fleet",
   "enable queue-driven replica autoscaling")
_k("TPUFLOW_FLEET_MIN_REPLICAS", "int", 1, "count", "fleet",
   "autoscaler floor")
_k("TPUFLOW_FLEET_MAX_REPLICAS", "int", 8, "count", "fleet",
   "autoscaler ceiling")
_k("TPUFLOW_FLEET_SCALE_OUT_QUEUE", "float", 2.0, "x", "fleet",
   "scale out when queue depth per replica exceeds this")
_k("TPUFLOW_FLEET_SCALE_IN_OCC", "float", 0.25, "frac", "fleet",
   "scale in when occupancy drops below this")
_k("TPUFLOW_FLEET_SCALE_SUSTAIN", "int", 3, "count", "fleet",
   "consecutive breaches before the autoscaler acts")

# --- slo -------------------------------------------------------------------
_k("TPUFLOW_SLO_FILE", "path", None, "", "slo",
   "JSON file of SLO rules")
_k("TPUFLOW_SLO_P99_TTFT_MS", "float", None, "ms", "slo",
   "upper bound on p99 time-to-first-token")
_k("TPUFLOW_SLO_P99_ITL_MS", "float", None, "ms", "slo",
   "upper bound on p99 inter-token latency")
_k("TPUFLOW_SLO_INPUT_STALL_FRAC", "float", None, "frac", "slo",
   "upper bound on input-pipeline stall fraction")
_k("TPUFLOW_SLO_RESTART_RATE_PER_MIN", "float", None, "1/min", "slo",
   "upper bound on replica restart rate")
_k("TPUFLOW_SLO_DESYNC", "float", None, "count", "slo",
   "upper bound on sanitizer desync count")

# --- telemetry -------------------------------------------------------------
_k("TPUFLOW_TELEMETRY", "bool", True, "", "telemetry",
   "enable the flight recorder")
_k("TPUFLOW_TELEMETRY_FLUSH_EVERY", "int", 512, "records", "telemetry",
   "flush the record buffer every N records")
_k("TPUFLOW_PROFILE_STEPS", "str", "", "", "telemetry",
   "profiler step window spec (e.g. '10:12')")
_k("TPUFLOW_PROFILE_REQUEST", "path", "", "", "telemetry",
   "touch-file that requests an ad-hoc profile capture")
_k("TPUFLOW_PROFILE_SIGNAL", "bool", False, "", "telemetry",
   "install the signal-triggered profile capture handler")

# --- analysis --------------------------------------------------------------
_k("TPUFLOW_ANALYZE", "bool", True, "", "analysis",
   "run the pre-run static-analysis gate")
_k("TPUFLOW_STRICT_CHECK", "bool", False, "", "analysis",
   "escalate analyzer warnings at the pre-run gate to fatal")

# --- tpu -------------------------------------------------------------------
_k("TPUFLOW_TPU_LAUNCHER", "str", None, "", "tpu",
   "launch @tpu steps through the TPU VM launcher when set")
_k("TPUFLOW_TPU_PROJECT", "str", None, "", "tpu",
   "GCP project for TPU provisioning")
_k("TPUFLOW_TPU_ZONE", "str", None, "", "tpu",
   "GCE zone for TPU provisioning")
_k("TPUFLOW_TPU_TYPE", "str", None, "", "tpu",
   "accelerator type (default: the topology knob)")
_k("TPUFLOW_TPU_TOPOLOGY", "str", "v5litepod-4", "", "tpu",
   "TPU topology / accelerator shape")
_k("TPUFLOW_TPU_VERSION", "str", "tpu-ubuntu2204-base", "", "tpu",
   "TPU VM runtime version")
_k("TPUFLOW_TPU_REUSE", "str", None, "", "tpu",
   "reuse this existing TPU VM instead of provisioning")
_k("TPUFLOW_TPU_SPOT", "bool", False, "", "tpu",
   "provision spot (preemptible) TPU VMs")
_k("TPUFLOW_TPU_KEEP", "bool", False, "", "tpu",
   "keep ephemeral TPU VMs alive after the step")
_k("TPUFLOW_PACKAGE_URL", "str", None, "", "tpu",
   "pre-uploaded code package URL for TPU VM bootstrap")
_k("TPUFLOW_SPOT_MARKER_TTL_S", "float", 900.0, "s", "tpu",
   "preemption marker freshness window")
_k("TPUFLOW_SPOT_METADATA_URL", "str",
   "http://metadata.google.internal/computeMetadata/v1/instance/preempted",
   "", "tpu", "preemption metadata probe URL")

# --- conda -----------------------------------------------------------------
_k("TPUFLOW_MICROMAMBA", "path", None, "", "conda",
   "micromamba binary override")
_k("TPUFLOW_CONDA_OFFLINE", "bool", False, "", "conda",
   "resolve conda environments offline")
_k("TPUFLOW_CONDA_PKGS_DIRS", "path", None, "", "conda",
   "conda package cache directory override")
_k("TPUFLOW_WHEELHOUSE", "path", None, "", "conda",
   "directory of wheels for offline pip installs")

# --- chaos -----------------------------------------------------------------
_k("TPUFLOW_CHAOS", "str", "", "", "chaos",
   "chaos schedule spec ('' = disabled)")
_k("TPUFLOW_CHAOS_STEPS", "int", 10, "steps", "chaos",
   "seeded chaos horizon")
_k("TPUFLOW_CHAOS_NKILLS", "int", 1, "count", "chaos",
   "kills drawn from the chaos seed")
_k("TPUFLOW_CHAOS_SLOW_S", "float", 1.0, "s", "chaos",
   "injected slowdown duration")
_k("TPUFLOW_CHAOS_DIR", "path", None, "", "chaos",
   "once-only chaos ledger dir (default: run-scoped tmp)")
_k("TPUFLOW_CHAOS_FLEET", "str", "", "", "chaos",
   "fleet chaos schedule spec ('' = disabled)")
_k("TPUFLOW_CHAOS_FLEET_DISPATCHES", "int", 8, "count", "chaos",
   "seeded fleet-chaos dispatch horizon")
_k("TPUFLOW_CHAOS_FLEET_NKILLS", "int", 1, "count", "chaos",
   "replica kills drawn from the fleet-chaos seed")

# --- internal (set by the runtime, read by children — not user-facing) -----
_k("TPUFLOW_QUEUE_TS", "float", None, "s", "internal",
   "epoch timestamp of task enqueue (set by the scheduler)")
_k("TPUFLOW_STEP_ARGV", "str", None, "", "internal",
   "step argv payload for the launcher trampoline")
_k("TPUFLOW_TRIGGER_EVENTS", "str", None, "", "internal",
   "JSON trigger-event payload injected by Argo")
_k("TPUFLOW_ELASTIC_SIZE", "int", None, "count", "internal",
   "gang size granted by the elastic supervisor")
_k("TPUFLOW_ELASTIC_TOPOLOGY", "str", None, "", "internal",
   "gang topology granted by the elastic supervisor")
_k("TPUFLOW_NUMPAR_INT", "str", None, "", "internal",
   "Argo template placeholder for the num-parallel integer")
_k("TPUFLOW_REPLICA_TELEMETRY_FLOW", "str", None, "", "internal",
   "flight-recorder flow name injected into serve replicas")
_k("TPUFLOW_REPLICA_TELEMETRY_RUN", "str", None, "", "internal",
   "flight-recorder run id injected into serve replicas")

# --- online (metaflow_tpu/online/: actor-learner loop) ---------------------
_k("TPUFLOW_ONLINE_ROUNDS", "int", 4, "count", "online",
   "rollout->append->train->push rounds per `tpuflow online` run")
_k("TPUFLOW_ONLINE_ROLLOUTS", "int", 8, "count", "online",
   "rollouts the actor generates per round")
_k("TPUFLOW_ONLINE_STEPS_PER_ROUND", "int", 2, "steps", "online",
   "learner train steps per round")
_k("TPUFLOW_ONLINE_PUSH_EVERY", "int", 1, "rounds", "online",
   "push learner weights to the actor every N rounds")
_k("TPUFLOW_ONLINE_MAX_NEW_TOKENS", "int", 16, "tokens", "online",
   "decode budget per rollout")
_k("TPUFLOW_ONLINE_MAX_LAG", "int", 2, "generations", "online",
   "off-policy guard: drop rollouts older than this many weight "
   "generations")
_k("TPUFLOW_ONLINE_FRESH_GENERATIONS", "int", 0, "generations", "online",
   "ReplayReader freshness window in generations (0 = no filter)")

# --- tenancy (serving/tenancy.py + cache_router.py: multi-tenant tier) -----
_k("TPUFLOW_TENANT_WEIGHTS", "str", "", "", "tenancy",
   "per-tenant DRR weights, 'gold=4,free=1' ('' = single-tenant)")
_k("TPUFLOW_TENANT_PRIORITIES", "str", "", "", "tenancy",
   "per-tenant priority classes, 'gold=high,free=low'")
_k("TPUFLOW_TENANT_BUDGETS", "str", "", "", "tenancy",
   "per-tenant token budgets per rolling window, 'free=4096'")
_k("TPUFLOW_TENANT_BUDGET_WINDOW_S", "float", 10.0, "s", "tenancy",
   "rolling window the tenant token budgets apply over")
_k("TPUFLOW_TENANT_DEFAULT", "str", "default", "", "tenancy",
   "bucket name for requests that carry no tenant id")
_k("TPUFLOW_TENANT_QUANTUM", "int", 256, "tokens", "tenancy",
   "DRR credit quantum per round (scaled by each tenant's weight)")
_k("TPUFLOW_TENANT_FLEET_MAP", "str", "", "", "tenancy",
   "federation tenant->fleet pins, 'gold=0,free=1' (else hash spread)")
_k("TPUFLOW_CACHE_ROUTE", "bool", True, "", "tenancy",
   "cache-aware dispatch: route to the replica with the longest "
   "cached prompt prefix")
_k("TPUFLOW_CACHE_ROUTE_BLOCK", "int", 16, "tokens", "tenancy",
   "digest block size for radix-cache replicas (paged replicas "
   "publish at their page size)")
_k("TPUFLOW_CACHE_ROUTE_DIGESTS", "int", 512, "count", "tenancy",
   "max prefix digests a replica publishes through /healthz")
_k("TPUFLOW_CACHE_ROUTE_MIN_TOKENS", "int", 32, "tokens", "tenancy",
   "cached-prefix score below this is treated as cold (load wins)")
_k("TPUFLOW_SLO_TENANT_P99_TTFT_MS", "float", None, "ms", "tenancy",
   "per-tenant upper bound on p99 time-to-first-token (one rule per "
   "live tenant)")


# ---------------------------------------------------------------------------
# deadline-ordering lattice
# ---------------------------------------------------------------------------

class Ordering(object):
    """One edge of the deadline partial order: ``lo`` must be <= ``hi``.

    ``skip_if_zero`` skips the check when either side is <= 0 (the
    0-means-disabled convention shared by the deadline knobs)."""

    __slots__ = ("lo", "hi", "reason", "skip_if_zero")

    def __init__(self, lo, hi, reason, skip_if_zero=False):
        assert lo in KNOBS and hi in KNOBS, (lo, hi)
        self.lo = lo
        self.hi = hi
        self.reason = reason
        self.skip_if_zero = skip_if_zero


#: unset knobs that inherit another knob's effective value
INHERITS = {
    "TPUFLOW_MPMD_SEND_TIMEOUT_S": "TPUFLOW_MPMD_RECV_TIMEOUT_S",
}

ORDERING = (
    Ordering("TPUFLOW_MPMD_RECV_TIMEOUT_S", "TPUFLOW_HANG_FLOOR_S",
             "a recv timeout above the hang floor lets the watchdog kill "
             "a gang that is merely backpressured — routine stalls become "
             "relaunch storms"),
    Ordering("TPUFLOW_MPMD_SEND_TIMEOUT_S", "TPUFLOW_HANG_FLOOR_S",
             "a send timeout above the hang floor lets the watchdog "
             "escalate before the sender can observe the slow link"),
    Ordering("TPUFLOW_MPMD_CONNECT_TIMEOUT_S", "TPUFLOW_MPMD_RECV_TIMEOUT_S",
             "connect must give up before the first recv deadline or the "
             "stage blames the payload for a link that never came up"),
    Ordering("TPUFLOW_PROGRESS_EVERY_S", "TPUFLOW_HANG_FLOOR_S",
             "beats throttled slower than the hang floor look like hangs "
             "to the watchdog even while the step is advancing"),
    Ordering("TPUFLOW_HANG_POLL_S", "TPUFLOW_HANG_FLOOR_S",
             "a poll interval above the floor cannot observe the floor"),
    Ordering("TPUFLOW_HANG_DUMP_WAIT_S", "TPUFLOW_HANG_KILL_GRACE_S",
             "the stack-dump wait must fit inside the kill grace or dumps "
             "are truncated by SIGKILL"),
    Ordering("TPUFLOW_STORAGE_TIMEOUT_S", "TPUFLOW_GANG_NODE_WAIT_TIMEOUT_S",
             "a storage attempt longer than the gang-node wait makes peers "
             "give up on a node that is still (legitimately) downloading",
             skip_if_zero=True),
    Ordering("TPUFLOW_RETRY_BACKOFF_BASE_S", "TPUFLOW_RETRY_BACKOFF_CAP_S",
             "a backoff base above the cap inverts the backoff curve"),
    Ordering("TPUFLOW_ELASTIC_GROW_EVERY_S", "TPUFLOW_RETRY_BACKOFF_CAP_S",
             "parked gangs must recheck capacity at least as often as "
             "failed ones retry, or parking is strictly worse than failing"),
    Ordering("TPUFLOW_FLEET_HEALTH_INTERVAL_S", "TPUFLOW_FLEET_SPAWN_TIMEOUT_S",
             "health probes slower than the spawn deadline can declare a "
             "replica dead before ever probing it"),
    Ordering("TPUFLOW_FLEET_WAIT_S", "TPUFLOW_FLEET_SPAWN_TIMEOUT_S",
             "requests must not shed while a replacement replica is still "
             "legitimately spawning"),
    Ordering("TPUFLOW_SANITIZE_TIMEOUT", "TPUFLOW_GANG_FINALIZE_TIMEOUT",
             "a sanitizer barrier longer than the finalize deadline turns "
             "every desync probe into a finalize failure"),
)


# ---------------------------------------------------------------------------
# typed accessors
# ---------------------------------------------------------------------------

def _nearest(name):
    best, best_d = None, 3
    for cand in KNOBS:
        d = _edit_distance(name, cand, best_d)
        if d < best_d:
            best, best_d = cand, d
    return best


def _edit_distance(a, b, cap=3):
    """Levenshtein distance, capped for cheap nearest-name lookup."""
    if abs(len(a) - len(b)) >= cap:
        return cap
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a):
        cur = [i + 1]
        for j, cb in enumerate(b):
            cur.append(min(prev[j + 1] + 1, cur[j] + 1,
                           prev[j] + (ca != cb)))
        if min(cur) >= cap:
            return cap
        prev = cur
    return min(prev[-1], cap)


def _knob(name):
    try:
        return KNOBS[name]
    except KeyError:
        raise UnknownKnobError(name, _nearest(name))


def _raw(name, env):
    """The raw string value, or None when unset/empty."""
    value = (env if env is not None else os.environ).get(name)
    if value is None or value == "":
        return None
    return value


def is_set(name, env=None):
    """True when the knob has a non-empty value in the environment."""
    _knob(name)
    return _raw(name, env) is not None


def get_raw(name, env=None):
    """The raw string value ('' and unset both -> None). Prefer the
    typed accessors; this exists for pass-through/forwarding sites."""
    _knob(name)
    return _raw(name, env)


def get_str(name, env=None, fallback=_UNSET):
    knob = _knob(name)
    value = _raw(name, env)
    if value is not None:
        return value
    return knob.default if fallback is _UNSET else fallback


def get_bool(name, env=None, fallback=_UNSET):
    knob = _knob(name)
    value = _raw(name, env)
    if value is not None:
        return value.strip().lower() not in _FALSEY
    return knob.default if fallback is _UNSET else fallback


def get_int(name, env=None, fallback=_UNSET):
    knob = _knob(name)
    default = knob.default if fallback is _UNSET else fallback
    value = _raw(name, env)
    if value is None:
        return default
    try:
        return int(float(value))
    except (TypeError, ValueError):
        return default


def get_float(name, env=None, fallback=_UNSET):
    knob = _knob(name)
    default = knob.default if fallback is _UNSET else fallback
    value = _raw(name, env)
    if value is None:
        return default
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


_GETTERS = {"str": get_str, "path": get_str, "bool": get_bool,
            "int": get_int, "float": get_float}


def get(name, env=None):
    """Type-dispatched read (registry decides the parse)."""
    return _GETTERS[_knob(name).ktype](name, env=env)


def items_with_prefix(prefix, env=None):
    """All set env entries under a registered dynamic prefix."""
    if prefix not in PREFIXES:
        raise UnknownKnobError(prefix)
    env = env if env is not None else os.environ
    return {k: v for k, v in env.items() if k.startswith(prefix)}


# ---------------------------------------------------------------------------
# lattice evaluation (lint time: defaults only; config-load time: live env)
# ---------------------------------------------------------------------------

class OrderingViolation(object):
    __slots__ = ("lo", "hi", "lo_value", "hi_value", "reason")

    def __init__(self, lo, hi, lo_value, hi_value, reason):
        self.lo = lo
        self.hi = hi
        self.lo_value = lo_value
        self.hi_value = hi_value
        self.reason = reason

    def render(self):
        return ("%s=%g must stay <= %s=%g: %s"
                % (self.lo, self.lo_value, self.hi, self.hi_value,
                   self.reason))


def _effective(name, env):
    value = get_float(name, env=env)
    if value is None and name in INHERITS:
        value = get_float(INHERITS[name], env=env)
    return value


def validate_env(env=None):
    """Evaluate the ordering lattice against ``env`` (default: the live
    process environment, overlaid on registry defaults). Returns the
    list of violations; empty means the deadline order holds."""
    violations = []
    for edge in ORDERING:
        lo_value = _effective(edge.lo, env)
        hi_value = _effective(edge.hi, env)
        if lo_value is None or hi_value is None:
            continue
        if edge.skip_if_zero and (lo_value <= 0 or hi_value <= 0):
            continue
        if lo_value > hi_value:
            violations.append(OrderingViolation(
                edge.lo, edge.hi, lo_value, hi_value, edge.reason))
    return violations


def validate_defaults():
    """The lattice evaluated over registry defaults alone — must always
    return [] (pinned by tests); a default drift that breaks the
    partial order is a registry bug."""
    return validate_env(env={})


# ---------------------------------------------------------------------------
# rendering (CLI + generated docs)
# ---------------------------------------------------------------------------

def by_subsystem():
    groups = {}
    for knob in KNOBS.values():
        groups.setdefault(knob.subsystem, []).append(knob)
    for knobs_ in groups.values():
        knobs_.sort(key=lambda k: k.name)
    return [(sub, groups[sub]) for sub in SUBSYSTEM_ORDER if sub in groups]


def to_json():
    return {
        "v": 1,
        "knobs": [KNOBS[name].to_dict() for name in sorted(KNOBS)],
        "prefixes": dict(PREFIXES),
        "ordering": [
            {"lo": e.lo, "hi": e.hi, "reason": e.reason,
             "skip_if_zero": e.skip_if_zero}
            for e in ORDERING
        ],
        "inherits": dict(INHERITS),
    }


def _default_str(knob):
    if knob.default is None:
        if knob.name in INHERITS:
            return "inherits " + INHERITS[knob.name]
        return "unset"
    if knob.ktype == "bool":
        return "on" if knob.default else "off"
    if isinstance(knob.default, float) and knob.default == int(knob.default):
        return str(int(knob.default))
    return str(knob.default)


def render_markdown():
    """The full registry as markdown — the exact content of
    docs/knobs.md (regenerated byte-identically, enforced by test)."""
    lines = [
        "# TPUFLOW_* knob registry",
        "",
        "Generated by `python -m metaflow_tpu knobs --markdown` from",
        "`metaflow_tpu/knobs.py` — do not edit by hand; regenerate and",
        "commit. `tests/test_contracts.py` fails when this file drifts",
        "from the registry.",
        "",
    ]
    for sub, knobs_ in by_subsystem():
        lines.append("## %s" % sub)
        lines.append("")
        lines.append("| knob | type | default | unit | description |")
        lines.append("|---|---|---|---|---|")
        for knob in knobs_:
            lines.append("| `%s` | %s | `%s` | %s | %s |" % (
                knob.name, knob.ktype, _default_str(knob),
                knob.unit or "—", knob.doc))
        lines.append("")
    lines.append("## dynamic prefixes")
    lines.append("")
    lines.append("| prefix | description |")
    lines.append("|---|---|")
    for prefix in sorted(PREFIXES):
        lines.append("| `%s*` | %s |" % (prefix, PREFIXES[prefix]))
    lines.append("")
    lines.append("## deadline ordering")
    lines.append("")
    lines.append("Each row pins `lo <= hi`; `check --deep` verifies the")
    lines.append("registry defaults and the pre-run gate verifies the live")
    lines.append("environment (warn by default, fatal under")
    lines.append("`TPUFLOW_STRICT_CHECK=1`).")
    lines.append("")
    lines.append("| lo | hi | why |")
    lines.append("|---|---|---|")
    for edge in ORDERING:
        suffix = " *(skipped when either side is 0)*" if edge.skip_if_zero \
            else ""
        lines.append("| `%s` | `%s` | %s%s |" % (
            edge.lo, edge.hi, edge.reason, suffix))
    lines.append("")
    return "\n".join(lines)


def render_json():
    return json.dumps(to_json(), indent=2, sort_keys=True) + "\n"
