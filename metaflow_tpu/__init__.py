"""metaflow_tpu: a TPU-native workflow framework with Metaflow's capabilities.

Public API (mirrors the reference's `from metaflow import ...` surface):

    from metaflow_tpu import FlowSpec, step, Parameter, JSONType, current
    from metaflow_tpu import retry, catch, timeout, resources, environment
    from metaflow_tpu import tpu, checkpoint, parallel
    from metaflow_tpu import Flow, Run, Step, Task, DataArtifact, namespace
    from metaflow_tpu import Runner
"""

from .flowspec import FlowSpec, step
from .parameters import Parameter, JSONType
from .current import current
from .exception import TpuFlowException, MetaflowException
from .unbounded_foreach import UnboundedForeachInput
from .decorators import make_step_decorator
from .plugins import STEP_DECORATORS

# generate user-facing decorator callables from the registry
retry = make_step_decorator(STEP_DECORATORS["retry"])
catch = make_step_decorator(STEP_DECORATORS["catch"])
timeout = make_step_decorator(STEP_DECORATORS["timeout"])
environment = make_step_decorator(STEP_DECORATORS["environment"])
resources = make_step_decorator(STEP_DECORATORS["resources"])
parallel = make_step_decorator(STEP_DECORATORS["parallel"])
tpu = make_step_decorator(STEP_DECORATORS["tpu"])
tpu_parallel = make_step_decorator(STEP_DECORATORS["tpu_parallel"])
checkpoint = make_step_decorator(STEP_DECORATORS["checkpoint"])

# client API (lazy-ish: import is cheap, no jax involved)
from .client import (  # noqa: E402
    Metaflow,
    Flow,
    Run,
    Step,
    Task,
    DataArtifact,
    namespace,
    get_namespace,
    default_namespace,
)

from .runner import Runner  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "FlowSpec",
    "step",
    "Parameter",
    "JSONType",
    "current",
    "TpuFlowException",
    "MetaflowException",
    "UnboundedForeachInput",
    "retry",
    "catch",
    "timeout",
    "environment",
    "resources",
    "parallel",
    "tpu",
    "tpu_parallel",
    "checkpoint",
    "Metaflow",
    "Flow",
    "Run",
    "Step",
    "Task",
    "DataArtifact",
    "namespace",
    "get_namespace",
    "default_namespace",
    "Runner",
]
