"""metaflow_tpu: a TPU-native workflow framework with Metaflow's capabilities.

Public API (mirrors the reference's `from metaflow import ...` surface):

    from metaflow_tpu import FlowSpec, step, Parameter, JSONType, current
    from metaflow_tpu import retry, catch, timeout, resources, environment
    from metaflow_tpu import tpu, checkpoint, parallel
    from metaflow_tpu import Flow, Run, Step, Task, DataArtifact, namespace
    from metaflow_tpu import Runner
"""

from .flowspec import FlowSpec, step
from .parameters import Parameter, JSONType
from .includefile import IncludedFile, IncludeFile
from .config_system import Config, ConfigValue, FlowMutator
from .current import current
from .exception import TpuFlowException, MetaflowException
from .unbounded_foreach import UnboundedForeachInput
from .decorators import make_step_decorator, make_flow_decorator
from .plugins import STEP_DECORATORS, FLOW_DECORATORS
from .user_decorators import USER_SKIP_STEP, user_step_decorator

# User-facing decorator callables (retry, catch, tpu, ...) resolve lazily
# through module __getattr__ below, straight from the live registries — so
# an extension that overrides a core decorator wins for BOTH
# `from metaflow_tpu import retry` and `--with retry`.

# client API (lazy-ish: import is cheap, no jax involved)
from .client import (  # noqa: E402
    Metaflow,
    Flow,
    Run,
    Step,
    Task,
    DataArtifact,
    namespace,
    get_namespace,
    default_namespace,
)

from .runner import Runner, Deployer  # noqa: E402

# cache keyed by (name, class) so wrapper identity is stable while the
# registry entry is unchanged, but removal/override invalidates naturally
_deco_cache = {}


def __getattr__(name):
    if name in ("NBRunner", "NBDeployer"):
        from . import runner as _runner

        value = getattr(_runner, name)
        globals()[name] = value
        return value
    # decorators contributed by extensions are importable like core ones:
    # `from metaflow_tpu import my_ext_decorator`
    if name in STEP_DECORATORS:
        key = (name, STEP_DECORATORS[name])
        if key not in _deco_cache:
            _deco_cache[key] = make_step_decorator(STEP_DECORATORS[name])
        return _deco_cache[key]
    if name in FLOW_DECORATORS:
        key = (name, FLOW_DECORATORS[name])
        if key not in _deco_cache:
            _deco_cache[key] = make_flow_decorator(FLOW_DECORATORS[name])
        return _deco_cache[key]
    raise AttributeError("module 'metaflow_tpu' has no attribute %r" % name)


# merge metaflow_tpu_extensions.* namespace packages into the registries
# (reference: metaflow/extension_support/plugins.py — extensions load at
# `import metaflow` time, before any CLI is built). Must run AFTER
# __getattr__ exists: extensions may `from metaflow_tpu import retry`.
from . import extension_support as _ext  # noqa: E402

_ext.load_extensions()

__version__ = "0.1.0"

__all__ = [
    "FlowSpec",
    "step",
    "Parameter",
    "JSONType",
    "IncludeFile",
    "IncludedFile",
    "Config",
    "ConfigValue",
    "FlowMutator",
    "current",
    "TpuFlowException",
    "MetaflowException",
    "UnboundedForeachInput",
    "retry",
    "catch",
    "timeout",
    "environment",
    "resources",
    "parallel",
    "tpu",
    "tpu_parallel",
    "checkpoint",
    "secrets",
    "card",
    "pypi",
    "conda",
    "uv",
    "project",
    "schedule",
    "trigger",
    "trigger_on_finish",
    "exit_hook",
    "Metaflow",
    "Flow",
    "Run",
    "Step",
    "Task",
    "DataArtifact",
    "namespace",
    "get_namespace",
    "default_namespace",
    "Runner",
    "Deployer",
    "user_step_decorator",
    "USER_SKIP_STEP",
]
