"""metaflow_tpu: a TPU-native workflow framework with Metaflow's capabilities.

Public API (mirrors the reference's `from metaflow import ...` surface):

    from metaflow_tpu import FlowSpec, step, Parameter, JSONType, current
    from metaflow_tpu import retry, catch, timeout, resources, environment
    from metaflow_tpu import tpu, checkpoint, parallel
    from metaflow_tpu import Flow, Run, Step, Task, DataArtifact, namespace
    from metaflow_tpu import Runner
"""

from .flowspec import FlowSpec, step
from .parameters import Parameter, JSONType
from .includefile import IncludeFile
from .config_system import Config, ConfigValue, FlowMutator
from .current import current
from .exception import TpuFlowException, MetaflowException
from .unbounded_foreach import UnboundedForeachInput
from .decorators import make_step_decorator, make_flow_decorator
from .plugins import STEP_DECORATORS, FLOW_DECORATORS

# generate user-facing decorator callables from the registry
retry = make_step_decorator(STEP_DECORATORS["retry"])
catch = make_step_decorator(STEP_DECORATORS["catch"])
timeout = make_step_decorator(STEP_DECORATORS["timeout"])
environment = make_step_decorator(STEP_DECORATORS["environment"])
resources = make_step_decorator(STEP_DECORATORS["resources"])
parallel = make_step_decorator(STEP_DECORATORS["parallel"])
tpu = make_step_decorator(STEP_DECORATORS["tpu"])
tpu_parallel = make_step_decorator(STEP_DECORATORS["tpu_parallel"])
checkpoint = make_step_decorator(STEP_DECORATORS["checkpoint"])
secrets = make_step_decorator(STEP_DECORATORS["secrets"])
card = make_step_decorator(STEP_DECORATORS["card"])
pypi = make_step_decorator(STEP_DECORATORS["pypi"])
conda = make_step_decorator(STEP_DECORATORS["conda"])
uv = make_step_decorator(STEP_DECORATORS["uv"])

project = make_flow_decorator(FLOW_DECORATORS["project"])
schedule = make_flow_decorator(FLOW_DECORATORS["schedule"])
trigger = make_flow_decorator(FLOW_DECORATORS["trigger"])
trigger_on_finish = make_flow_decorator(FLOW_DECORATORS["trigger_on_finish"])
exit_hook = make_flow_decorator(FLOW_DECORATORS["exit_hook"])

# client API (lazy-ish: import is cheap, no jax involved)
from .client import (  # noqa: E402
    Metaflow,
    Flow,
    Run,
    Step,
    Task,
    DataArtifact,
    namespace,
    get_namespace,
    default_namespace,
)

from .runner import Runner, Deployer  # noqa: E402


def __getattr__(name):
    if name == "NBRunner":
        from .runner.nbrun import NBRunner

        return NBRunner
    raise AttributeError("module 'metaflow_tpu' has no attribute %r" % name)

__version__ = "0.1.0"

__all__ = [
    "FlowSpec",
    "step",
    "Parameter",
    "JSONType",
    "IncludeFile",
    "Config",
    "ConfigValue",
    "FlowMutator",
    "current",
    "TpuFlowException",
    "MetaflowException",
    "UnboundedForeachInput",
    "retry",
    "catch",
    "timeout",
    "environment",
    "resources",
    "parallel",
    "tpu",
    "tpu_parallel",
    "checkpoint",
    "secrets",
    "card",
    "pypi",
    "conda",
    "uv",
    "project",
    "schedule",
    "trigger",
    "trigger_on_finish",
    "exit_hook",
    "Metaflow",
    "Flow",
    "Run",
    "Step",
    "Task",
    "DataArtifact",
    "namespace",
    "get_namespace",
    "default_namespace",
    "Runner",
    "Deployer",
]
