"""Async training checkpoints through the content-addressed store.

The @checkpoint decorator's orbax path (plugins/tpu/checkpoint_decorator)
is synchronous: the train loop stalls for the whole serialize+upload
wall-clock at every checkpoint step. This manager is the overlapped
alternative for pipeline/SPMD training (the tail-latency lesson from
arxiv 2011.03641): `save(state, step)` blocks only for the device→host
snapshot — an eager `copy_to_host_async` fan-out followed by the gather —
and hands serialization + CAS upload + manifest write to a background
thread, so checkpoint upload overlaps the train steps that follow.

Contract (see docs/persist_pipeline.md):

  - `save(state, step)` returns once the snapshot is on the HOST. The
    caller may immediately donate/overwrite the device buffers (the jit
    train step's donate_argnums) — the background thread only touches
    host numpy.
  - One save is in flight at a time: `save` barriers on the previous
    background persist first (so checkpoint bandwidth can never fall
    behind by more than one snapshot's worth of RAM).
  - A background failure is NEVER lost: it re-raises at the next
    `save()`, `wait()` or `done()` call.
  - Crash consistency: the per-step manifest is written only AFTER the
    snapshot blob is fully in the CAS. A crash mid-upload leaves no
    manifest, so `restore()` sees the previous complete checkpoint; a
    torn checkpoint is unobservable.
  - `restore(like=live_state)` re-places restored leaves onto the live
    tree's shardings via train_step.reshard_like — the resume recipe for
    a fresh process whose mesh differs from the saver's.
"""

import collections
import json
import threading
import time

import numpy as np

from .. import tracing
from ..datastore import serializers

Checkpoint = collections.namedtuple("Checkpoint", ["state", "step", "extra"])


def _sanitize_journal(kind, name, key=None):
    """Journal a shared-write signature into the collective sanitizer
    (spmd/sanitizer.py) when TPUFLOW_SANITIZE=1. Imported lazily so this
    module stays importable without pulling the spmd package (jax) in."""
    from .. import knobs

    if not knobs.get_bool("TPUFLOW_SANITIZE"):
        return
    from ..spmd import sanitizer

    sanitizer.journal(kind, name, key=key)


class AsyncCheckpointManager(object):
    """Checkpoints pytree train states into a flow datastore's CAS.

    flow_datastore: a datastore.FlowDataStore (any storage backend).
    name: logical stream name — one manager per trainer; manifests live
          under <flow>/_checkpoints/<name>/step_<n>.json.
    keep: retain only the newest N manifests (None = keep all). Blobs
          stay in the CAS (content-addressed, shared, gc'd elsewhere).
    """

    def __init__(self, flow_datastore, name="default", keep=None):
        self._storage = flow_datastore.storage
        self._ca = flow_datastore.ca_store
        self._prefix = self._storage.path_join(
            flow_datastore.flow_name, "_checkpoints", name
        )
        self._keep = keep
        self._thread = None
        self._error = None
        self._lock = threading.Lock()
        # the most recent restore()'s Checkpoint — callers that went
        # through make_trainer(checkpoint=...) read the resumed step and
        # the data-iterator stamp here without re-downloading the state
        self.last_restored = None

    # ---------- write path ----------

    def save(self, state, step, extra=None):
        """Snapshot `state` (a pytree of arrays/scalars) for logical
        `step` and return as soon as the snapshot is host-resident.
        `extra` (JSON-able, e.g. the data iterator's resume stamp) rides
        in the manifest. Serialization + upload happen in the background;
        errors surface at the next save()/wait()/done()."""
        _sanitize_journal("write", "checkpoint.save", key=int(step))
        self.wait()  # barrier on the previous in-flight persist
        with tracing.span("checkpoint.snapshot", {"step": int(step)}):
            host = _snapshot_to_host(state)
        t = threading.Thread(
            target=self._persist, args=(host, int(step), extra),
            name="ckpt-persist", daemon=True,
        )
        with self._lock:
            self._thread = t
        t.start()

    def _persist(self, host_state, step, extra):
        try:
            with tracing.span("checkpoint.persist", {"step": step}):
                payload, tag = serializers.serialize(host_state)
                # cacheable=False: a superseded snapshot in the shared
                # LRU blob cache would only evict real artifact blobs
                [(_uri, key)] = self._ca.save_blobs([payload],
                                                    cacheable=False)
                manifest = {
                    "step": step,
                    "key": key,
                    "type_tag": tag,
                    "size": len(payload),
                    "time": time.time(),
                }
                if extra is not None:
                    manifest["extra"] = extra
                # manifest LAST: its existence asserts the blob is whole
                self._storage.save_bytes(
                    [(self._manifest_path(step),
                      json.dumps(manifest).encode("utf-8"))],
                    overwrite=True,
                )
                self._prune(keep_step=step)
        except BaseException as ex:
            with self._lock:
                self._error = ex

    def wait(self):
        """Block until the in-flight persist (if any) completes; re-raise
        its error. After wait() returns, the last save is durable."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()
            with self._lock:
                if self._thread is t:
                    self._thread = None
        self._raise_pending()

    def done(self):
        """Non-blocking: True when no persist is in flight. Re-raises a
        background failure instead of letting it rot."""
        self._raise_pending()
        with self._lock:
            t = self._thread
        if t is None:
            return True
        if t.is_alive():
            return False
        t.join()
        with self._lock:
            if self._thread is t:
                self._thread = None
        self._raise_pending()
        return True

    def _raise_pending(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _prune(self, keep_step):
        if not self._keep:
            return
        steps = self.steps()
        # never prune the step just written, whatever the listing says
        stale = [s for s in steps if s != keep_step][: max(
            0, len(steps) - self._keep)]
        if stale:
            self._storage.delete([self._manifest_path(s) for s in stale])

    # ---------- read path ----------

    def _manifest_path(self, step):
        return self._storage.path_join(self._prefix, "step_%d.json" % step)

    def steps(self):
        """Sorted steps with COMPLETE checkpoints (manifest present)."""
        out = []
        for path, is_file in self._storage.list_content([self._prefix]):
            name = self._storage.basename(path)
            if (is_file and name.startswith("step_")
                    and name.endswith(".json")
                    and name[5:-5].isdigit()):
                out.append(int(name[5:-5]))
        return sorted(out)

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step=None, like=None):
        """Load checkpoint `step` (default: latest complete one) as a
        Checkpoint(state, step, extra), or None when none exist. With
        `like` (a LIVE state tree of the same structure), restored leaves
        are re-placed onto its shardings via reshard_like."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        manifest = self._load_manifest(step)
        if manifest is None:
            return None
        with tracing.span("checkpoint.restore", {"step": step}):
            state = None
            # cacheable=False mirrors the save side: a one-shot multi-GB
            # snapshot must not churn the shared artifact blob cache
            for _key, blob in self._ca.load_blobs([manifest["key"]],
                                                  cacheable=False):
                state = serializers.deserialize(blob, manifest["type_tag"])
            if like is not None:
                from .train_step import reshard_like

                self._check_like(state, like, step)
                state = reshard_like(state, like)
        ck = Checkpoint(state, manifest["step"], manifest.get("extra"))
        self.last_restored = ck
        return ck

    @staticmethod
    def _check_like(state, like, step):
        """Fail restore-onto-live-shardings loudly when the trees disagree.

        reshard_like would die inside jax.tree.map with an opaque
        structure error; the overwhelmingly common cause is a checkpoint
        saved under a DIFFERENT optimizer than the trainer now uses
        (opt_state trees differ), so name that. Shape mismatches (a
        changed model config) surface from device_put with the leaf
        named, which is already actionable."""
        import jax

        saved = jax.tree.structure(state)
        live = jax.tree.structure(like)
        if saved != live:
            raise ValueError(
                "checkpoint step %s does not match the live state tree —\n"
                "  saved: %s\n  live:  %s\n"
                "most likely the checkpoint was saved under a different "
                "optimizer (or model) than this trainer was built with; "
                "rebuild the trainer with the original optimizer, or "
                "start a fresh run for the new one. (DP-size and ZeRO "
                "on/off changes are fine — those reshard, they don't "
                "change the tree.)" % (step, saved, live))

    def _load_manifest(self, step):
        with self._storage.load_bytes([self._manifest_path(step)]) as loaded:
            for _path, local, _meta in loaded:
                if local is None:
                    return None
                with open(local, "rb") as f:
                    return json.loads(f.read().decode("utf-8"))
        return None


def _snapshot_to_host(tree):
    """Host-resident numpy snapshot of a pytree: issue EVERY device
    array's D2H copy first (transfers queue back-to-back and overlap),
    then gather. Total wall-clock ≈ the single largest transfer, not the
    sum — and the result is donation-safe: no live device buffers."""
    serializers.prefetch_to_host(tree)
    return _gather_to_host(tree)


MAX_TREE_DEPTH = 64


def _gather_to_host(obj, depth=0):
    """Like serializers._pickle_safe but SNAPSHOTTING: device arrays come
    to host, host numpy arrays are COPIED (the caller mutates/donates its
    state right after save() returns — the background thread must never
    alias it), and container types — optax namedtuples, dict subclasses —
    are preserved so the restored tree's structure matches the live one."""
    if depth > MAX_TREE_DEPTH:
        # returning the sub-tree uncopied would silently break save()'s
        # donation-safety contract (the background thread would read
        # buffers the caller is about to donate/mutate) — fail in the
        # caller's thread instead, where it is immediately visible
        raise ValueError(
            "checkpoint state nests deeper than %d levels — refusing to "
            "snapshot (deeper leaves would alias live buffers)"
            % MAX_TREE_DEPTH)
    if serializers._is_jax_array(obj):
        return serializers._to_host(obj)
    if isinstance(obj, np.ndarray):
        return obj.copy()
    if isinstance(obj, dict):
        vals = {k: _gather_to_host(v, depth + 1) for k, v in obj.items()}
        try:
            clone = obj.copy()  # preserves OrderedDict/defaultdict
            clone.update(vals)
            return clone
        except Exception:
            return vals
    if isinstance(obj, tuple):
        vals = [_gather_to_host(v, depth + 1) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple (optax states)
            try:
                return type(obj)._make(vals)
            except Exception:
                return tuple(vals)
        try:
            return type(obj)(vals)
        except Exception:
            return tuple(vals)
    if isinstance(obj, list):
        return [_gather_to_host(v, depth + 1) for v in obj]
    return obj
