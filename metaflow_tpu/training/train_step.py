"""Training loop building blocks: sharded init, jitted train step.

The pjit/GSPMD path the reference delegates to user frameworks (SURVEY.md
§5.7): params and optimizer state are sharded via the model's logical axes +
the mesh's rule table; the train step donates its state buffers so the update
is in-place in HBM, and XLA inserts the gradient psum/reduce-scatter over the
data/fsdp axes.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..spmd import sanitizer
from ..spmd import sharding as shd


def _lr_schedule(lr, warmup_steps, total_steps):
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1), end_value=lr * 0.1,
    )


def default_optimizer(lr=3e-4, weight_decay=0.1, clip_norm=1.0,
                      warmup_steps=100, total_steps=10_000, b1=0.9, b2=0.95,
                      mu_dtype=jnp.float32):
    schedule = _lr_schedule(lr, warmup_steps, total_steps)
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def memory_efficient_optimizer(lr=3e-4, weight_decay=0.1, clip_norm=1.0,
                               warmup_steps=100, total_steps=10_000, b1=0.9):
    """Adafactor-style state: bf16 first moment + factored second moment
    (~2 bytes/param of optimizer state vs adamw's 8). On a single v5e chip
    this is what unlocks batch >16 for the ~1B bench config — optimizer
    state stops competing with activations for HBM.

    Weight decay matches default_optimizer's decoupled form (decay scaled by
    the scheduled lr, adamw-style) so switching optimizers changes memory,
    not regularization."""
    schedule = _lr_schedule(lr, warmup_steps, total_steps)
    adafactor = optax.adafactor(
        learning_rate=schedule,
        multiply_by_parameter_scale=False,
        clipping_threshold=None,
        momentum=b1,
        dtype_momentum=jnp.bfloat16,
        weight_decay_rate=None,
        eps=1e-30,
        factored=True,
    )

    # decoupled decay: adafactor's update already carries its -lr(t) sign,
    # so add -lr(t)*wd*w on top (same step-count the schedule sees)
    def init_fn(params):
        return {"inner": adafactor.init(params),
                "count": jnp.zeros((), jnp.int32)}

    def update_fn(updates, state, params=None):
        new_updates, inner = adafactor.update(updates, state["inner"], params)
        if weight_decay:
            lr = schedule(state["count"])
            new_updates = jax.tree.map(
                lambda u, p: u - lr * weight_decay * p, new_updates, params
            )
        return new_updates, {"inner": inner, "count": state["count"] + 1}

    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.GradientTransformation(init_fn, update_fn),
    )


def reshard_like(tree, like):
    """Re-place a checkpoint-restored pytree onto the shardings of a
    LIVE state tree (same structure) — the resume recipe for a fresh
    process.

    orbax restores arrays with the shardings they were SAVED with, which
    a retry/resume process cannot use directly. Mesh-sharded leaves are
    device_put onto their NamedSharding; leaves whose live counterpart
    sits on a single device (optimizer step counters and other scalars
    that jit left unconstrained) are returned as HOST numpy instead —
    committing them to device 0 via device_put would poison a
    multi-device jit with 'incompatible devices', while an uncommitted
    host array lets jit place them exactly as it placed the originals.
    """
    from jax.sharding import NamedSharding

    def _place(restored, live):
        host = np.asarray(jax.device_get(restored))
        sharding = getattr(live, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(host, sharding)
        return host

    return jax.tree.map(_place, tree, like)


def make_train_state(rng, cfg, mesh, model, optimizer=None, rules=None):
    """Sharded init: params + optimizer state placed per the rule table.

    model: module exposing init_params(rng, cfg) and logical_axes(cfg).
    Returns (state dict, shardings dict).
    """
    optimizer = optimizer or default_optimizer()
    rules = rules or shd.rules_for_mesh(mesh)
    log_axes = model.logical_axes(cfg)
    param_shardings = shd.tree_shardings(log_axes, mesh, rules)

    def init():
        params = model.init_params(rng, cfg)
        return params

    with mesh:
        params = jax.jit(init, out_shardings=param_shardings)()
        opt_state = jax.jit(
            optimizer.init,
            # optimizer state mirrors the param tree; let GSPMD propagate
        )(params)
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    shardings = {
        "params": param_shardings,
        "opt_state": jax.tree.map(lambda x: x.sharding, opt_state),
        "step": jax.tree.map(lambda x: x.sharding, state["step"]),
    }
    return state, shardings


def make_train_step(cfg, mesh, model, optimizer=None, loss_fn=None):
    """Build the jitted, donated train step: (state, batch) → (state, metrics).

    `mesh` is accepted for signature symmetry with make_train_state; the
    step itself is mesh-agnostic (shardings propagate from the state)."""
    optimizer = optimizer or default_optimizer()
    loss_fn = loss_fn or model.loss_fn

    import inspect

    loss_takes_mesh = "mesh" in inspect.signature(loss_fn).parameters

    def step(state, batch):
        def compute_loss(params):
            if loss_takes_mesh:
                return loss_fn(params, batch, cfg, mesh=mesh)
            return loss_fn(params, batch, cfg)

        loss, grads = jax.value_and_grad(compute_loss)(state["params"])
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        grad_norm = optax.global_norm(grads)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return jax.jit(step, donate_argnums=(0,))


def make_trainer(rng, cfg, mesh, model, optimizer=None, rules=None,
                 loss_fn=None, checkpoint=None, telemetry=None):
    """One-stop builder: returns (state, train_step_fn, shardings) with a
    SINGLE shared optimizer — prefer this over calling make_train_state and
    make_train_step separately (mismatched optimizers give silently wrong or
    crashing updates).

    telemetry: truthy wraps the returned step with
    training.metrics.instrument_train_step so every call emits per-step
    wall time (+ tokens/sec and MFU when the kwargs below are given)
    through the run's flight recorder. Pass True for defaults or a dict of
    instrument_train_step kwargs, e.g.
    ``telemetry={"tokens_per_step": batch * seq, "flops_per_step": ...}``.

    checkpoint: an AsyncCheckpointManager (training/checkpoint.py). When
    it holds a complete checkpoint, the freshly-initialized state is
    replaced by the restored one re-placed onto the live shardings
    (reshard_like) — so a preempted/retried run resumes instead of
    restarting, and subsequent `checkpoint.save(state, step)` calls
    overlap their upload with the train steps that follow. The resumed
    step and the saved `extra` (e.g. the data iterator's resume stamp)
    are available afterwards as `checkpoint.last_restored` — without
    them a resumed run would silently restart its data stream."""
    optimizer = optimizer or default_optimizer()
    # compile-shaping state: every rank must build the SAME mesh/program
    # (analysis/divergence.py's gang-divergent-compile class, verified at
    # runtime by the sanitizer barrier)
    sanitizer.journal("compile", "make_trainer", axes=mesh.axis_names,
                      key=str(dict(mesh.shape)))
    state, shardings = make_train_state(
        rng, cfg, mesh, model, optimizer=optimizer, rules=rules
    )
    step = make_train_step(cfg, mesh, model, optimizer=optimizer,
                           loss_fn=loss_fn)
    if checkpoint is not None:
        restored = checkpoint.restore(like=state)
        if restored is not None:
            state = restored.state
    if telemetry:
        from .metrics import instrument_train_step

        kwargs = telemetry if isinstance(telemetry, dict) else {}
        step = instrument_train_step(step, **kwargs)
    # sanitizer wraps OUTERMOST: the instrumentation must keep seeing the
    # raw jitted step (its jit-cache probe and cost-analysis .lower() die
    # on a plain wrapper); the .telemetry handle stays reachable
    wrapped = sanitizer.wrap_step(step)
    if wrapped is not step and hasattr(step, "telemetry"):
        wrapped.telemetry = step.telemetry
    step = wrapped
    return state, step, shardings


def make_eval_step(cfg, mesh, model, loss_fn=None):
    import inspect

    loss_fn = loss_fn or model.loss_fn
    loss_takes_mesh = "mesh" in inspect.signature(loss_fn).parameters

    def step(params, batch):
        if loss_takes_mesh:
            return loss_fn(params, batch, cfg, mesh=mesh)
        return loss_fn(params, batch, cfg)

    return jax.jit(step)


def shard_batch(batch, mesh):
    """Place a host batch onto the mesh: batch dim over data axes; the
    sequence dim over the 'sequence' axis when present AND divisible (a
    [B, S+1] token array stays batch-sharded; GSPMD reshards the sliced
    [B, S] inputs inside the step)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..spmd.mesh import data_axes

    sanitizer.journal("collective", "shard_batch", axes=mesh.axis_names,
                      shape=batch)
    axes = data_axes(mesh)
    batch_spec = axes if axes else None
    seq_size = mesh.shape.get("sequence", 1)

    def place(x):
        if (
            seq_size > 1
            and getattr(x, "ndim", 0) >= 2
            and x.shape[1] % seq_size == 0
        ):
            spec = PartitionSpec(batch_spec, "sequence")
        else:
            spec = PartitionSpec(batch_spec)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, batch)
