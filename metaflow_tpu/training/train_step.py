"""Training loop building blocks: sharded init, jitted train step.

The pjit/GSPMD path the reference delegates to user frameworks (SURVEY.md
§5.7): params and optimizer state are sharded via the model's logical axes +
the mesh's rule table; the train step donates its state buffers so the update
is in-place in HBM, and XLA inserts the gradient psum/reduce-scatter over the
data/fsdp axes.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..spmd import sanitizer
from ..spmd import sharding as shd


def _lr_schedule(lr, warmup_steps, total_steps):
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1), end_value=lr * 0.1,
    )


def default_optimizer(lr=3e-4, weight_decay=0.1, clip_norm=1.0,
                      warmup_steps=100, total_steps=10_000, b1=0.9, b2=0.95,
                      mu_dtype=jnp.float32):
    schedule = _lr_schedule(lr, warmup_steps, total_steps)
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def memory_efficient_optimizer(lr=3e-4, weight_decay=0.1, clip_norm=1.0,
                               warmup_steps=100, total_steps=10_000, b1=0.9):
    """Adafactor-style state: bf16 first moment + factored second moment
    (~2 bytes/param of optimizer state vs adamw's 8). On a single v5e chip
    this is what unlocks batch >16 for the ~1B bench config — optimizer
    state stops competing with activations for HBM.

    Weight decay matches default_optimizer's decoupled form (decay scaled by
    the scheduled lr, adamw-style) so switching optimizers changes memory,
    not regularization."""
    schedule = _lr_schedule(lr, warmup_steps, total_steps)
    adafactor = optax.adafactor(
        learning_rate=schedule,
        multiply_by_parameter_scale=False,
        clipping_threshold=None,
        momentum=b1,
        dtype_momentum=jnp.bfloat16,
        weight_decay_rate=None,
        eps=1e-30,
        factored=True,
    )

    # decoupled decay: adafactor's update already carries its -lr(t) sign,
    # so add -lr(t)*wd*w on top (same step-count the schedule sees)
    def init_fn(params):
        return {"inner": adafactor.init(params),
                "count": jnp.zeros((), jnp.int32)}

    def update_fn(updates, state, params=None):
        new_updates, inner = adafactor.update(updates, state["inner"], params)
        if weight_decay:
            lr = schedule(state["count"])
            new_updates = jax.tree.map(
                lambda u, p: u - lr * weight_decay * p, new_updates, params
            )
        return new_updates, {"inner": inner, "count": state["count"] + 1}

    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.GradientTransformation(init_fn, update_fn),
    )


def reshard_like(tree, like):
    """Re-place a checkpoint-restored pytree onto the shardings of a
    LIVE state tree (same structure) — the resume recipe for a fresh
    process.

    orbax restores arrays with the shardings they were SAVED with, which
    a retry/resume process cannot use directly. Mesh-sharded leaves are
    device_put onto their NamedSharding; leaves whose live counterpart
    sits on a single device (optimizer step counters and other scalars
    that jit left unconstrained) are returned as HOST numpy instead —
    committing them to device 0 via device_put would poison a
    multi-device jit with 'incompatible devices', while an uncommitted
    host array lets jit place them exactly as it placed the originals.
    """
    from jax.sharding import NamedSharding

    def _place(restored, live):
        host = np.asarray(jax.device_get(restored))
        sharding = getattr(live, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(host, sharding)
        return host

    return jax.tree.map(_place, tree, like)


def check_opt_state(optimizer, state):
    """Guard: would `optimizer.init(state['params'])` produce this opt state?

    Using one optimizer to build the state and a different one in the step
    is silently wrong when the trees happen to line up (e.g. two adamw
    chains with different hyperparams) and a deep GSPMD crash when they do
    not. The check compares the abstract tree `optimizer.init` would build
    against the live/restored `state['opt_state']` — structure, shapes and
    dtypes — and raises a ValueError that names the mismatch. Costs one
    eval_shape (no compile, no device work)."""
    expect = jax.eval_shape(optimizer.init, state["params"])
    got = state["opt_state"]
    want_def = jax.tree.structure(expect)
    got_def = jax.tree.structure(got)
    if want_def != got_def:
        raise ValueError(
            "optimizer/opt_state mismatch: optimizer.init(params) would "
            "build tree\n  %s\nbut state['opt_state'] has tree\n  %s\n"
            "make_train_state and make_train_step must share ONE optimizer "
            "(use make_trainer, which enforces this); a restored checkpoint "
            "must have been saved with the same optimizer the trainer now "
            "uses." % (want_def, got_def))
    for path_want, path_got in zip(
            jax.tree_util.tree_leaves_with_path(expect),
            jax.tree_util.tree_leaves_with_path(got)):
        path, want = path_want
        _, have = path_got
        want_shape = tuple(want.shape)
        have_shape = tuple(getattr(have, "shape", ()))
        have_dtype = getattr(have, "dtype", None)
        if want_shape != have_shape or (
                have_dtype is not None and want.dtype != have_dtype):
            raise ValueError(
                "optimizer/opt_state mismatch at opt_state%s: optimizer."
                "init(params) would build %s%s, state has %s%s — same "
                "optimizer family but different hyperparameters (mu_dtype, "
                "factoring, ...)?" % (
                    jax.tree_util.keystr(path), want.dtype, want_shape,
                    have_dtype, have_shape))


def make_train_state(rng, cfg, mesh, model, optimizer=None, rules=None,
                     zero=None):
    """Sharded init: params + optimizer state placed per the rule table.

    model: module exposing init_params(rng, cfg) and logical_axes(cfg).
    zero: ZeRO-style sharded update (spmd/sharding.py) — when enabled, the
    optimizer state is re-placed 1/N-sharded over the DP axis after init,
    so each replica holds (and updates) only its shard. None resolves from
    the TPUFLOW_ZERO env knob; a mesh without a data axis forces it off.
    Returns (state dict, shardings dict).
    """
    optimizer = optimizer or default_optimizer()
    rules = rules or shd.rules_for_mesh(mesh)
    log_axes = model.logical_axes(cfg)
    param_shardings = shd.tree_shardings(log_axes, mesh, rules)
    use_zero = shd.zero_enabled(mesh, zero)

    def init():
        params = model.init_params(rng, cfg)
        return params

    with mesh:
        params = jax.jit(init, out_shardings=param_shardings)()
        opt_state = jax.jit(
            optimizer.init,
            # optimizer state mirrors the param tree; let GSPMD propagate
        )(params)
        if use_zero:
            # re-spec each live leaf over the DP axis (base = the sharding
            # GSPMD propagated, so model-parallel axes are kept) and
            # re-place. device_put, not a second compile: the replicated
            # copy is freed as each leaf lands, so peak memory never
            # exceeds the non-zero path's.
            opt_state = jax.device_put(
                opt_state, shd.zero_tree_shardings(opt_state, mesh))
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    shardings = {
        "params": param_shardings,
        "opt_state": jax.tree.map(lambda x: x.sharding, opt_state),
        "step": jax.tree.map(lambda x: x.sharding, state["step"]),
    }
    return state, shardings


def make_train_step(cfg, mesh, model, optimizer=None, loss_fn=None,
                    zero=None, rules=None, opt_specs=None,
                    timed_update=False):
    """Build the jitted, donated train step: (state, batch) → (state, metrics).

    WARNING: `optimizer` must be the SAME GradientTransformation the state
    was built with — a mismatch gives silently wrong updates when the state
    trees happen to line up. Use make_trainer (which shares one optimizer
    and runs check_opt_state) unless you have a reason not to.

    zero: ZeRO-style weight-update sharding. The replicated-DP update is
    rewritten as  grad reduce-scatter → 1/N-sharded optimizer update →
    param all-gather, expressed purely as sharding constraints (GSPMD
    inserts the collectives; semantics are unchanged). The all-gathered
    params feed only the RETURNED state — nothing later in the step
    consumes them — so XLA's latency-hiding scheduler can overlap the
    gather with the loss/grad-norm tail and the next step's dispatch.
    None resolves from TPUFLOW_ZERO; meshes without a data axis force off.

    opt_specs: optional pytree of PartitionSpecs for the (zero-sharded)
    optimizer state, matching make_train_state's placement. When omitted,
    the specs are re-derived at trace time from shapes with a replicated
    base — identical on pure-DP meshes; pass the live specs on mixed
    meshes to avoid a per-step reshard of model-parallel state.

    timed_update: split the step into two jits (grad, then donated update)
    with dispatch fences so the wrapper can report `last_update_ms` — the
    wall time of the optimizer update + collectives — per call. This is a
    DIAGNOSTIC mode: the fences serialize work the fused step overlaps, so
    never benchmark with it on. training/metrics.py picks the attribute up
    into the per-step telemetry record as `optimizer_update_ms`.

    `mesh` shapes the zero schedule's constraints; with zero off the step
    itself is mesh-agnostic (shardings propagate from the state)."""
    optimizer = optimizer or default_optimizer()
    loss_fn = loss_fn or model.loss_fn
    use_zero = shd.zero_enabled(mesh, zero)

    import inspect

    loss_takes_mesh = "mesh" in inspect.signature(loss_fn).parameters

    def compute_loss(params, batch):
        if loss_takes_mesh:
            return loss_fn(params, batch, cfg, mesh=mesh)
        return loss_fn(params, batch, cfg)

    if use_zero:
        zero_axis = shd.zero_update_axis(mesh)
        base_specs = shd.tree_specs(
            model.logical_axes(cfg), rules or shd.rules_for_mesh(mesh))

    def apply_update(params, grads, opt_state):
        """(full grads, state) -> (new params, new opt state, grad norm).

        Zero path: constraining the summed grads onto DP-sharded specs
        turns the grad all-reduce into a reduce-scatter; the optimizer
        then runs on 1/N-sized shards (params sliced locally — no
        collective, each replica already holds the full value); finally
        constraining the updated params back to their base (replicated-
        over-DP) specs emits the all-gather. grad_norm is computed from
        the scattered shards — same value, 1/N the reduction input."""
        if not use_zero:
            updates, new_opt = optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_opt,
                    optax.global_norm(grads))
        specs = jax.tree.map(
            lambda g, sp: shd.zero_spec(sp, g.shape, mesh, axis=zero_axis),
            grads, base_specs)
        ospecs = opt_specs
        if ospecs is None:
            ospecs = jax.tree.map(
                lambda o: shd.zero_spec(
                    jax.sharding.PartitionSpec(), o.shape, mesh,
                    axis=zero_axis),
                opt_state)
        grads = shd.zero_constrain(grads, mesh, specs, "reduce_scatter")
        params_sh = shd.zero_constrain(params, mesh, specs, "shard")
        opt_state = jax.tree.map(
            lambda o, sp: jax.lax.with_sharding_constraint(
                o, jax.sharding.NamedSharding(mesh, sp)),
            opt_state, ospecs)
        updates, new_opt = optimizer.update(grads, opt_state, params_sh)
        updates = jax.tree.map(
            lambda u, sp: jax.lax.with_sharding_constraint(
                u, jax.sharding.NamedSharding(mesh, sp)),
            updates, specs)
        new_params = optax.apply_updates(params_sh, updates)
        new_params = shd.zero_constrain(
            new_params, mesh, base_specs, "all_gather")
        new_opt = jax.tree.map(
            lambda o, sp: jax.lax.with_sharding_constraint(
                o, jax.sharding.NamedSharding(mesh, sp)),
            new_opt, ospecs)
        return new_params, new_opt, optax.global_norm(grads)

    if not timed_update:
        def step(state, batch):
            loss, grads = jax.value_and_grad(
                lambda p: compute_loss(p, batch))(state["params"])
            params, opt_state, grad_norm = apply_update(
                state["params"], grads, state["opt_state"])
            new_state = {
                "params": params,
                "opt_state": opt_state,
                "step": state["step"] + 1,
            }
            return new_state, {"loss": loss, "grad_norm": grad_norm}

        return jax.jit(step, donate_argnums=(0,))

    # diagnostic split: measure the update (optimizer math + zero
    # collectives) separately from the fwd/bwd. Two compiles, two fences.
    grad_fn = jax.jit(lambda params, batch: jax.value_and_grad(
        lambda p: compute_loss(p, batch))(params))

    def update(state, grads):
        params, opt_state, grad_norm = apply_update(
            state["params"], grads, state["opt_state"])
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, grad_norm

    update_fn = jax.jit(update, donate_argnums=(0, 1))

    def step(state, batch):
        import time

        loss, grads = grad_fn(state["params"], batch)
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        new_state, grad_norm = update_fn(state, grads)
        jax.block_until_ready(new_state["params"])
        step.last_update_ms = (time.perf_counter() - t0) * 1e3
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    step.last_update_ms = None
    return step


def make_trainer(rng, cfg, mesh, model, optimizer=None, rules=None,
                 loss_fn=None, checkpoint=None, telemetry=None, zero=None,
                 timed_update=False):
    """One-stop builder: returns (state, train_step_fn, shardings) with a
    SINGLE shared optimizer — prefer this over calling make_train_state and
    make_train_step separately: a mismatched optimizer between the two gives
    SILENTLY WRONG updates whenever the opt-state trees happen to line up
    (same optax family, different hyperparameters) and an opaque GSPMD
    crash when they don't. make_trainer shares one optimizer and runs
    check_opt_state after build/restore, so a stale checkpoint saved under
    a different optimizer fails loudly with the mismatch named.

    zero: ZeRO-style cross-replica weight-update sharding (see
    make_train_step / docs/training.md). None resolves from the
    TPUFLOW_ZERO env knob; forced off on meshes without a data axis.

    timed_update: diagnostic split-step mode reporting per-call
    `optimizer_update_ms` through telemetry (see make_train_step).

    telemetry: truthy wraps the returned step with
    training.metrics.instrument_train_step so every call emits per-step
    wall time (+ tokens/sec and MFU when the kwargs below are given)
    through the run's flight recorder. Pass True for defaults or a dict of
    instrument_train_step kwargs, e.g.
    ``telemetry={"tokens_per_step": batch * seq, "flops_per_step": ...}``.

    checkpoint: an AsyncCheckpointManager (training/checkpoint.py). When
    it holds a complete checkpoint, the freshly-initialized state is
    replaced by the restored one re-placed onto the live shardings
    (reshard_like) — so a preempted/retried run resumes instead of
    restarting, and subsequent `checkpoint.save(state, step)` calls
    overlap their upload with the train steps that follow. The resumed
    step and the saved `extra` (e.g. the data iterator's resume stamp)
    are available afterwards as `checkpoint.last_restored` — without
    them a resumed run would silently restart its data stream."""
    optimizer = optimizer or default_optimizer()
    use_zero = shd.zero_enabled(mesh, zero)
    # compile-shaping state: every rank must build the SAME mesh/program
    # (analysis/divergence.py's gang-divergent-compile class, verified at
    # runtime by the sanitizer barrier); the zero switch shapes the
    # program, so it is part of the compile key
    sanitizer.journal("compile", "make_trainer", axes=mesh.axis_names,
                      key=str(dict(mesh.shape))
                      + (";zero" if use_zero else ""))
    state, shardings = make_train_state(
        rng, cfg, mesh, model, optimizer=optimizer, rules=rules,
        zero=use_zero,
    )
    # hand the step the LIVE opt-state placement so mixed (data+model
    # parallel) meshes constrain onto exactly what make_train_state built
    # instead of re-deriving from a replicated base
    opt_specs = None
    if use_zero:
        from jax.sharding import NamedSharding

        opt_specs = jax.tree.map(
            lambda s: s.spec if isinstance(s, NamedSharding) else None,
            shardings["opt_state"])
        if any(sp is None for sp in jax.tree.leaves(
                opt_specs, is_leaf=lambda x: x is None)):
            opt_specs = None  # non-mesh placements: let trace-time derive
    step = make_train_step(cfg, mesh, model, optimizer=optimizer,
                           loss_fn=loss_fn, zero=use_zero, rules=rules,
                           opt_specs=opt_specs, timed_update=timed_update)
    if checkpoint is not None:
        restored = checkpoint.restore(like=state)
        if restored is not None:
            state = restored.state
    check_opt_state(optimizer, state)
    if telemetry:
        from .metrics import instrument_train_step

        kwargs = telemetry if isinstance(telemetry, dict) else {}
        step = instrument_train_step(step, **kwargs)
    # sanitizer wraps OUTERMOST: the instrumentation must keep seeing the
    # raw jitted step (its jit-cache probe and cost-analysis .lower() die
    # on a plain wrapper); the .telemetry handle stays reachable
    wrapped = sanitizer.wrap_step(step)
    if wrapped is not step and hasattr(step, "telemetry"):
        wrapped.telemetry = step.telemetry
    step = wrapped
    return state, step, shardings


def make_eval_step(cfg, mesh, model, loss_fn=None):
    import inspect

    loss_fn = loss_fn or model.loss_fn
    loss_takes_mesh = "mesh" in inspect.signature(loss_fn).parameters

    def step(params, batch):
        if loss_takes_mesh:
            return loss_fn(params, batch, cfg, mesh=mesh)
        return loss_fn(params, batch, cfg)

    return jax.jit(step)


def shard_batch(batch, mesh):
    """Place a host batch onto the mesh: batch dim over data axes; the
    sequence dim over the 'sequence' axis when present AND divisible (a
    [B, S+1] token array stays batch-sharded; GSPMD reshards the sliced
    [B, S] inputs inside the step)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..spmd.mesh import data_axes

    sanitizer.journal("collective", "shard_batch", axes=mesh.axis_names,
                      shape=batch)
    axes = data_axes(mesh)
    batch_spec = axes if axes else None
    seq_size = mesh.shape.get("sequence", 1)

    def place(x):
        if (
            seq_size > 1
            and getattr(x, "ndim", 0) >= 2
            and x.shape[1] % seq_size == 0
        ):
            spec = PartitionSpec(batch_spec, "sequence")
        else:
            spec = PartitionSpec(batch_spec)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, batch)
