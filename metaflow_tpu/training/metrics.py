"""Training-step telemetry: wall time, tokens/sec, MFU, compile cache,
device-memory high-water — emitted through the run's flight recorder.

The papers this repo leans on (arxiv 2011.03641, 2104.06272) attribute
their wins to exactly this per-step timing/utilization telemetry; the
reference framework delegates it to user frameworks. Here it is built in:
wrap any jitted train step with `instrument_train_step` (or pass
`telemetry=...` to `make_trainer`) and every step emits a `train.step`
timer record with tokens/sec and MFU attached, compile events are
detected via the jit cache, and an on-demand `jax.profiler` capture
(telemetry.ProfileTrigger) can be armed on a live run.

Timing semantics: step N's duration is the host wall-clock interval
between the dispatch of step N and step N+1. With donated buffers the
host throttles to the device rate in steady state, so the interval IS
the device step time without inserting a per-step `block_until_ready`
(which would serialize the pipeline the telemetry is measuring).
"""

import functools
import os
import time

from .. import knobs

from .. import progress as progress_mod
from .. import telemetry

# bf16 peak TFLOP/s per chip, from published TPU specs (substring-matched
# against jax Device.device_kind so "TPU v5 lite" and "TPU v5e" both hit).
# Single source of truth: bench.py imports these.
TPU_PEAK_TFLOPS = [
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v6e", 918.0),
    ("v4", 275.0),
    ("v3", 123.0),
]

# HBM bandwidth GB/s per chip, same sources (bench roofline)
TPU_HBM_GBPS = [
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v6e", 1640.0),
    ("v4", 1228.0),
    ("v3", 900.0),
]


def peak_tflops(device_kind):
    """Published bf16 peak TFLOP/s for a chip kind, or None (CPU/GPU).

    TPUFLOW_PEAK_TFLOPS overrides the table — for chips not yet listed,
    or to get meaningful MFU numbers out of CPU/GPU dev runs."""
    override = knobs.get_raw("TPUFLOW_PEAK_TFLOPS")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    return next((tf for sub, tf in TPU_PEAK_TFLOPS if sub in kind), None)


def hbm_gbps(device_kind):
    kind = (device_kind or "").lower()
    return next((bw for sub, bw in TPU_HBM_GBPS if sub in kind), None)


def flops_per_token_dense(n_params, n_layers, dim, seq):
    """Train-step FLOPs/token for a dense transformer (fwd+bwd = 3x fwd):
    6*N + 12*L*D*S, the PaLM appendix-B convention (see bench.py _mfu for
    the honesty caveats about counting embedding params)."""
    return 6.0 * n_params + 12.0 * n_layers * dim * seq


def _cache_size(fn):
    try:
        return fn._cache_size()
    except Exception:
        return None


def _device_memory_bytes():
    """(in_use, peak) device memory in bytes for the worst local device;
    falls back to the live-array footprint where the backend exposes no
    allocator stats (CPU)."""
    import jax

    in_use = peak = None
    try:
        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            in_use = max(in_use or 0, stats.get("bytes_in_use", 0))
            peak = max(peak or 0,
                       stats.get("peak_bytes_in_use",
                                 stats.get("bytes_in_use", 0)))
    except Exception:
        pass
    if in_use is None:
        try:
            in_use = sum(int(a.nbytes) for a in jax.live_arrays())
        except Exception:
            return None, None
    return in_use, peak if peak is not None else in_use


def _tree_device_bytes(tree):
    """Per-device resident bytes for a pytree of sharded arrays.

    Metadata-only (shape/dtype/sharding.shard_shape) so it is safe on
    DONATED buffers — the train step consumed its input state, but the
    layout survives deletion. Replicated leaves count full size (each
    device holds a copy); a ZeRO/fsdp-sharded leaf counts 1/N — this is
    the gauge the sharded-update memory win shows up in. SPMD placement
    is uniform across devices, so one device's sum is every device's."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
        except Exception:
            shard_shape = tuple(shape)
        n = 1
        for d in shard_shape:
            n *= int(d)
        total += n * np.dtype(dtype).itemsize
    return total


class TrainStepTelemetry(object):
    """Per-step metric emitter driven by instrument_train_step."""

    def __init__(self, tokens_per_step=None, flops_per_step=None,
                 cost_analysis=False, prefix="train", memory_every=10,
                 profile=True):
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self._want_cost_analysis = cost_analysis
        self.prefix = prefix
        self.memory_every = max(1, int(memory_every))
        self.step_num = 0
        self.compiles = 0
        self.compile_ms = 0.0
        self._compile_steps = set()
        self._prev_start = None
        self._prev_return = None
        self._stalls = []
        self._intervals = []
        self._mem_peak = 0
        self._mem_split = {}
        self._update_ms = []
        self._pending_update_ms = None
        self._transfer_ms = []
        self._pending_transfer_ms = None
        self._per_chip = None  # (n_devices, peak_tflops) lazy
        self._profile = None
        self._want_profile = profile
        self._closed = False
        self._step_ema_s = None  # steady-state step-time EMA (hang deadline)

    # ---------- lazy hardware context ----------

    def _chip_context(self):
        if self._per_chip is None:
            import jax

            n = jax.device_count()
            kind = jax.devices()[0].device_kind
            self._per_chip = (n, peak_tflops(kind), kind)
        return self._per_chip

    def _trigger(self):
        if self._profile is None and self._want_profile:
            self._profile = telemetry.ProfileTrigger(
                recorder=telemetry.current_recorder())
        return self._profile

    # ---------- per-step hooks ----------

    def before_step(self):
        now = time.perf_counter()
        trigger = self._trigger()
        if trigger is not None:
            trigger.on_step(self.step_num)
        # host time between the previous step's return and this call is
        # the input stall: the train loop was blocked in next(iterator)
        # (plus loop overhead) instead of dispatching — the signal that a
        # run is INPUT-bound. It lands inside step N-1's wall interval,
        # so it rides that step's record.
        stall_s = (None if self._prev_return is None
                   else now - self._prev_return)
        if self._prev_start is not None:
            self._emit_step(self.step_num - 1, now - self._prev_start,
                            stall_s=stall_s)
        self._prev_start = now
        # per-rank progress beat: the hang watchdog's liveness channel.
        # Deadline is adaptive (max(floor, mult × EMA)); while a compile
        # is still POSSIBLE — no steady-state interval yet, or the step
        # just before this one compiled (retraces come in bursts) — the
        # much larger compile grace applies, so a long first-step compile
        # never reads as a hang.
        compile_possible = (
            self._step_ema_s is None
            or (self.step_num - 1) in self._compile_steps)
        progress_mod.beat(
            step_num=self.step_num, phase=self.prefix,
            deadline_s=progress_mod.hang_deadline_s(
                ema_s=self._step_ema_s,
                compile_possible=compile_possible))
        return now

    def after_step(self, step_fn, call_started, pre_cache, args, kwargs):
        """Compile detection + one-time cost-analysis FLOPs resolution."""
        dt = time.perf_counter() - call_started
        size = _cache_size(step_fn)
        if size is not None and pre_cache is not None and size > pre_cache:
            # the jit cache grew during this call: it traced + compiled
            self.compiles += size - pre_cache
            self.compile_ms += dt * 1000
            self._compile_steps.add(self.step_num)
            telemetry.emit("timer", "%s.compile" % self.prefix,
                           ms=dt * 1000, ok=True, step_num=self.step_num)
            telemetry.counter("%s.compile_cache_miss" % self.prefix)
        # cache hits are derived in report() (calls - compiles): a
        # per-step hit counter would be pure record noise
        if (self.flops_per_step is None and self._want_cost_analysis
                and self.step_num == 0):
            self.flops_per_step = self._flops_from_cost_analysis(
                step_fn, args, kwargs)
        if self.step_num % self.memory_every == 0:
            in_use, peak = _device_memory_bytes()
            if in_use is not None:
                self._mem_peak = max(self._mem_peak, peak or in_use)
                telemetry.gauge(
                    "%s.device_memory_bytes" % self.prefix, in_use,
                    step_num=self.step_num,
                    data={"peak": peak} if peak else None)
            self._emit_memory_split(args, peak or in_use)
        # diagnostic split-step mode (make_train_step timed_update=True)
        # exposes the update's wall time as an attribute; ride it into the
        # NEXT emitted record — _emit_step(N) fires before after_step(N+1)
        update_ms = getattr(step_fn, "last_update_ms", None)
        if update_ms is not None:
            self._pending_update_ms = float(update_ms)
        # MPMD stage steps expose the wall-clock they spent BLOCKED on
        # the stage transport (spmd/mpmd.py) the same way — the
        # PIPELINE-BOUND signal `tpuflow metrics` surfaces per stage
        transfer_ms = getattr(step_fn, "last_transfer_stall_ms", None)
        if transfer_ms is not None:
            self._pending_transfer_ms = float(transfer_ms)
        self.step_num += 1
        self._prev_return = time.perf_counter()

    def _emit_memory_split(self, args, peak):
        """Split the high-water gauge: params vs optimizer state are
        metadata-exact per device (see _tree_device_bytes); activations is
        the remainder of the allocator peak — on backends with no
        allocator stats (CPU) the remainder is live-footprint-derived and
        only a rough upper bound, but the params/opt split stays exact."""
        state = args[0] if args else None
        if not (isinstance(state, dict) and "params" in state
                and "opt_state" in state):
            return
        try:
            params_b = _tree_device_bytes(state["params"])
            opt_b = _tree_device_bytes(state["opt_state"])
        except Exception:
            return
        split = {"params": params_b, "opt_state": opt_b}
        if peak:
            split["activations"] = max(0, int(peak) - params_b - opt_b)
        self._mem_split = split
        for key, value in split.items():
            telemetry.gauge("%s.memory.%s_bytes" % (self.prefix, key),
                            value, step_num=self.step_num)

    def _flops_from_cost_analysis(self, step_fn, args, kwargs):
        """XLA cost-model FLOPs for the exact step — pays ONE extra
        lower+compile (AOT path), so it is opt-in (cost_analysis=True).
        Pass flops_per_step explicitly when the analytic count is known
        (flops_per_token_dense)."""
        try:
            cost = step_fn.lower(*args, **kwargs).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            flops = float(cost.get("flops", 0.0))
            if flops > 0:
                telemetry.event(
                    "%s.cost_analysis" % self.prefix,
                    data={"flops_per_step": flops})
                return flops
        except Exception:
            pass
        return None

    def _emit_step(self, step_num, interval_s, stall_s=None):
        if interval_s <= 0:
            return
        data = {}
        if step_num in self._compile_steps:
            # a compile happened inside this interval: the record is
            # still emitted (with the flag), but it stays out of the
            # steady-state summary — compile time is tracked separately
            data["compile"] = True
        else:
            self._intervals.append(interval_s)
            if stall_s is not None:
                self._stalls.append(stall_s)
            self._step_ema_s = (
                interval_s if self._step_ema_s is None
                else 0.8 * self._step_ema_s + 0.2 * interval_s)
        if stall_s is not None:
            data["input_stall_ms"] = round(stall_s * 1000, 3)
        if self._pending_update_ms is not None:
            data["optimizer_update_ms"] = round(self._pending_update_ms, 3)
            if "compile" not in data:
                self._update_ms.append(self._pending_update_ms)
            self._pending_update_ms = None
        if self._pending_transfer_ms is not None:
            data["transfer_stall_ms"] = round(self._pending_transfer_ms, 3)
            if "compile" not in data:
                self._transfer_ms.append(self._pending_transfer_ms)
            self._pending_transfer_ms = None
        if self.tokens_per_step:
            data["tokens_per_sec"] = round(
                self.tokens_per_step / interval_s, 1)
        if self.flops_per_step:
            n_devices, peak, _kind = self._chip_context()
            achieved_tflops = (
                self.flops_per_step / interval_s / n_devices / 1e12)
            data["tflops_per_chip"] = round(achieved_tflops, 3)
            if peak:
                data["mfu"] = round(achieved_tflops / peak, 4)
        telemetry.emit("timer", "%s.step" % self.prefix,
                       ms=interval_s * 1000, ok=True, step_num=step_num,
                       data=data or None)

    # ---------- finalization ----------

    def close(self):
        """Emit the trailing step + summary gauges; stop any in-flight
        profiler capture. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._prev_start is not None and self.step_num > 0:
            self._emit_step(self.step_num - 1,
                            time.perf_counter() - self._prev_start)
        # terminal progress beat: the loop is over — a control rank
        # idling in worker reap after its last step is NOT hung
        progress_mod.done(step_num=self.step_num)
        if self._profile is not None:
            self._profile.stop(self.step_num)
        interval = self._goodput_interval()
        if interval is not None:
            # per-rank chip-second rollup in the goodput taxonomy
            # (metaflow_tpu/goodput.py): rides the crash-safe recorder
            # so the ledger CLI can cross-check its derivation against
            # what the rank itself tallied
            telemetry.event("goodput.interval", data=interval)
        summary = self.report()
        for key in ("steps", "mean_step_ms", "tokens_per_sec", "mfu",
                    "input_stall_ms", "optimizer_update_ms",
                    "transfer_stall_ms",
                    "memory_params_bytes", "memory_opt_state_bytes",
                    "memory_activations_bytes",
                    "compiles", "compile_ms", "device_memory_peak_bytes"):
            value = summary.get(key)
            if value is not None:
                telemetry.gauge("%s.summary.%s" % (self.prefix, key), value)
        telemetry.flush()

    def _goodput_interval(self):
        """This rank's step time split into goodput categories
        (seconds): the `goodput.interval` event payload, schema pinned
        in tests/schema_validate.py::GOODPUT_INTERVAL_DATA_SCHEMA."""
        steady_s = sum(self._intervals)
        compile_s = self.compile_ms / 1000.0
        if steady_s <= 0 and compile_s <= 0:
            return None
        stall_s = sum(self._stalls)
        update_s = sum(self._update_ms) / 1000.0
        transfer_s = sum(self._transfer_ms) / 1000.0
        productive = max(0.0, steady_s - stall_s - update_s - transfer_s)
        return {
            "span_s": round(steady_s + compile_s, 3),
            "steps": len(self._intervals),
            "categories": {
                "productive_step": round(productive, 3),
                "compile": round(compile_s, 3),
                "input_stall": round(stall_s, 3),
                "transfer_stall": round(transfer_s, 3),
                "update": round(update_s, 3),
            },
        }

    def report(self):
        """Summary dict over the recorded steps (steady-state: the first
        post-compile interval is included; compile time is separate)."""
        out = {"steps": len(self._intervals), "compiles": self.compiles,
               "compile_cache_hits": max(0, self.step_num - self.compiles),
               "compile_ms": round(self.compile_ms, 1)}
        if self._mem_peak:
            out["device_memory_peak_bytes"] = self._mem_peak
        for key, value in self._mem_split.items():
            out["memory_%s_bytes" % key] = value
        if self._update_ms:
            out["optimizer_update_ms"] = round(
                sum(self._update_ms) / len(self._update_ms), 3)
        if self._transfer_ms:
            out["transfer_stall_ms"] = round(
                sum(self._transfer_ms) / len(self._transfer_ms), 3)
        if not self._intervals:
            return out
        mean = sum(self._intervals) / len(self._intervals)
        out["mean_step_ms"] = round(mean * 1000, 3)
        if self._stalls:
            out["input_stall_ms"] = round(
                sum(self._stalls) / len(self._stalls) * 1000, 3)
        if self.tokens_per_step:
            out["tokens_per_sec"] = round(self.tokens_per_step / mean, 1)
        if self.flops_per_step:
            n_devices, peak, kind = self._chip_context()
            achieved = self.flops_per_step / mean / n_devices / 1e12
            out["tflops_per_chip"] = round(achieved, 3)
            out["device_kind"] = kind
            if peak:
                out["mfu"] = round(achieved / peak, 4)
        return out


def instrument_train_step(step_fn, tokens_per_step=None, flops_per_step=None,
                          cost_analysis=False, prefix="train",
                          memory_every=10, profile=True):
    """Wrap a (jitted) train step so every call emits per-step telemetry.

    The wrapper adds only host-side bookkeeping (no device syncs): two
    perf_counter reads, a cache-size probe, and one buffered record per
    step — the BENCH_MODE=telemetry bench pins the overhead at ≤2%.

    tokens_per_step: GLOBAL tokens consumed per step (batch*seq) — enables
        tokens/sec on every record.
    flops_per_step: GLOBAL FLOPs per step (e.g. flops_per_token_dense(...)
        * tokens) — enables achieved-TFLOPs and, on TPU, MFU.
    cost_analysis: resolve flops_per_step from XLA's cost model instead
        (pays one extra lower+compile on the first step).
    profile: arm telemetry.ProfileTrigger (TPUFLOW_PROFILE_STEPS window,
        file/signal triggers) on this step counter.

    Returns the wrapped callable; `.telemetry` is the TrainStepTelemetry
    (call `.telemetry.close()` after the loop — or rely on the task
    finalization flush for the buffered records).
    """
    tel = TrainStepTelemetry(
        tokens_per_step=tokens_per_step, flops_per_step=flops_per_step,
        cost_analysis=cost_analysis, prefix=prefix,
        memory_every=memory_every, profile=profile)

    # chaos harness tick (TPUFLOW_CHAOS): any instrumented train loop
    # gets deterministic fault injection for free — the scheduled kill
    # lands at a step boundary, before the step's compute is issued
    chaos_on = bool(knobs.get_str("TPUFLOW_CHAOS"))

    @functools.wraps(step_fn, assigned=("__name__", "__doc__"), updated=())
    def wrapped(*args, **kwargs):
        if chaos_on:
            from ..devtools.chaos import maybe_chaos_step

            maybe_chaos_step(tel.step_num)
        started = tel.before_step()
        pre_cache = _cache_size(step_fn)
        out = step_fn(*args, **kwargs)
        tel.after_step(step_fn, started, pre_cache, args, kwargs)
        return out

    wrapped.telemetry = tel
    return wrapped
