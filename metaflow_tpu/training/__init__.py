from ..data import StreamingTokenBatches
from .checkpoint import AsyncCheckpointManager, Checkpoint
from .data import STATE_KEY, ResumableTokenBatches, sharded_dataset
from .metrics import (
    TrainStepTelemetry,
    flops_per_token_dense,
    instrument_train_step,
    peak_tflops,
)
from .train_step import (
    check_opt_state,
    default_optimizer,
    memory_efficient_optimizer,
    make_train_state,
    make_train_step,
    make_trainer,
    make_eval_step,
    reshard_like,
    shard_batch,
)

__all__ = [
    "AsyncCheckpointManager",
    "Checkpoint",
    "check_opt_state",
    "default_optimizer",
    "memory_efficient_optimizer",
    "make_train_state",
    "make_train_step",
    "make_trainer",
    "make_eval_step",
    "reshard_like",
    "shard_batch",
    "ResumableTokenBatches",
    "StreamingTokenBatches",
    "sharded_dataset",
    "STATE_KEY",
    "TrainStepTelemetry",
    "instrument_train_step",
    "flops_per_token_dense",
    "peak_tflops",
]
