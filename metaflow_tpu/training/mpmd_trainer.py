"""Train a FULL Llama through MPMD pipeline parallelism.

The per-stage analogue of pipeline_trainer.py: the same
layer_fn/loss_fn/embedding-cotangent bridging, but instead of one SPMD
program ticking the whole schedule, THIS process runs exactly one
stage's row of the interleaved-1F1B timetable (spmd/mpmd.py) and trades
activations/cotangents with its ring neighbours over the stage
transport. Stage 0 owns the embedding (its gradient chains from the
schedule's input cotangent via the scatter-add transpose of the
gather); the last stage owns final norm + lm_head, differentiated
inside its last-chunk loss slots.

Telemetry: construction emits one `mpmd.stage.trace` event (the MPMD
mirror of `pipeline.trace`); every step emits one `mpmd.transfer` event
with that step's frame/byte/stall deltas, and exposes the stall as
`last_transfer_stall_ms` so `instrument_train_step` rides it into the
per-step record — `tpuflow metrics` aggregates both into the per-stage
MPMD section that names the bubble stage.
"""

import os

import jax
import jax.numpy as jnp

from .. import telemetry, tracing
from ..models import llama
from ..ops import rms_norm, rope_frequencies
from ..spmd import mpmd


def make_stage_step(cfg, plan, stage, transport, seq_len):
    """Build this stage's step callable: step(params, tokens) ->
    {"loss": mean loss (last stage, else None),
     "grads": dict of THIS stage's parameter gradients — "layers" in
         the stage's local chunk order (plan.layers_for_stage maps back
         to natural indices), plus "embed" on stage 0 and
         "final_norm"/"lm_head" on the last stage}.

    `params` is the full Llama pytree; each stage reads only its own
    slice (at scale each gang would only ever materialize that slice —
    the slicing is the ownership contract). seq_len is the TOKEN count
    per example (the model sees seq_len-1 after the shift).
    """
    stage = int(stage)
    dt = llama.param_dtype(cfg)
    cos, sin = rope_frequencies(
        cfg.head_dim, int(seq_len) - 1, cfg.rope_theta, dtype=dt,
        llama3_scaling=cfg.rope_llama3_scaling,
    )

    def layer_fn(x, lp):
        return llama._layer(cfg, cos, sin, x, lp)

    def loss_fn(out, y, head):
        # the same chunk-safe CE the non-pipelined loss uses (fp32
        # logits never materialize beyond one chunk)
        h = rms_norm(out, head["final_norm"], cfg.norm_eps)
        loss_sum, count = llama._ce_sums(h, head["lm_head"], y, None)
        return loss_sum / jnp.maximum(count, 1)

    executor = mpmd.StageExecutor(
        plan, stage, transport, layer_fn,
        loss_fn=loss_fn if stage == plan.S - 1 else None,
        return_input_grad=(stage == 0),
    )
    # join the run's trace tree: each stage gets a deterministic child
    # span of the ambient run traceparent, stamped into its records so
    # `tpuflow trace` can show per-stage transfer spans alongside the
    # request trees (and Perfetto exports can lane them per stage)
    ambient_tp = os.environ.get("TRACEPARENT", "")
    stage_trace = stage_span = ""
    if ambient_tp:
        stage_tp = tracing.child_traceparent(
            ambient_tp, "mpmd-stage-%d" % stage)
        stage_trace, stage_span = tracing.traceparent_ids(stage_tp)

    def _trace_data(data):
        if stage_span:
            data["trace"] = stage_trace
            data["span"] = stage_span
        return data

    telemetry.event(
        "mpmd.stage.trace",
        data=_trace_data(dict(plan.describe(), stage=stage,
                              layers=plan.layers_for_stage(stage),
                              seq=int(seq_len) - 1)))

    def step(params, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        M = plan.M
        mb = inp.shape[0] // M
        x_mbs = y_mbs = head = None
        if stage == 0:
            x = params["embed"][inp].astype(dt)
            x_mbs = x.reshape((M, mb) + x.shape[1:])
        if stage == plan.S - 1:
            y_mbs = tgt.reshape((M, mb) + tgt.shape[1:])
            head = {"final_norm": params["final_norm"],
                    "lm_head": params["lm_head"]}
        before = transport.stats()
        res = executor.run(
            mpmd.slice_stage_params(plan, stage, params["layers"]),
            x_mbs=x_mbs, y_mbs=y_mbs, head_params=head)
        after = transport.stats()
        step.last_transfer_stall_ms = executor.last_transfer_stall_ms
        telemetry.event(
            "mpmd.transfer",
            data=_trace_data(
                {"stage": stage,
                 "double_buffer": bool(after["double_buffer"]),
                 "frames_sent": int(after["frames_sent"]
                                    - before["frames_sent"]),
                 "frames_recv": int(after["frames_recv"]
                                    - before["frames_recv"]),
                 "bytes_sent": int(after["bytes_sent"]
                                   - before["bytes_sent"]),
                 "bytes_recv": int(after["bytes_recv"]
                                   - before["bytes_recv"]),
                 "stall_ms": round(after["stall_ms"]
                                   - before["stall_ms"], 3)}))
        grads = {"layers": res["grads"]}
        if stage == 0:
            # embedding gradient: the gather's transpose is a
            # scatter-add of the input cotangent over the token ids
            dx = res["input_grad"].reshape((M * mb,) + inp.shape[1:]
                                           + (cfg.dim,))
            grads["embed"] = jnp.zeros(
                (cfg.vocab_size, cfg.dim), jnp.float32).at[inp].add(dx)
        if stage == plan.S - 1:
            grads["final_norm"] = res["head_grads"]["final_norm"]
            grads["lm_head"] = res["head_grads"]["lm_head"]
        return {"loss": res["loss"], "grads": grads}

    # instrument_train_step probes this for compile-cache growth: the
    # three chunk programs ARE this stage's compile footprint
    step._cache_size = executor.compile_count
    step.last_transfer_stall_ms = 0.0
    step.executor = executor
    return step


def run_stage_steps(cfg, plan, stage, transport, tokens, num_steps=1,
                    params=None, instrument=True):
    """Drive `num_steps` schedule passes on one stage gang — the demo
    flow / bench entrypoint. Returns (last step's result, telemetry
    summary dict or None)."""
    from .metrics import instrument_train_step

    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(lambda p: p.astype(jnp.float32), params)
    step = make_stage_step(cfg, plan, stage, transport,
                           seq_len=tokens.shape[1])
    fn = step
    if instrument:
        fn = instrument_train_step(
            step, tokens_per_step=int(tokens.shape[0])
            * (int(tokens.shape[1]) - 1),
            prefix="mpmd.stage%d" % int(stage), profile=False)
    out = None
    for _ in range(int(num_steps)):
        out = fn(params, tokens)
    summary = None
    if instrument:
        fn.telemetry.close()
        summary = fn.telemetry.report()
    return out, summary
