"""Input pipeline: host-side batching + device prefetch.

The reference delegates data loading to user code entirely; on TPU the
framework must keep the MXU fed — this module provides a minimal sharded
loader: deterministic global batches cut per-host, placed onto the mesh
asynchronously one step ahead (double buffering hides the host→HBM copy).

Resumable streams: the reference gets exact resume for free by persisting
every artifact per task (/root/reference/metaflow/datastore/
task_datastore.py:880); a TPU training step's data cursor lives in the
input iterator, so ResumableTokenBatches carries explicit state (epoch,
batch cursor, shuffle seed) and stamps it onto every batch — checkpoint
the stamp with the model and a preempted run resumes its token sequence
exactly, no replay, no skip.
"""

import collections
import threading

import numpy as np

# canonical home: metaflow_tpu/data/ordering.py (shared with the
# streaming loader); re-exported here for the existing import surface.
# shard_iterator passes the stamp through host-side (never deviced).
from ..data.ordering import (  # noqa: F401  (STATE_KEY re-export)
    STATE_KEY,
    hierarchical_window_order,
)


class ResumableTokenBatches(object):
    """Deterministic, resumable epoch iterator over a 1-D token array.

    Yields {'tokens': [B, seq_len+1], STATE_KEY: {...}} batches. The
    per-epoch shuffle is a pure function of (seed, epoch), so the stamped
    state — three ints — fully determines the rest of the stream:

        ds = ResumableTokenBatches(data, 8, 128, seed=0)
        ...train, checkpoint batch[STATE_KEY] with the model...
        ds2 = ResumableTokenBatches(data, 8, 128, seed=0)
        ds2.restore(saved_state)   # continues with the NEXT batch

    The stamp rides inside the batch (not on the iterator) so device
    prefetch — which runs the iterator ahead of consumption — cannot
    desynchronize the checkpointed cursor from the batches the train
    loop actually consumed.
    """

    def __init__(self, data, batch_size, seq_len, *, seed=None,
                 epochs=None, drop_last=True, shard_windows=None):
        """shard_windows: view the array as consecutive shards of this
        many windows and shuffle hierarchically (shard order, then
        windows within each shard) instead of globally — the EXACT order
        a StreamingTokenBatches walks over the equivalent sharded corpus
        (data/ordering.py), so the two are byte-identical for the same
        seed. Default None keeps the historical global permutation."""
        self._data = np.asarray(data)
        self._batch_size = batch_size
        self._window = seq_len + 1
        self._seed = seed
        self._epochs = epochs
        self._drop_last = bool(drop_last)
        self._shard_windows = (None if shard_windows is None
                               else int(shard_windows))
        self._epoch = 0
        self._cursor = 0  # batches already yielded in the current epoch
        n_windows = len(self._data) // self._window
        if n_windows == 0:
            raise ValueError(
                "data holds %d tokens — shorter than one %d-token window"
                % (len(self._data), self._window))
        self._n_windows = n_windows

    @property
    def batches_per_epoch(self):
        if self._drop_last:
            return self._n_windows // self._batch_size
        return -(-self._n_windows // self._batch_size)

    def state(self):
        """Resume state BEFORE the next batch to be produced (flat ints;
        JSON- and orbax-serializable). Carries the stream geometry too,
        so restoring onto a differently-shaped stream is a hard error,
        not a silently different token sequence."""
        state = {"epoch": int(self._epoch), "cursor": int(self._cursor),
                 "seed": self._seed,
                 "batch_size": int(self._batch_size),
                 "window": int(self._window),
                 "n_windows": int(self._n_windows),
                 # drop_last changes batches_per_epoch, so a stamp from a
                 # drop_last=False stream must not restore into a
                 # drop_last=True one (and vice versa)
                 "drop_last": int(self._drop_last)}
        if self._shard_windows is not None:
            state["shard_windows"] = int(self._shard_windows)
        return state

    def restore(self, state):
        """Position the stream just after the batch that carried `state`
        — iteration continues with the batch that would have come next."""
        if state.get("seed") != self._seed:
            raise ValueError(
                "checkpointed stream seed %r != this stream's %r — "
                "restoring would produce a different shuffle order"
                % (state.get("seed"), self._seed))
        for key, mine in (("batch_size", self._batch_size),
                          ("window", self._window),
                          ("n_windows", self._n_windows)):
            theirs = int(state[key])
            if theirs != mine:
                raise ValueError(
                    "checkpointed stream %s=%d != this stream's %d — the "
                    "cursor would address different tokens (same data, "
                    "batch_size and seq_len are required to resume)"
                    % (key, theirs, mine))
        # drop_last changes batches_per_epoch: a mismatched stamp would
        # restore into a stream whose cursor addresses different batches.
        # Pre-drop_last stamps don't carry the key; skip only then.
        theirs = state.get("drop_last")
        if theirs is not None and bool(int(theirs)) != self._drop_last:
            raise ValueError(
                "checkpointed stream drop_last=%r != this stream's %r — "
                "batches_per_epoch differs, the cursor would address "
                "different batches" % (bool(int(theirs)), self._drop_last))
        # a stamp without shard_windows came from a global-permutation
        # stream (shard_windows=None): the orders differ, so None vs set
        # is a mismatch, not a missing key
        theirs = state.get("shard_windows")
        if (theirs is None) != (self._shard_windows is None) or (
                theirs is not None
                and int(theirs) != self._shard_windows):
            raise ValueError(
                "checkpointed stream shard_windows=%r != this stream's %r "
                "— the shuffle orders differ, restoring would produce a "
                "different token sequence"
                % (theirs, self._shard_windows))
        epoch = int(state["epoch"])
        cursor = int(state["cursor"])
        # a corrupted stamp must fail loudly, not silently truncate or
        # shift the token stream: cursor == batches_per_epoch is the
        # legal "last batch of the epoch" stamp, anything past it (or
        # negative) addresses batches that don't exist
        per_epoch = self.batches_per_epoch
        if epoch < 0 or (self._epochs is not None and epoch > self._epochs):
            raise ValueError(
                "checkpointed stream epoch=%d out of range [0, %s] — "
                "corrupted resume stamp" % (epoch, self._epochs))
        if not 0 <= cursor <= per_epoch:
            raise ValueError(
                "checkpointed stream cursor=%d out of range [0, %d] — "
                "corrupted resume stamp" % (cursor, per_epoch))
        self._epoch = epoch
        self._cursor = cursor
        return self

    def _order(self, epoch):
        if self._shard_windows is not None:
            # hierarchical (shard order, then windows within shard): the
            # shared pure function the streaming loader also walks
            return hierarchical_window_order(
                self._seed, epoch, self._n_windows, self._shard_windows)
        if self._seed is None:
            return np.arange(self._n_windows)
        rng = np.random.default_rng([int(self._seed), int(epoch)])
        return rng.permutation(self._n_windows)

    def __iter__(self):
        data, W, B = self._data, self._window, self._batch_size
        while self._epochs is None or self._epoch < self._epochs:
            order = self._order(self._epoch)
            per_epoch = self.batches_per_epoch
            while self._cursor < per_epoch:
                idxs = order[self._cursor * B:(self._cursor + 1) * B]
                rows = [data[i * W:(i + 1) * W] for i in idxs]
                self._cursor += 1
                yield {"tokens": np.stack(rows), STATE_KEY: self.state()}
            self._epoch += 1
            self._cursor = 0


def token_batches(data, batch_size, seq_len, *, rng=None, drop_last=True):
    """Yield {'tokens': [B, seq_len+1]} batches from a 1-D token array
    (next-token LM convention: targets are inputs shifted by one)."""
    data = np.asarray(data)
    window = seq_len + 1
    n_windows = len(data) // window
    order = np.arange(n_windows)
    if rng is not None:
        rng.shuffle(order)
    batch = []
    for idx in order:
        batch.append(data[idx * window:(idx + 1) * window])
        if len(batch) == batch_size:
            yield {"tokens": np.stack(batch)}
            batch = []
    if batch and not drop_last:
        yield {"tokens": np.stack(batch)}


def shard_iterator(it, mesh):
    """Place each host batch onto the mesh (batch dim over data axes).
    The STATE_KEY resume stamp stays host-side, untouched."""
    from .train_step import shard_batch

    for batch in it:
        state = batch.pop(STATE_KEY, None)
        batch = shard_batch(batch, mesh)
        if state is not None:
            batch[STATE_KEY] = state
        yield batch


def prefetch(iterator, depth=2):
    """Run `iterator` in a background thread, keeping `depth` items ready —
    device transfer of step N+1 overlaps compute of step N."""
    queue = collections.deque()
    lock = threading.Condition()
    done = []
    error = []
    stopped = []

    def producer():
        try:
            for item in iterator:
                with lock:
                    while len(queue) >= depth and not stopped:
                        lock.wait()
                    if stopped:
                        return
                    queue.append(item)
                    lock.notify_all()
        except BaseException as ex:  # surface in the consumer, never swallow
            with lock:
                error.append(ex)
                lock.notify_all()
        finally:
            with lock:
                done.append(True)
                lock.notify_all()

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
        while True:
            with lock:
                while not queue and not done:
                    lock.wait()
                if queue:
                    item = queue.popleft()
                    lock.notify_all()
                elif error:
                    raise error[0]
                else:
                    return
            yield item
    finally:
        # consumer stopped early (break / close): release the producer so
        # the thread and its prefetched device buffers are reclaimed
        with lock:
            stopped.append(True)
            queue.clear()
            lock.notify_all()


def sharded_dataset(data, batch_size, seq_len, mesh, rng=None,
                    prefetch_depth=2, seed=None, state=None, epochs=None,
                    drop_last=True, corpus=None):
    """Batching → mesh placement → background prefetch, composed.

    With `seed` (and optionally a checkpointed `state` stamp to resume
    from), batches come from ResumableTokenBatches and carry their
    STATE_KEY resume stamp; the legacy `rng` path is single-epoch and
    unstamped.

    corpus: a data.StreamingTokenBatches (or any source honoring the
    same restore/iterate contract) — the on-datastore streaming path;
    `data`/`seed`/`epochs`/`drop_last` are ignored (they live on the
    corpus), `state` resumes it."""
    if corpus is not None:
        if state is not None:
            corpus.restore(state)
        source = iter(corpus)
    elif seed is not None or state is not None:
        ds = ResumableTokenBatches(data, batch_size, seq_len,
                                   seed=seed if seed is not None
                                   else (state or {}).get("seed"),
                                   epochs=epochs, drop_last=drop_last)
        if state is not None:
            ds.restore(state)
        source = iter(ds)
    else:
        source = token_batches(data, batch_size, seq_len, rng=rng,
                               drop_last=drop_last)
    return prefetch(shard_iterator(source, mesh), depth=prefetch_depth)
