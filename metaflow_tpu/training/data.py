"""Input pipeline: host-side batching + device prefetch.

The reference delegates data loading to user code entirely; on TPU the
framework must keep the MXU fed — this module provides a minimal sharded
loader: deterministic global batches cut per-host, placed onto the mesh
asynchronously one step ahead (double buffering hides the host→HBM copy).
"""

import collections
import threading

import numpy as np


def token_batches(data, batch_size, seq_len, *, rng=None, drop_last=True):
    """Yield {'tokens': [B, seq_len+1]} batches from a 1-D token array
    (next-token LM convention: targets are inputs shifted by one)."""
    data = np.asarray(data)
    window = seq_len + 1
    n_windows = len(data) // window
    order = np.arange(n_windows)
    if rng is not None:
        rng.shuffle(order)
    batch = []
    for idx in order:
        batch.append(data[idx * window:(idx + 1) * window])
        if len(batch) == batch_size:
            yield {"tokens": np.stack(batch)}
            batch = []
    if batch and not drop_last:
        yield {"tokens": np.stack(batch)}


def shard_iterator(it, mesh):
    """Place each host batch onto the mesh (batch dim over data axes)."""
    from .train_step import shard_batch

    for batch in it:
        yield shard_batch(batch, mesh)


def prefetch(iterator, depth=2):
    """Run `iterator` in a background thread, keeping `depth` items ready —
    device transfer of step N+1 overlaps compute of step N."""
    queue = collections.deque()
    lock = threading.Condition()
    done = []
    error = []
    stopped = []

    def producer():
        try:
            for item in iterator:
                with lock:
                    while len(queue) >= depth and not stopped:
                        lock.wait()
                    if stopped:
                        return
                    queue.append(item)
                    lock.notify_all()
        except BaseException as ex:  # surface in the consumer, never swallow
            with lock:
                error.append(ex)
                lock.notify_all()
        finally:
            with lock:
                done.append(True)
                lock.notify_all()

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
        while True:
            with lock:
                while not queue and not done:
                    lock.wait()
                if queue:
                    item = queue.popleft()
                    lock.notify_all()
                elif error:
                    raise error[0]
                else:
                    return
            yield item
    finally:
        # consumer stopped early (break / close): release the producer so
        # the thread and its prefetched device buffers are reclaimed
        with lock:
            stopped.append(True)
            queue.clear()
            lock.notify_all()


def sharded_dataset(data, batch_size, seq_len, mesh, rng=None,
                    prefetch_depth=2):
    """token_batches → mesh placement → background prefetch, composed."""
    return prefetch(
        shard_iterator(
            token_batches(data, batch_size, seq_len, rng=rng), mesh
        ),
        depth=prefetch_depth,
    )
