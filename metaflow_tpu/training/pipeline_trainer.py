"""Train a FULL Llama through pipeline parallelism.

Bridges models/llama.py onto the interleaved-1F1B schedule
(spmd/pipeline.py): the stacked transformer blocks become pipeline
chunks; the embedding lookup runs before the pipeline with its gradient
chained from the schedule's input cotangent (the scatter-add transpose
of the gather); final norm + lm_head ride as replicated head params
differentiated inside the last chunk's loss slots.

This is what the reference delegates to torchrun+DeepSpeed pipeline
engines — here the schedule, the model and the mesh are one system.
"""

import jax
import jax.numpy as jnp

from .. import telemetry
from ..models import llama
from ..ops import rms_norm, rope_frequencies
from ..spmd.pipeline import pipeline_train_interleaved


def pipeline_loss_and_grads(params, tokens, cfg, mesh,
                            num_microbatches=4, num_virtual_stages=1,
                            axis_name="pipeline"):
    """Next-token loss + gradients for EVERY parameter of the Llama
    pytree, computed through the pipeline schedule. Returns
    (loss, grads) with grads shaped exactly like `params`."""
    # this function body runs under jit TRACING (per-call records would
    # never fire) — emit the schedule's configuration once per compile,
    # which is exactly when it can change
    telemetry.event(
        "pipeline.trace",
        data={"num_microbatches": num_microbatches,
              "num_virtual_stages": num_virtual_stages,
              "axis_name": axis_name,
              "batch": int(tokens.shape[0]),
              "seq": int(tokens.shape[1]) - 1,
              "n_layers": int(cfg.n_layers)})
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    dt = llama.param_dtype(cfg)
    cos, sin = rope_frequencies(
        cfg.head_dim, inp.shape[1], cfg.rope_theta, dtype=dt,
        llama3_scaling=cfg.rope_llama3_scaling,
    )

    def layer_fn(x, lp):
        return llama._layer(cfg, cos, sin, x, lp)

    def loss_fn(out, y, head):
        # the same chunk-safe CE the non-pipelined loss uses (fp32
        # logits never materialize beyond one chunk)
        h = rms_norm(out, head["final_norm"], cfg.norm_eps)
        loss_sum, count = llama._ce_sums(h, head["lm_head"], y, None)
        return loss_sum / jnp.maximum(count, 1)

    head = {"final_norm": params["final_norm"],
            "lm_head": params["lm_head"]}
    x = params["embed"][inp].astype(dt)
    loss, layer_grads, aux = pipeline_train_interleaved(
        layer_fn, loss_fn, params["layers"], x, tgt, mesh,
        num_microbatches=num_microbatches,
        num_virtual_stages=num_virtual_stages, axis_name=axis_name,
        head_params=head, return_input_grad=True,
    )
    # embedding gradient: the gather's transpose is a scatter-add of the
    # input cotangent over the token ids
    d_embed = jnp.zeros_like(params["embed"], jnp.float32).at[inp].add(
        aux["input_grad"]
    )
    grads = {
        "embed": d_embed,
        "layers": layer_grads,
        "final_norm": aux["head_grads"]["final_norm"],
        "lm_head": aux["head_grads"]["lm_head"],
    }
    return loss, grads
