"""Structured task-log protocol.

Reference behavior: metaflow/mflog/ — lines are tagged
`[MFLOG|0|timestamp|source|id]message` so streams from different sources
(runtime vs task, multiple attempts) merge deterministically by timestamp.
The runtime's Worker tags captured lines on persist; readers merge + strip.
"""

import time
from datetime import datetime, timezone

VERSION = b"0"
RUNTIME = b"runtime"
TASK = b"task"

_DELIM = b"|"
_HEAD = b"[MFLOG" + _DELIM


def utc_timestamp():
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")


def decorate(source, line, now=None):
    """Tag one raw line (bytes) with the mflog header."""
    if isinstance(line, str):
        line = line.encode("utf-8")
    now = now or utc_timestamp()
    return b"".join(
        (_HEAD, VERSION, _DELIM, now.encode("ascii"), _DELIM, source, b"]",
         line.rstrip(b"\n"), b"\n")
    )


def decorate_stream(source, data):
    """Tag every line of a raw byte stream."""
    now = utc_timestamp()
    return b"".join(
        decorate(source, line, now) for line in data.split(b"\n") if line
    )


def parse(line):
    """Parse a tagged line → (timestamp_str, source, message) or None."""
    if not line.startswith(_HEAD):
        return None
    try:
        rest = line[len(_HEAD):]
        version, ts, rest = rest.split(_DELIM, 2)
        source, _, message = rest.partition(b"]")
        return ts.decode("ascii"), source.decode("ascii"), message
    except ValueError:
        return None


def merge_logs(streams):
    """Merge multiple tagged byte streams in timestamp order.

    streams: iterable of bytes. Untagged lines sort with their neighbours'
    timestamps (legacy logs stay readable)."""
    records = []
    for stream_idx, data in enumerate(streams):
        last_ts = ""
        for line_idx, line in enumerate(data.split(b"\n")):
            if not line:
                continue
            parsed = parse(line)
            if parsed:
                ts, source, message = parsed
                last_ts = ts
            else:
                ts, source, message = last_ts, "raw", line
            records.append((ts, stream_idx, line_idx, source, message))
    records.sort(key=lambda r: (r[0], r[1], r[2]))
    return records


def format_merged(streams, show_source=False, show_timestamp=False):
    out = []
    for ts, _si, _li, source, message in merge_logs(streams):
        prefix = b""
        if show_timestamp and ts:
            prefix += ts.encode("ascii") + b" "
        if show_source:
            prefix += b"[" + source.encode("ascii") + b"] "
        out.append(prefix + message)
    return b"\n".join(out) + (b"\n" if out else b"")
