"""Namespace-package extension discovery.

Reference behavior: metaflow/extension_support/plugins.py:15,140 — any
installed distribution providing a `metaflow_extensions.*` package can add
or override plugins in every category at import time. Here the extension
root is the PEP-420 namespace package ``metaflow_tpu_extensions``: multiple
distributions may each ship ``metaflow_tpu_extensions/<name>/`` (no
``__init__.py`` at the root), and every such subpackage is discovered and
merged when ``metaflow_tpu`` is imported.

An extension subpackage contributes via a ``plugins`` submodule (preferred)
or its own ``__init__``, exporting any of:

    STEP_DECORATORS    list of StepDecorator subclasses (merged by ``.name``)
    FLOW_DECORATORS    list of FlowDecorator subclasses (merged by ``.name``)
    STORAGE_BACKENDS   dict  name -> DataStoreStorage subclass
    METADATA_PROVIDERS dict  name -> MetadataProvider subclass
    CLI_COMMANDS       list of click commands added to every flow CLI
    SERIALIZERS        list of ArtifactSerializer INSTANCES (merged by
                       ``.type_tag``; priority orders them vs. built-ins)
    register(api)      callable for imperative registration; ``api`` is this
                       module (use api.add_step_decorator(cls) etc.)

Merged entries *override* core entries with the same name, mirroring the
reference's extension-wins semantics. Set ``TPUFLOW_DISABLE_EXTENSIONS=1``
to skip discovery. A broken extension is reported to stderr and skipped —
it never takes core down with it.
"""

import importlib
import os
import pkgutil
import sys
import traceback

from . import knobs

EXT_PKG = "metaflow_tpu_extensions"

# click commands contributed by extensions; cli.main() adds these to every
# flow's command group after the core commands.
CLI_COMMANDS = []

_loaded = False
_loaded_extensions = []
_failed_extensions = {}
# registries as they were before ANY extension merged — lets a forced
# re-scan (or a later disable) start from a clean core baseline
_core_snapshot = None


def add_step_decorator(cls):
    from . import plugins

    return plugins.register_step_decorator(cls)


def add_flow_decorator(cls):
    from . import plugins

    return plugins.register_flow_decorator(cls)


def add_storage_backend(name, cls):
    from .datastore.storage import STORAGE_BACKENDS

    STORAGE_BACKENDS[name] = cls
    return cls


def add_metadata_provider(name, cls):
    from .metadata import METADATA_PROVIDERS

    METADATA_PROVIDERS[name] = cls
    return cls


def add_cli_command(cmd):
    CLI_COMMANDS.append(cmd)
    return cmd


def add_serializer(serializer):
    from .datastore.serializers import register_serializer

    return register_serializer(serializer)


def _merge(mod):
    for cls in getattr(mod, "STEP_DECORATORS", []):
        add_step_decorator(cls)
    for cls in getattr(mod, "FLOW_DECORATORS", []):
        add_flow_decorator(cls)
    for name, cls in dict(getattr(mod, "STORAGE_BACKENDS", {})).items():
        add_storage_backend(name, cls)
    for name, cls in dict(getattr(mod, "METADATA_PROVIDERS", {})).items():
        add_metadata_provider(name, cls)
    for cmd in getattr(mod, "CLI_COMMANDS", []):
        add_cli_command(cmd)
    for serializer in getattr(mod, "SERIALIZERS", []):
        add_serializer(serializer)
    reg = getattr(mod, "register", None)
    if callable(reg):
        reg(sys.modules[__name__])


def loaded_extensions():
    """Names of successfully merged extension subpackages."""
    return list(_loaded_extensions)


def failed_extensions():
    """Map of extension name -> one-line error for broken extensions."""
    return dict(_failed_extensions)


def _registry_snapshot():
    from . import plugins
    from .datastore import serializers
    from .datastore.storage import STORAGE_BACKENDS
    from .metadata import METADATA_PROVIDERS

    return (
        dict(plugins.STEP_DECORATORS),
        dict(plugins.FLOW_DECORATORS),
        dict(STORAGE_BACKENDS),
        dict(METADATA_PROVIDERS),
        list(CLI_COMMANDS),
        list(serializers._SERIALIZERS),
    )


def _registry_restore(snap):
    from . import plugins
    from .datastore import serializers
    from .datastore.storage import STORAGE_BACKENDS
    from .metadata import METADATA_PROVIDERS

    steps, flows, storage, metadata, clis, serials = snap
    plugins.STEP_DECORATORS.clear()
    plugins.STEP_DECORATORS.update(steps)
    plugins.FLOW_DECORATORS.clear()
    plugins.FLOW_DECORATORS.update(flows)
    STORAGE_BACKENDS.clear()
    STORAGE_BACKENDS.update(storage)
    METADATA_PROVIDERS.clear()
    METADATA_PROVIDERS.update(metadata)
    CLI_COMMANDS[:] = clis
    serializers._SERIALIZERS[:] = serials
    serializers._BY_TAG.clear()
    serializers._BY_TAG.update({s.type_tag: s for s in serials})


def load_extensions(force=False):
    """Discover and merge all metaflow_tpu_extensions.* subpackages.

    Idempotent per-process unless force=True, which re-scans sys.path and
    re-merges every discovered extension (for tests that install an
    extension after import). A partially-merged broken extension is rolled
    back so "skipped" really means no trace in the registries.
    """
    global _loaded, _core_snapshot
    if _loaded and not force:
        return list(_loaded_extensions)
    _loaded = True
    if _core_snapshot is None:
        _core_snapshot = _registry_snapshot()
    if knobs.get_bool("TPUFLOW_DISABLE_EXTENSIONS"):
        # disabling after a previous load must also UNregister: reset to
        # the pre-extension baseline, not just report empty
        if _loaded_extensions:
            _registry_restore(_core_snapshot)
        del _loaded_extensions[:]
        _failed_extensions.clear()
        return []
    if force:
        # pick up extension roots added to sys.path after first import,
        # and re-merge everything from the clean core baseline (registries
        # may have been mutated by tests or earlier scans)
        importlib.invalidate_caches()
        sys.modules.pop(EXT_PKG, None)
        for modname in [
            m for m in sys.modules if m.startswith(EXT_PKG + ".")
        ]:
            sys.modules.pop(modname, None)
        if _loaded_extensions:
            _registry_restore(_core_snapshot)
        del _loaded_extensions[:]
        _failed_extensions.clear()
        # extension CLI commands re-merge below; dict registries re-merge
        # idempotently but this list would otherwise accumulate duplicates
        del CLI_COMMANDS[:]
    try:
        ext_pkg = importlib.import_module(EXT_PKG)
    except ImportError:
        return list(_loaded_extensions)
    for _finder, name, _ispkg in pkgutil.iter_modules(
        list(getattr(ext_pkg, "__path__", []))
    ):
        full = "%s.%s" % (EXT_PKG, name)
        if full in _loaded_extensions:
            continue
        snap = _registry_snapshot()
        try:
            mod = importlib.import_module(full)
            try:
                plug = importlib.import_module(full + ".plugins")
            except ModuleNotFoundError as ex:
                # only fall back when the plugins submodule itself is absent;
                # an import error *inside* plugins.py must surface as broken
                if ex.name != full + ".plugins":
                    raise
                plug = mod
            _merge(plug)
            _loaded_extensions.append(full)
            _failed_extensions.pop(full, None)
        except Exception as ex:
            _registry_restore(snap)
            _failed_extensions[full] = "%s: %s" % (type(ex).__name__, ex)
            sys.stderr.write(
                "[extensions] skipping broken extension %s (%s)\n"
                % (full, _failed_extensions[full])
            )
            if knobs.get_bool("TPUFLOW_DEBUG"):
                traceback.print_exc()
    return list(_loaded_extensions)
