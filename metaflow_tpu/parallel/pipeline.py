"""Pipeline parallelism via shard_map over the 'pipeline' mesh axis.

GPipe-style schedule (SURVEY.md §5.7 "pipeline via shard_map"): the layer
stack is split into S contiguous stages (the stacked-layer pytree's leading
axis is sharded over 'pipeline'); M microbatches stream through, activations
hop stage→stage with lax.ppermute over neighbouring ICI links. Total ticks =
M + S - 1; bubble fraction = (S-1)/(M+S-1).

MPMD-style per-stage programs (PAPERS.md: MPMD pipeline parallelism) are a
later optimization — this single-SPMD-program formulation lets XLA overlap
the ppermute with stage compute already.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def pipeline_apply(layer_fn, stage_params, x, mesh, num_microbatches,
                   axis_name="pipeline"):
    """Run x through all pipeline stages.

    layer_fn: (carry, layer_params) -> carry, applied per layer via scan
        inside each stage.
    stage_params: pytree whose leaves have leading dim n_layers, SHARDED on
        `axis_name` (n_layers % n_stages == 0).
    x: [B, ...] global batch (replicated across the pipeline axis);
        B % num_microbatches == 0.
    Returns y with x's shape.
    """
    n_stages = mesh.shape[axis_name]
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")

    def local(x_local, params_local):
        stage = jax.lax.axis_index(axis_name)
        B = x_local.shape[0]
        mb_size = B // num_microbatches
        microbatches = x_local.reshape((num_microbatches, mb_size)
                                       + x_local.shape[1:])

        def run_stage(act):
            out, _ = jax.lax.scan(
                lambda c, lp: (layer_fn(c, lp), None), act, params_local
            )
            return out

        perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        n_ticks = num_microbatches + n_stages - 1
        # mark the carries as varying over the pipeline axis (their values
        # genuinely differ per stage once the loop runs)
        outputs = jax.lax.pcast(
            jnp.zeros_like(microbatches), (axis_name,), to="varying"
        )
        buf = jax.lax.pcast(
            jnp.zeros((mb_size,) + x_local.shape[1:], x_local.dtype),
            (axis_name,), to="varying",
        )

        def tick(t, carry):
            buf, outputs = carry
            # stage 0 ingests microbatch t (when available)
            mb_idx = jnp.clip(t, 0, num_microbatches - 1)
            incoming = microbatches[mb_idx]
            buf = jnp.where(stage == 0,
                            jnp.where(t < num_microbatches, incoming, buf),
                            buf)
            buf = run_stage(buf)
            # last stage emits microbatch t - (S-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
            emit = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outputs = jnp.where(
                emit,
                outputs.at[out_idx].set(buf),
                outputs,
            )
            # hand activations to the next stage
            buf = jax.lax.ppermute(buf, axis_name, perm_fwd)
            return buf, outputs

        buf, outputs = jax.lax.fori_loop(0, n_ticks, tick, (buf, outputs))
        y_local = outputs.reshape(x_local.shape)
        # every stage returns a buffer; only the last stage's is real —
        # broadcast it so the output is replicated over the pipeline axis
        last = jax.lax.psum(
            jnp.where(stage == n_stages - 1, 1.0, 0.0) * 0 + (
                y_local * (stage == n_stages - 1)
            ),
            axis_name,
        )
        return last

    # params sharded over pipeline axis on the leading (layers) dim;
    # x replicated; output replicated
    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = _shard_map(
        local, mesh,
        in_specs=(P(), param_specs),
        out_specs=P(),
    )
    return fn(x, stage_params)


def pipelined_forward(model_layer_fn, params_layers, x, mesh,
                      num_microbatches=4, axis_name="pipeline"):
    """Convenience wrapper matching models' stacked-layer params."""
    return pipeline_apply(
        model_layer_fn, params_layers, x, mesh, num_microbatches, axis_name
    )
