"""Pipeline parallelism via shard_map over the 'pipeline' mesh axis.

GPipe-style schedule (SURVEY.md §5.7 "pipeline via shard_map"): the layer
stack is split into S contiguous stages (the stacked-layer pytree's leading
axis is sharded over 'pipeline'); M microbatches stream through, activations
hop stage→stage with lax.ppermute over neighbouring ICI links. Total ticks =
M + S - 1; bubble fraction = (S-1)/(M+S-1).

MPMD-style per-stage programs (PAPERS.md: MPMD pipeline parallelism) are a
later optimization — this single-SPMD-program formulation lets XLA overlap
the ppermute with stage compute already.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def pipeline_apply(layer_fn, stage_params, x, mesh, num_microbatches,
                   axis_name="pipeline"):
    """Run x through all pipeline stages.

    layer_fn: (carry, layer_params) -> carry, applied per layer via scan
        inside each stage.
    stage_params: pytree whose leaves have leading dim n_layers, SHARDED on
        `axis_name` (n_layers % n_stages == 0).
    x: [B, ...] global batch (replicated across the pipeline axis);
        B % num_microbatches == 0.
    Returns y with x's shape.
    """
    n_stages = mesh.shape[axis_name]
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")

    def local(x_local, params_local):
        stage = jax.lax.axis_index(axis_name)
        B = x_local.shape[0]
        mb_size = B // num_microbatches
        microbatches = x_local.reshape((num_microbatches, mb_size)
                                       + x_local.shape[1:])

        def run_stage(act):
            out, _ = jax.lax.scan(
                lambda c, lp: (layer_fn(c, lp), None), act, params_local
            )
            return out

        perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        n_ticks = num_microbatches + n_stages - 1
        # mark the carries as varying over the pipeline axis (their values
        # genuinely differ per stage once the loop runs)
        outputs = jax.lax.pcast(
            jnp.zeros_like(microbatches), (axis_name,), to="varying"
        )
        buf = jax.lax.pcast(
            jnp.zeros((mb_size,) + x_local.shape[1:], x_local.dtype),
            (axis_name,), to="varying",
        )

        def tick(t, carry):
            buf, outputs = carry
            # stage 0 ingests microbatch t (when available)
            mb_idx = jnp.clip(t, 0, num_microbatches - 1)
            incoming = microbatches[mb_idx]
            buf = jnp.where(stage == 0,
                            jnp.where(t < num_microbatches, incoming, buf),
                            buf)
            buf = run_stage(buf)
            # last stage emits microbatch t - (S-1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
            emit = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outputs = jnp.where(
                emit,
                outputs.at[out_idx].set(buf),
                outputs,
            )
            # hand activations to the next stage
            buf = jax.lax.ppermute(buf, axis_name, perm_fwd)
            return buf, outputs

        buf, outputs = jax.lax.fori_loop(0, n_ticks, tick, (buf, outputs))
        y_local = outputs.reshape(x_local.shape)
        # every stage returns a buffer; only the last stage's is real —
        # broadcast it so the output is replicated over the pipeline axis
        last = jax.lax.psum(
            jnp.where(stage == n_stages - 1, 1.0, 0.0) * 0 + (
                y_local * (stage == n_stages - 1)
            ),
            axis_name,
        )
        return last

    # params sharded over pipeline axis on the leading (layers) dim;
    # x replicated; output replicated
    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = _shard_map(
        local, mesh,
        in_specs=(P(), param_specs),
        out_specs=P(),
    )
    return fn(x, stage_params)


def pipelined_forward(model_layer_fn, params_layers, x, mesh,
                      num_microbatches=4, axis_name="pipeline"):
    """Convenience wrapper matching models' stacked-layer params."""
    return pipeline_apply(
        model_layer_fn, params_layers, x, mesh, num_microbatches, axis_name
    )


def pipeline_train_1f1b(layer_fn, loss_fn, stage_params, x, y, mesh,
                        num_microbatches, axis_name="pipeline"):
    """1F1B training schedule: loss + per-stage parameter gradients.

    Unlike differentiating through the GPipe loop (which holds every
    microbatch's activations until the flush), the one-forward-one-backward
    schedule starts each microbatch's backward as soon as the last stage
    finishes its forward, so live activation memory is bounded by the
    pipeline DEPTH (≈2S in-flight stage inputs), independent of the
    microbatch count M. Backward recomputes the stage forward from the
    saved stage input (activation checkpointing), the standard
    remat-in-pipeline trade.

    Lockstep formulation (one SPMD program): each cycle c has an F slot and
    a B slot. Stage i forwards microbatch c-i and backwards microbatch
    c-(2S-2-i); activations hop i→i+1 and cotangents hop i→i-1 via
    lax.ppermute each cycle. Total cycles M + 2(S-1); bubble matches
    non-interleaved 1F1B.

    layer_fn: (carry, layer_params) -> carry (scanned over the stage's
        local layers).
    loss_fn: (stage_output, targets) -> scalar mean loss (applied by the
        last stage per microbatch).
    stage_params: pytree, leaves stacked [n_layers, ...], sharded on
        `axis_name`.
    x: [B, ...] inputs, y: [B, ...] targets, both replicated over the
        pipeline axis; B % num_microbatches == 0.
    Returns (mean_loss, param_grads) with param_grads sharded like
    stage_params.
    """
    n_stages = dict(mesh.shape).get(axis_name, 1)
    M = num_microbatches
    if M < 1:
        raise ValueError("num_microbatches must be >= 1")

    if n_stages == 1:
        # degenerate pipeline: plain microbatched loss/grad, no collectives
        # (size-1 mesh axes are dropped by MeshSpec)
        def full_loss(params):
            mbs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            ybs = y.reshape((M, y.shape[0] // M) + y.shape[1:])

            def body(acc, mb_yb):
                mb, yb = mb_yb
                out, _ = jax.lax.scan(
                    lambda c, lp: (layer_fn(c, lp), None), mb, params
                )
                return acc + loss_fn(out.astype(jnp.float32), yb), None

            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                                    (mbs, ybs))
            return total / M

        return jax.value_and_grad(full_loss)(stage_params)

    def local(x_local, y_local, params_local):
        stage = jax.lax.axis_index(axis_name)
        S = n_stages
        B = x_local.shape[0]
        mb_size = B // M
        mbs = x_local.reshape((M, mb_size) + x_local.shape[1:])
        ybs = y_local.reshape((M, mb_size) + y_local.shape[1:])

        def run_stage(act, params):
            out, _ = jax.lax.scan(
                lambda c, lp: (layer_fn(c, lp), None), act, params
            )
            return out

        L = min(M, 2 * (S - 1) + 1) if S > 1 else 1  # live-input slots
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]

        def var(z):
            # mark as varying over the pipeline axis; no-op if already so
            # (zeros_like(params) inherits the params' annotation)
            try:
                if axis_name in jax.typeof(z).vma:
                    return z
            except (AttributeError, TypeError):
                pass
            return jax.lax.pcast(z, (axis_name,), to="varying")

        act_shape = (mb_size,) + x_local.shape[1:]
        state = dict(
            saved=var(jnp.zeros((L,) + act_shape, x_local.dtype)),
            fwd_buf=var(jnp.zeros(act_shape, x_local.dtype)),
            grad_buf=var(jnp.zeros(act_shape, jnp.float32)),
            pgrads=jax.tree.map(
                lambda p: var(jnp.zeros_like(p, jnp.float32)), params_local
            ),
            loss=var(jnp.zeros((), jnp.float32)),
        )

        def cycle(c, state):
            # ---- F slot: stage forwards microbatch c - stage ----
            m_f = c - stage
            f_active = jnp.logical_and(m_f >= 0, m_f < M)
            m_f_idx = jnp.clip(m_f, 0, M - 1)
            a_in = jnp.where(stage == 0, mbs[m_f_idx], state["fwd_buf"])
            slot = jnp.mod(m_f_idx, L)
            saved = jnp.where(
                f_active,
                state["saved"].at[slot].set(a_in),
                state["saved"],
            )
            a_out = run_stage(a_in, params_local)
            fwd_buf = jax.lax.ppermute(a_out, axis_name, perm_fwd)

            # ---- B slot: stage backwards microbatch c - (2S-2-stage) ----
            m_b = c - (2 * S - 2 - stage)
            b_active = jnp.logical_and(m_b >= 0, m_b < M)
            m_b_idx = jnp.clip(m_b, 0, M - 1)
            a_saved = saved[jnp.mod(m_b_idx, L)]
            out, pullback = jax.vjp(
                lambda a, p: run_stage(a, p), a_saved, params_local
            )
            # cotangent source: the last stage seeds from the loss, every
            # other stage consumes the cotangent arriving from stage+1
            loss_val, dloss_dout = jax.value_and_grad(loss_fn)(
                out.astype(jnp.float32), ybs[m_b_idx]
            )
            cot = jnp.where(
                stage == S - 1,
                dloss_dout.astype(out.dtype),
                state["grad_buf"].astype(out.dtype),
            )
            da, dp = pullback(cot)
            pgrads = jax.tree.map(
                lambda acc, g: acc
                + jnp.where(b_active, g.astype(jnp.float32), 0.0),
                state["pgrads"],
                dp,
            )
            loss = state["loss"] + jnp.where(
                jnp.logical_and(b_active, stage == S - 1), loss_val, 0.0
            )
            grad_buf = jax.lax.ppermute(
                da.astype(jnp.float32), axis_name, perm_bwd
            )
            return dict(saved=saved, fwd_buf=fwd_buf, grad_buf=grad_buf,
                        pgrads=pgrads, loss=loss)

        n_cycles = M + 2 * (S - 1)
        state = jax.lax.fori_loop(0, n_cycles, cycle, state)
        # only the last stage accumulated loss; share it with every stage
        mean_loss = jax.lax.psum(state["loss"], axis_name) / M
        pgrads = jax.tree.map(lambda g: g / M, state["pgrads"])
        return mean_loss, pgrads

    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = _shard_map(
        local, mesh,
        in_specs=(P(), P(), param_specs),
        out_specs=(P(), param_specs),
    )
    return fn(x, y, stage_params)
