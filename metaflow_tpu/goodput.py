"""Fleet-wide goodput ledger: account every chip-second in one taxonomy.

The scheduling objective every later subsystem optimizes ("goodput over
elastic capacity") needs a measurement substrate first: the subsystems
already emit the raw signals — `train.step` timers with per-component
stall breakdowns (training/metrics.py), `serve.prefill_chunk` /
`serve.decode_step` timers (serving/scheduler.py), `data.batch_wait`
(data/loader.py), checkpoint spans (training/checkpoint.py), and
`elastic.backoff` capacity parks (elastic/supervisor.py) — but nobody
could SUM them. This module derives a per-rank interval ledger from
those streams and rolls it up into a wall-clock-reconciled breakdown.

Taxonomy (pinned in tests/schema_validate.py::GOODPUT_CATEGORIES):

    productive_step     forward+backward compute inside a train step
    compile             XLA trace+compile (whole interval of a step that
                        grew the jit cache)
    input_stall         host blocked in next(iterator) / batch wait
    transfer_stall      MPMD stage blocked on the inter-stage transport
    update              optimizer update (diagnostic split-step mode)
    checkpoint_blocked  train loop blocked in the checkpoint snapshot
    restore_replay      recovery overhead: checkpoint restore + steps
                        re-done after an elastic resize / hang kill
    capacity_wait       parked attempts (chip-seconds the gang WOULD
                        have used while waiting for admissible capacity)
    serve_prefill       serving: chunked prefill device work
    serve_decode        serving: batched decode device work
    serve_idle          serving: scheduler span not covered by device
                        work (empty queue, admission gaps)
    actor_rollout       online loop: remote-fleet rollout batches (the
                        actor's chip-seconds; local-engine rollouts
                        already ride serve_prefill/serve_decode)
    unattributed        observed chip-time no category explains — an
                        explicit bucket, never silently dropped

Derivation model: records group into LANES keyed by
(step, task_id, attempt, rank) — one lane is one task attempt on one
rank, i.e. one chip's allocation. A lane's observed chip-time is the
span of its *work* timers (a timer's interval is [ts - ms, ts]); infra
envelopes (task.user_code, persist.*) are deliberately excluded so the
span measures chip occupancy, not host bookkeeping. Replayed work is
detected gang-level: a step record whose step_num does not exceed the
furthest step any earlier attempt of the same flow step reached is
work being re-done after a restore. Parked capacity (elastic.backoff
with waiting_for_capacity) contributes delay_s x world chip-seconds on
top of lane spans.

Reconciliation: sum(categories) must reach (1 - tolerance) of observed
chip-time; the remainder is the explicit `unattributed` bucket. The
dominant non-productive category names the run's loss verdict — the
run-level generalization of the INPUT-BOUND / PIPELINE-BOUND verdicts
`tpuflow metrics` prints per stage.

The same module renders OpenMetrics text (render_openmetrics) for the
/metrics endpoints on the replica server and fleet router, and hosts
the run-scope exporter (RunMetricsExporter) training gangs expose.
"""

import json
import threading

from . import telemetry

LEDGER_VERSION = 1
GOODPUT_PREFIX = "_telemetry/goodput"
RECONCILE_TOLERANCE = 0.05

PRODUCTIVE_STEP = "productive_step"
COMPILE = "compile"
INPUT_STALL = "input_stall"
TRANSFER_STALL = "transfer_stall"
UPDATE = "update"
CHECKPOINT_BLOCKED = "checkpoint_blocked"
RESTORE_REPLAY = "restore_replay"
CAPACITY_WAIT = "capacity_wait"
SERVE_PREFILL = "serve_prefill"
SERVE_DECODE = "serve_decode"
SERVE_IDLE = "serve_idle"
ACTOR_ROLLOUT = "actor_rollout"
UNATTRIBUTED = "unattributed"

CATEGORIES = (
    PRODUCTIVE_STEP, COMPILE, INPUT_STALL, TRANSFER_STALL, UPDATE,
    CHECKPOINT_BLOCKED, RESTORE_REPLAY, CAPACITY_WAIT,
    SERVE_PREFILL, SERVE_DECODE, SERVE_IDLE, ACTOR_ROLLOUT,
)

# chip-time spent doing the work the run exists for; everything else
# (incl. unattributed) is a loss category the verdict can name
PRODUCTIVE_CATEGORIES = (
    PRODUCTIVE_STEP, UPDATE, SERVE_PREFILL, SERVE_DECODE, ACTOR_ROLLOUT)


def _is_step_timer(rec):
    return (rec.get("type") == "timer"
            and rec.get("name", "").endswith(".step")
            and "step_num" in rec and "ms" in rec)


def _lane_key(rec):
    return (rec.get("step", ""), str(rec.get("task_id", "")),
            int(rec.get("attempt", 0)), int(rec.get("rank", 0)))


class _Lane(object):
    __slots__ = ("start", "end", "cats", "has_steps", "serve_busy",
                 "batch_wait_s", "snapshot_s", "kinds")

    def __init__(self):
        self.start = None
        self.end = None
        self.cats = {}
        self.has_steps = False
        self.serve_busy = 0.0
        self.batch_wait_s = 0.0
        self.snapshot_s = 0.0
        self.kinds = set()

    def work(self, ts, seconds):
        """Extend the lane's observed span by one work interval
        [ts - seconds, ts]."""
        t0 = ts - seconds
        self.start = t0 if self.start is None else min(self.start, t0)
        self.end = ts if self.end is None else max(self.end, ts)

    def add(self, category, seconds):
        if seconds > 0:
            self.cats[category] = self.cats.get(category, 0.0) + seconds

    @property
    def span(self):
        if self.start is None:
            return 0.0
        return max(0.0, self.end - self.start)


def derive_ledger(records, run_id=None, tolerance=RECONCILE_TOLERANCE):
    """Derive the goodput ledger from a run's telemetry records (the
    list read_run_records returns). Pure: no datastore access."""
    # pass 1 — replay horizon: the furthest step_num each flow step's
    # gang reached, per attempt. A later attempt's records at or below
    # an earlier attempt's horizon are work being re-done.
    reached = {}  # step_name -> {attempt: max step_num}
    for rec in records:
        if not _is_step_timer(rec):
            continue
        per = reached.setdefault(rec.get("step", ""), {})
        att = int(rec.get("attempt", 0))
        num = int(rec["step_num"])
        if num > per.get(att, -1):
            per[att] = num

    def _replay_horizon(step_name, attempt):
        per = reached.get(step_name, {})
        prior = [n for a, n in per.items() if a < attempt]
        return max(prior) if prior else None

    # pass 2 — attribute work timers into lanes
    lanes = {}
    parked = []
    capacity_wait_s = 0.0
    for rec in records:
        rtype = rec.get("type")
        name = rec.get("name", "")
        if rtype == "event":
            if name == "elastic.backoff":
                data = rec.get("data") or {}
                if data.get("waiting_for_capacity"):
                    delay = float(data.get("delay_s") or 0.0)
                    world = int(data.get("world") or 1)
                    parked.append({
                        "pathspec": data.get("pathspec", ""),
                        "attempt": int(data.get("attempt", 0)),
                        "delay_s": round(delay, 3),
                        "world": world,
                    })
                    capacity_wait_s += delay * max(1, world)
            continue
        if rtype != "timer" or "ms" not in rec:
            continue
        seconds = float(rec["ms"]) / 1000.0
        if seconds <= 0:
            continue
        ts = float(rec.get("ts", 0.0))
        if _is_step_timer(rec):
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.has_steps = True
            lane.kinds.add("train")
            data = rec.get("data") or {}
            horizon = _replay_horizon(rec.get("step", ""),
                                      int(rec.get("attempt", 0)))
            if horizon is not None and int(rec["step_num"]) <= horizon:
                lane.add(RESTORE_REPLAY, seconds)
            elif data.get("compile"):
                lane.add(COMPILE, seconds)
            else:
                stall = float(data.get("input_stall_ms") or 0.0) / 1000.0
                xfer = float(data.get("transfer_stall_ms") or 0.0) / 1000.0
                upd = float(data.get("optimizer_update_ms") or 0.0) / 1000.0
                lane.add(INPUT_STALL, min(stall, seconds))
                lane.add(TRANSFER_STALL, min(xfer, seconds))
                lane.add(UPDATE, min(upd, seconds))
                lane.add(PRODUCTIVE_STEP,
                         max(0.0, seconds - stall - xfer - upd))
        elif name == "serve.decode_step":
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("serve")
            lane.add(SERVE_DECODE, seconds)
            lane.serve_busy += seconds
        elif name == "serve.prefill_chunk":
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("serve")
            lane.add(SERVE_PREFILL, seconds)
            lane.serve_busy += seconds
        elif name == "data.batch_wait":
            # inside an instrumented train loop the wait already rides
            # the step record's input_stall_ms: attribute it only for
            # lanes that have no step records (resolved below)
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("train")
            lane.batch_wait_s += seconds
        elif name == "checkpoint.snapshot":
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("train")
            lane.snapshot_s += seconds
        elif name == "checkpoint.restore":
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("train")
            lane.add(RESTORE_REPLAY, seconds)
        elif name == "online.rollout":
            # the online ActorPool's remote-fleet batches: the fleet's
            # chip-seconds viewed from the supervisor lane (the actor
            # emits it ONLY on the remote path — a local engine's
            # rollouts already land in serve_* via the serve timers in
            # the same-process lane, and double-counting would break
            # reconciliation)
            lane = lanes.setdefault(_lane_key(rec), _Lane())
            lane.work(ts, seconds)
            lane.kinds.add("actor")
            lane.add(ACTOR_ROLLOUT, seconds)
        # any other timer (task.user_code, persist.*, distributed.*) is
        # host bookkeeping, not chip work: it extends neither the span
        # nor any category

    # pass 3 — per-lane resolution + rollup
    totals = dict.fromkeys(CATEGORIES, 0.0)
    totals[CAPACITY_WAIT] = capacity_wait_s
    lane_rows = []
    observed_s = capacity_wait_s
    wall_start = wall_end = None
    for key in sorted(lanes):
        lane = lanes[key]
        if lane.batch_wait_s and not lane.has_steps:
            lane.add(INPUT_STALL, lane.batch_wait_s)
        if lane.snapshot_s:
            # the snapshot lands INSIDE a step interval already counted
            # as productive: move it rather than double-count it
            lane.add(CHECKPOINT_BLOCKED, lane.snapshot_s)
            if lane.has_steps:
                prod = lane.cats.get(PRODUCTIVE_STEP, 0.0)
                moved = min(prod, lane.snapshot_s)
                if moved:
                    lane.cats[PRODUCTIVE_STEP] = prod - moved
        attributed = sum(lane.cats.values())
        if lane.serve_busy and not lane.has_steps:
            idle = max(0.0, lane.span - attributed)
            lane.add(SERVE_IDLE, idle)
            attributed += idle
        # a lane is occupied at least as long as its measured busy time
        # (span alone can undercount single-record lanes)
        lane_observed = max(lane.span, attributed)
        observed_s += lane_observed
        if lane.start is not None:
            wall_start = (lane.start if wall_start is None
                          else min(wall_start, lane.start))
            wall_end = (lane.end if wall_end is None
                        else max(wall_end, lane.end))
        for cat, sec in lane.cats.items():
            totals[cat] += sec
        step_name, task_id, attempt, rank = key
        kind = ("mixed" if len(lane.kinds) > 1
                else next(iter(lane.kinds), "train"))
        lane_rows.append({
            "step": step_name,
            "task_id": task_id,
            "attempt": attempt,
            "rank": rank,
            "kind": kind,
            "span_s": round(lane.span, 3),
            "observed_s": round(lane_observed, 3),
            "unattributed_s": round(lane_observed - attributed, 3),
            "categories": {c: round(s, 3)
                           for c, s in sorted(lane.cats.items()) if s > 0},
        })

    attributed_s = sum(totals.values())
    unattributed_s = max(0.0, observed_s - attributed_s)
    coverage = (attributed_s / observed_s) if observed_s > 0 else 1.0
    productive_s = sum(totals[c] for c in PRODUCTIVE_CATEGORIES)
    losses = {c: totals[c] for c in CATEGORIES
              if c not in PRODUCTIVE_CATEGORIES and totals[c] > 0}
    if unattributed_s > 0:
        losses[UNATTRIBUTED] = unattributed_s
    dominant = max(losses, key=losses.get) if losses else None
    return {
        "v": LEDGER_VERSION,
        "run_id": str(run_id) if run_id is not None else None,
        "wall_clock_s": round((wall_end - wall_start), 3)
        if wall_start is not None else 0.0,
        "observed_chip_s": round(observed_s, 3),
        "attributed_chip_s": round(attributed_s, 3),
        "unattributed_chip_s": round(unattributed_s, 3),
        "coverage": round(min(1.0, coverage), 4),
        "goodput_frac": round(productive_s / observed_s, 4)
        if observed_s > 0 else 0.0,
        "tolerance": tolerance,
        "reconciled": coverage >= (1.0 - tolerance),
        "categories": {c: round(totals[c], 3) for c in CATEGORIES},
        "dominant_loss": dominant,
        "dominant_loss_s": round(losses.get(dominant, 0.0), 3)
        if dominant else 0.0,
        "parked": parked,
        "lanes": lane_rows,
    }


# ---------------------------------------------------------------------------
# persistence: crash-safe ledger records under _telemetry/goodput/
# ---------------------------------------------------------------------------


def ledger_path(flow_datastore, run_id, name="ledger.json"):
    return flow_datastore.storage.path_join(
        flow_datastore.flow_name, str(run_id), GOODPUT_PREFIX, name)


def save_ledger(flow_datastore, run_id, ledger, name="ledger.json"):
    """Persist a derived ledger under the run's telemetry tree; returns
    the datastore-relative path (None on storage error — persisting a
    ledger must never fail the run it describes)."""
    path = ledger_path(flow_datastore, run_id, name)
    payload = json.dumps(ledger, sort_keys=True).encode("utf-8")
    try:
        flow_datastore.storage.save_bytes(
            [(path, payload)], overwrite=True)
    except Exception:
        return None
    return path


def load_ledger(flow_datastore, run_id, name="ledger.json"):
    """The persisted ledger, or None when none was saved."""
    path = ledger_path(flow_datastore, run_id, name)
    try:
        with flow_datastore.storage.load_bytes([path]) as loaded:
            for _path, local, _meta in loaded:
                if local is None:
                    return None
                with open(local, "rb") as f:
                    return json.loads(f.read().decode("utf-8"))
    except Exception:
        return None
    return None


def derive_run_ledger(flow_datastore, run_id, persist=False,
                      tolerance=RECONCILE_TOLERANCE):
    """Read a run's records, derive the ledger, optionally persist it."""
    records = telemetry.read_run_records(flow_datastore, run_id)
    ledger = derive_ledger(records, run_id=run_id, tolerance=tolerance)
    if persist:
        save_ledger(flow_datastore, run_id, ledger)
    return ledger


# ---------------------------------------------------------------------------
# OpenMetrics text format (stdlib-only writer + strict parser)
# ---------------------------------------------------------------------------

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")

_TYPES = ("gauge", "counter", "summary", "info", "unknown")


class Family(object):
    """One OpenMetrics metric family: a TYPE + HELP header and its
    samples. Counter samples get the mandatory `_total` suffix at
    render time; summary samples carry their quantile label."""

    def __init__(self, name, mtype, help_text=""):
        if mtype not in _TYPES:
            raise ValueError("bad metric type %r" % (mtype,))
        self.name = name
        self.mtype = mtype
        self.help_text = help_text
        self.samples = []  # (suffix, labels, value)

    def add(self, value, labels=None, suffix=None):
        if suffix is None:
            suffix = "_total" if self.mtype == "counter" else ""
        self.samples.append((suffix, dict(labels or {}), value))
        return self


def _escape_label(value):
    return (str(value).replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def _escape_help(value):
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value):
    if isinstance(value, bool):
        return "1" if value else "0"
    if value is None:
        return "0"
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_openmetrics(families):
    """Families -> OpenMetrics text (terminated by the mandatory
    `# EOF` line)."""
    lines = []
    for fam in families:
        lines.append("# TYPE %s %s" % (fam.name, fam.mtype))
        if fam.help_text:
            lines.append("# HELP %s %s"
                         % (fam.name, _escape_help(fam.help_text)))
        for suffix, labels, value in fam.samples:
            if labels:
                label_str = "{%s}" % ",".join(
                    "%s=\"%s\"" % (k, _escape_label(v))
                    for k, v in sorted(labels.items()))
            else:
                label_str = ""
            lines.append("%s%s%s %s" % (fam.name, suffix, label_str,
                                        _format_value(value)))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _parse_labels(text):
    """`k="v",k2="v2"` -> dict, with strict escape handling."""
    labels = {}
    i, n = 0, len(text)
    while i < n:
        j = text.index("=", i)
        key = text[i:j]
        if not key or not key.replace("_", "a").isalnum():
            raise ValueError("bad label name %r" % key)
        if j + 1 >= n or text[j + 1] != "\"":
            raise ValueError("label value must be quoted: %r" % text)
        i = j + 2
        buf = []
        while True:
            if i >= n:
                raise ValueError("unterminated label value in %r" % text)
            c = text[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ValueError("dangling escape in %r" % text)
                nxt = text[i + 1]
                buf.append({"\\": "\\", "\"": "\"", "n": "\n"}.get(nxt))
                if buf[-1] is None:
                    raise ValueError("bad escape \\%s" % nxt)
                i += 2
                continue
            if c == "\"":
                i += 1
                break
            buf.append(c)
            i += 1
        labels[key] = "".join(buf)
        if i < n:
            if text[i] != ",":
                raise ValueError("expected ',' between labels in %r"
                                 % text)
            i += 1
    return labels


def _sample_family(name, labels, families):
    """Resolve which declared family a sample name belongs to, per the
    OpenMetrics suffix rules for each type."""
    if name in families:
        fam = families[name]
        if fam["type"] == "counter":
            raise ValueError(
                "counter sample %r missing _total suffix" % name)
        if fam["type"] == "summary" and "quantile" not in labels:
            raise ValueError(
                "summary sample %r missing quantile label" % name)
        if fam["type"] == "info":
            raise ValueError("info sample %r missing _info suffix" % name)
        return name
    for suffix, types in (("_total", ("counter",)),
                          ("_created", ("counter", "summary")),
                          ("_count", ("summary",)),
                          ("_sum", ("summary",)),
                          ("_info", ("info",))):
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if base in families and families[base]["type"] in types:
                return base
    raise ValueError("sample %r matches no declared family" % name)


def parse_openmetrics(text):
    """Strict OpenMetrics text parser (the test oracle for the /metrics
    endpoints). Enforces: terminal `# EOF`, declared-before-use
    families, no duplicate or interleaved families, suffix rules
    (counters end in _total, info in _info, summaries carry quantile),
    parseable sample values, non-negative counters. Returns
    {family: {"type", "help", "samples": [(name, labels, value)]}}."""
    if not text.endswith("\n"):
        raise ValueError("must end with a newline")
    lines = text.split("\n")[:-1]
    if not lines or lines[-1] != "# EOF":
        raise ValueError("missing terminal # EOF line")
    families = {}
    order = []
    current = None
    for lineno, line in enumerate(lines[:-1], 1):
        if not line:
            raise ValueError("blank line %d not allowed" % lineno)
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 4 or parts[0] != "#" or parts[1] not in (
                    "TYPE", "HELP", "UNIT"):
                raise ValueError("bad comment line %d: %r"
                                 % (lineno, line))
            kind, name, rest = parts[1], parts[2], parts[3]
            if kind == "TYPE":
                if rest not in _TYPES:
                    raise ValueError("bad type %r (line %d)"
                                     % (rest, lineno))
                if name in families:
                    raise ValueError("duplicate family %r (line %d)"
                                     % (name, lineno))
                families[name] = {"type": rest, "help": "",
                                  "samples": []}
                order.append(name)
                current = name
            else:
                if name not in families or name != current:
                    raise ValueError(
                        "%s for undeclared/non-current family %r "
                        "(line %d)" % (kind, name, lineno))
                if kind == "HELP":
                    families[name]["help"] = rest
            continue
        # sample line: name[{labels}] value [timestamp]
        brace = line.find("{")
        labels = {}
        if brace >= 0:
            close = line.find("}", brace)
            if close < 0:
                raise ValueError("unclosed labels (line %d)" % lineno)
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:close])
            rest = line[close + 1:].strip()
        else:
            name, _, rest = line.partition(" ")
            rest = rest.strip()
        if not rest:
            raise ValueError("sample missing value (line %d)" % lineno)
        value_str = rest.split(" ")[0]
        try:
            value = float(value_str)
        except ValueError:
            raise ValueError("bad sample value %r (line %d)"
                             % (value_str, lineno))
        base = _sample_family(name, labels, families)
        if base != current:
            raise ValueError(
                "interleaved sample %r under family %r (line %d)"
                % (name, current, lineno))
        if families[base]["type"] == "counter" and value < 0:
            raise ValueError("negative counter %r (line %d)"
                             % (name, lineno))
        families[base]["samples"].append((name, labels, value))
    return families


# ---------------------------------------------------------------------------
# metric-family builders: one vocabulary, pinned in schema_validate.py
# ---------------------------------------------------------------------------


def scheduler_metric_families(stats):
    """Scheduler.stats() -> replica-scope metric families. Every value
    is read from the SAME stats dict /v1/stats serves, so the two
    surfaces cannot disagree."""
    fams = []

    def gauge(name, value, help_text="", labels=None):
        fams.append(Family(name, "gauge", help_text).add(value, labels))

    gauge("tpuflow_serve_queue_depth", stats["queue_depth"],
          "Requests waiting for a slot")
    gauge("tpuflow_serve_in_flight", stats["in_flight"],
          "Requests occupying slots")
    gauge("tpuflow_serve_slots", stats["slots"], "Decode slot capacity")
    gauge("tpuflow_serve_occupancy", stats["occupancy"],
          "Instantaneous slot occupancy")
    gauge("tpuflow_serve_mean_batch_occupancy",
          stats["mean_batch_occupancy"],
          "Mean decode-batch occupancy over all decode steps")
    gauge("tpuflow_serve_draining", bool(stats["draining"]),
          "1 while a graceful drain is in progress")
    gauge("tpuflow_serve_peak_in_flight", stats["peak_in_flight"],
          "High-water mark of concurrent requests")
    gauge("tpuflow_serve_max_context_tokens",
          stats["max_context_tokens"],
          "Largest prompt+max_new this engine admits")
    fams.append(
        Family("tpuflow_serve_requests", "counter",
               "Requests finished, by outcome")
        .add(stats["served"], {"outcome": "served"})
        .add(stats["cancelled"], {"outcome": "cancelled"}))
    fams.append(Family("tpuflow_serve_decode_steps", "counter",
                       "Batched decode steps executed")
                .add(stats["decode_steps"]))
    fams.append(Family("tpuflow_serve_iterations", "counter",
                       "Scheduler loop iterations")
                .add(stats["iterations"]))
    ttft = Family("tpuflow_serve_ttft_ms", "summary",
                  "Time to first token, rolling window")
    ttft.add(stats["p50_ttft_ms"] or 0.0, {"quantile": "0.5"})
    ttft.add(stats["p99_ttft_ms"] or 0.0, {"quantile": "0.99"})
    fams.append(ttft)
    itl = Family("tpuflow_serve_itl_ms", "summary",
                 "Inter-token latency, rolling window")
    itl.add(stats["p50_itl_ms"] or 0.0, {"quantile": "0.5"})
    itl.add(stats["p99_itl_ms"] or 0.0, {"quantile": "0.99"})
    fams.append(itl)
    prefix = stats.get("prefix_cache") or {}
    if prefix.get("enabled"):
        fams.append(
            Family("tpuflow_serve_prefix_lookups", "counter",
                   "Prefix-cache lookups, by result")
            .add(prefix["hits"], {"result": "hit"})
            .add(prefix["misses"], {"result": "miss"}))
        gauge("tpuflow_serve_prefix_hit_rate", prefix["hit_rate"],
              "Prefix-cache hit rate")
        gauge("tpuflow_serve_prefix_tokens_skipped_frac",
              prefix["prefill_tokens_skipped_frac"],
              "Fraction of prompt tokens served from cache")
    kv = stats.get("kv_pages") or {}
    if kv.get("enabled"):
        used = int(kv.get("pages_total", 0)) - int(kv.get("pages_free", 0))
        pages = Family("tpuflow_serve_kv_pages", "gauge",
                       "Paged-KV pool pages, by state")
        pages.add(used, {"state": "used"})
        pages.add(kv.get("pages_free", 0), {"state": "free"})
        pages.add(kv.get("shared_pages", 0), {"state": "shared"})
        pages.add(kv.get("cow_pages", 0), {"state": "cow"})
        fams.append(pages)
        gauge("tpuflow_serve_kv_occupancy", kv.get("occupancy", 0.0),
              "Paged-KV pool occupancy")
        fams.append(Family("tpuflow_serve_kv_exhausted", "counter",
                           "Admission stalls on page exhaustion")
                    .add(kv.get("exhausted", 0)))
    spec = stats.get("speculative") or {}
    if spec.get("enabled"):
        gauge("tpuflow_serve_spec_accept_rate",
              spec.get("accept_rate", 0.0),
              "Speculative-decode draft acceptance rate")
    goodput = stats.get("goodput") or {}
    if goodput:
        chip = Family("tpuflow_serve_goodput_seconds", "counter",
                      "Serving chip-seconds, by goodput category")
        chip.add(goodput.get("serve_prefill_s", 0.0),
                 {"category": SERVE_PREFILL})
        chip.add(goodput.get("serve_decode_s", 0.0),
                 {"category": SERVE_DECODE})
        chip.add(goodput.get("serve_idle_s", 0.0),
                 {"category": SERVE_IDLE})
        fams.append(chip)
    return fams


def fleet_metric_families(stats, healthz):
    """Fleet.stats()/healthz() -> router-scope metric families (the
    same dicts /v1/stats and /healthz serve)."""
    fams = []

    def gauge(name, value, help_text=""):
        fams.append(Family(name, "gauge", help_text).add(value))

    fams.append(
        Family("tpuflow_fleet_requests", "counter",
               "Fleet requests, by outcome")
        .add(stats["dispatched"], {"outcome": "dispatched"})
        .add(stats["completed"], {"outcome": "completed"})
        .add(stats["shed"], {"outcome": "shed"}))
    fams.append(Family("tpuflow_fleet_failovers", "counter",
                       "Requests retried on another replica")
                .add(stats["failovers"]))
    fams.append(Family("tpuflow_fleet_restarts", "counter",
                       "Replica processes restarted")
                .add(stats["restarts"]))
    fams.append(Family("tpuflow_fleet_prefill_handoffs", "counter",
                       "Disaggregated prefill->decode handoffs")
                .add(stats["prefill_handoffs"]))
    fams.append(Family("tpuflow_fleet_disagg_fallbacks", "counter",
                       "Disaggregated dispatches that fell back unified")
                .add(stats["disagg_fallbacks"]))
    fams.append(
        Family("tpuflow_fleet_scale_events", "counter",
               "Autoscaler actions, by direction")
        .add(stats["scale_outs"], {"direction": "out"})
        .add(stats["scale_ins"], {"direction": "in"}))
    gauge("tpuflow_fleet_inflight", stats["inflight"],
          "Requests in flight across the fleet")
    gauge("tpuflow_fleet_max_inflight", stats["max_inflight"],
          "Router admission limit")
    gauge("tpuflow_fleet_draining", bool(stats["draining"]),
          "1 while the fleet is draining")
    gauge("tpuflow_fleet_generation", stats["fleet_generation"],
          "Rollout generation of the newest replica")
    replicas = healthz.get("replicas") or []
    by_state = {}
    for rep in replicas:
        state = rep.get("state", "unknown")
        by_state[state] = by_state.get(state, 0) + 1
    reps = Family("tpuflow_fleet_replicas", "gauge",
                  "Replicas by lifecycle state")
    for state in sorted(by_state):
        reps.add(by_state[state], {"state": state})
    if not by_state:
        reps.add(0, {"state": "ready"})
    fams.append(reps)
    kv = healthz.get("kv_pages") or {}
    if kv.get("enabled"):
        used = int(kv.get("pages_total", 0)) - int(kv.get("pages_free", 0))
        pages = Family("tpuflow_fleet_kv_pages", "gauge",
                       "Fleet-wide paged-KV pages, by state")
        pages.add(used, {"state": "used"})
        pages.add(kv.get("pages_free", 0), {"state": "free"})
        pages.add(kv.get("shared_pages", 0), {"state": "shared"})
        pages.add(kv.get("cow_pages", 0), {"state": "cow"})
        fams.append(pages)
        gauge("tpuflow_fleet_kv_occupancy", kv.get("occupancy", 0.0),
              "Fleet-wide paged-KV occupancy")
    prefix = healthz.get("prefix_cache") or {}
    if prefix.get("enabled"):
        gauge("tpuflow_fleet_prefix_hit_rate",
              prefix.get("hit_rate", 0.0),
              "Mean prefix-cache hit rate over ready replicas")
    ttft = Family("tpuflow_fleet_ttft_ms", "summary",
                  "Worst ready-replica tail TTFT")
    ttft.add(healthz.get("p99_ttft_ms") or 0.0, {"quantile": "0.99"})
    fams.append(ttft)
    itl = Family("tpuflow_fleet_itl_ms", "summary",
                 "Worst ready-replica tail ITL")
    itl.add(healthz.get("p99_itl_ms") or 0.0, {"quantile": "0.99"})
    fams.append(itl)
    slo = healthz.get("slo") or {}
    gauge("tpuflow_fleet_slo_breached", bool(slo.get("breached")),
          "1 while any SLO rule is in breach")
    return fams


def ledger_metric_families(ledger):
    """Derived ledger -> run-scope metric families (the training-gang
    exporter's vocabulary)."""
    fams = []
    chip = Family("tpuflow_goodput_chip_seconds", "counter",
                  "Chip-seconds accounted, by goodput category")
    for cat in CATEGORIES:
        chip.add(ledger["categories"].get(cat, 0.0), {"category": cat})
    chip.add(ledger["unattributed_chip_s"], {"category": UNATTRIBUTED})
    fams.append(chip)
    fams.append(Family("tpuflow_goodput_coverage_ratio", "gauge",
                       "Attributed / observed chip-time")
                .add(ledger["coverage"]))
    fams.append(Family("tpuflow_goodput_fraction", "gauge",
                       "Productive chip-time / observed chip-time")
                .add(ledger["goodput_frac"]))
    fams.append(Family("tpuflow_goodput_wall_clock_seconds", "gauge",
                       "Wall-clock span of observed chip work")
                .add(ledger["wall_clock_s"]))
    lanes = Family("tpuflow_goodput_lanes", "gauge",
                   "Observed lanes (task-attempt-rank), by kind")
    by_kind = {}
    for lane in ledger["lanes"]:
        by_kind[lane["kind"]] = by_kind.get(lane["kind"], 0) + 1
    for kind in sorted(by_kind):
        lanes.add(by_kind[kind], {"kind": kind})
    if not by_kind:
        lanes.add(0, {"kind": "train"})
    fams.append(lanes)
    return fams


# ---------------------------------------------------------------------------
# run-scope exporter: a /metrics listener for training gangs
# ---------------------------------------------------------------------------


class RunMetricsExporter(object):
    """Scrape target for a training run: every GET /metrics re-derives
    the ledger from the run's persisted telemetry (records only append,
    so counter semantics hold across scrapes)."""

    def __init__(self, flow_datastore, run_id, host="127.0.0.1", port=0):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "tpuflow-goodput/1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    try:
                        body = exporter.render().encode("utf-8")
                    except Exception as ex:
                        body = json.dumps({"error": str(ex)}).encode()
                        self.send_response(500)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     OPENMETRICS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = json.dumps({"error": "not found"}).encode()
                self.send_response(404)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._fds = flow_datastore
        self.run_id = str(run_id)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    def render(self):
        ledger = derive_run_ledger(self._fds, self.run_id)
        return render_openmetrics(ledger_metric_families(ledger))

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="tpuflow-goodput-exporter", daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
