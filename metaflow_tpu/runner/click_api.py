"""Typed reflection of a flow's CLI for the programmatic API.

Reference behavior: metaflow/runner/click_api.py — Runner methods are
derived from the click command tree, so a new CLI option is immediately a
valid Runner kwarg and a typo'd kwarg fails fast with the valid choices.

Mechanism here: import the flow file as a module (the `if __name__ ==
'__main__'` guard keeps the CLI from firing), instantiate its FlowSpec
subclass with use_cli=False, and build the real click group via
cli.make_cli — then translate validated kwargs into argv for the
subprocess. If the flow file cannot be imported in-process (heavy imports,
import-time side effects), reflection degrades to permissive passthrough:
kwargs map to --kebab-case options unvalidated, preserving the old Runner
behavior instead of failing.
"""

import importlib.util
import os
import sys
import uuid

from ..exception import TpuFlowException


class UnknownCLIOption(TpuFlowException):
    headline = "Unknown option"


def load_flow_instance(flow_file):
    """Import a flow file and return its FlowSpec instance (use_cli=False)."""
    from ..flowspec import FlowSpec

    modname = "tpuflow_reflected_%s" % uuid.uuid4().hex[:8]
    spec = importlib.util.spec_from_file_location(modname, flow_file)
    if spec is None or spec.loader is None:
        raise TpuFlowException("Cannot import flow file %s" % flow_file)
    module = importlib.util.module_from_spec(spec)
    sys.modules[modname] = module
    try:
        spec.loader.exec_module(module)
        candidates = [
            obj
            for obj in vars(module).values()
            if isinstance(obj, type)
            and issubclass(obj, FlowSpec)
            and obj is not FlowSpec
            and obj.__module__ == modname
        ]
        if not candidates:
            raise TpuFlowException(
                "No FlowSpec subclass found in %s" % flow_file
            )
        if len(candidates) > 1:
            raise TpuFlowException(
                "Multiple FlowSpec subclasses in %s: %s"
                % (flow_file, ", ".join(c.__name__ for c in candidates))
            )
        # instantiate AND force the graph build while still registered:
        # graph construction inspects the class source, which resolves
        # through sys.modules — after the pop it would raise TypeError
        flow = candidates[0](use_cli=False)
        flow._graph  # noqa: B018 — builds + caches the AST graph
        return flow
    finally:
        # reflection only needs the built flow object; leaving the uuid
        # name in sys.modules would leak one flow module per Runner for
        # the life of the process
        sys.modules.pop(modname, None)


class _ParamSpec(object):
    def __init__(self, click_param):
        self.name = click_param.name
        self.opt = max(click_param.opts, key=len)  # the --long form
        self.multiple = getattr(click_param, "multiple", False)
        self.is_flag = getattr(click_param, "is_flag", False)
        self.nargs = getattr(click_param, "nargs", 1)
        self.is_argument = click_param.param_type_name == "argument"
        self.secondary = [
            o for o in getattr(click_param, "secondary_opts", [])
        ]

    def to_argv(self, value):
        if self.is_argument:
            return [str(value)]
        if self.is_flag:
            if value:
                return [self.opt]
            if self.secondary:
                return [max(self.secondary, key=len)]
            return []
        values = (
            list(value)
            if self.multiple and isinstance(value, (list, tuple))
            else [value]
        )
        argv = []
        for v in values:
            if self.nargs > 1:
                if not isinstance(v, (list, tuple)) or len(v) != self.nargs:
                    raise UnknownCLIOption(
                        "Option %s takes %d values per occurrence; got %r"
                        % (self.opt, self.nargs, v)
                    )
                argv += [self.opt] + [str(x) for x in v]
            else:
                argv += [self.opt, str(v)]
        return argv


class CommandSpec(object):
    def __init__(self, click_command):
        self.name = click_command.name
        self.params = {}
        self.arguments = []
        self.aliases = {}
        for p in click_command.params:
            ps = _ParamSpec(p)
            if ps.is_argument:
                self.arguments.append(ps)
            else:
                self.params[ps.name] = ps
        # options with a renamed click param ('--namespace', 'user_namespace')
        # also accept the kwarg spelled like the option itself
        for ps in self.params.values():
            opt_name = ps.opt.lstrip("-").replace("-", "_")
            if opt_name != ps.name and opt_name not in self.params:
                self.aliases[opt_name] = ps.name

    def build_argv(self, kwargs, positional=()):
        argv = [str(a) for a in positional]
        resolved = {
            self.aliases.get(name, name): value
            for name, value in kwargs.items()
        }
        unknown = sorted(set(resolved) - set(self.params))
        if unknown:
            raise UnknownCLIOption(
                "Unknown option(s) for '%s': %s. Valid options: %s"
                % (
                    self.name,
                    ", ".join(unknown),
                    ", ".join(sorted(set(self.params) | set(self.aliases))),
                )
            )
        for name, value in resolved.items():
            if value is None:
                continue
            argv += self.params[name].to_argv(value)
        return argv


class FlowCLIReflection(object):
    """Lazily-built view of a flow file's CLI command tree."""

    def __init__(self, flow_file):
        self.flow_file = os.path.abspath(flow_file)
        self._group = None
        self._failed = None

    def _load(self):
        if self._group is not None or self._failed is not None:
            return
        try:
            from ..cli import CliState, make_cli

            flow = load_flow_instance(self.flow_file)
            self._group = make_cli(flow, CliState(flow))
        except Exception as ex:
            self._failed = ex

    @property
    def available(self):
        self._load()
        return self._group is not None

    def command_names(self):
        self._load()
        if not self._group:
            return []
        return sorted(self._group.commands)

    def top_level(self):
        self._load()
        return CommandSpec(self._group) if self._group else None

    def command(self, name):
        self._load()
        if not self._group:
            return None
        # nested groups ('tag add', 'argo-workflows create') via space-path
        node = self._group
        for part in name.split():
            cmd = node.commands.get(part) if hasattr(node, "commands") else None
            if cmd is None:
                return None
            node = cmd
        return CommandSpec(node)

    def build_command_argv(self, command, kwargs, positional=()):
        """Validated argv for `command` (without interpreter/flow file);
        permissive passthrough when reflection is unavailable."""
        spec = self.command(command) if self.available else None
        if spec is None:
            return (
                list(command.split())
                + [str(a) for a in positional]
                + _permissive_argv(kwargs)
            )
        return list(command.split()) + spec.build_argv(kwargs, positional)

    def build_top_level_argv(self, kwargs):
        spec = self.top_level() if self.available else None
        if spec is None:
            return _permissive_argv(kwargs)
        return spec.build_argv(kwargs)


def _permissive_argv(kwargs):
    """Unvalidated kwargs → --kebab-case argv (reflection-unavailable
    fallback, the pre-reflection Runner behavior)."""
    argv = []
    for k, v in kwargs.items():
        if v is None:
            continue
        key = "--" + k.replace("_", "-")
        if isinstance(v, bool):
            if v:
                argv.append(key)
        elif isinstance(v, (list, tuple)):
            for item in v:
                argv += [key, str(item)]
        else:
            argv += [key, str(v)]
    return argv
