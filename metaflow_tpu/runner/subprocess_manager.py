"""Async subprocess supervision for the programmatic API.

Reference behavior: metaflow/runner/subprocess_manager.py — every Runner
subprocess is owned by a manager that can await it with a timeout, stream
its logs live, and kill it with TERM→KILL escalation; logs always land in
files so they survive the process and can be tailed after the fact.

Implementation: asyncio (create_subprocess_exec) on a dedicated daemon
event-loop thread, so both `async` callers and plain synchronous code get
the same supervision. Log files live under a per-command temp dir.
"""

import asyncio
import os
import shutil
import signal
import tempfile
import threading
import time


class _LoopThread(object):
    """A single background asyncio loop shared by all managers in-process."""

    _lock = threading.Lock()
    _instance = None

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, name="tpuflow-subproc", daemon=True
        )
        self.thread.start()

    @classmethod
    def get(cls):
        with cls._lock:
            if cls._instance is None or not cls._instance.thread.is_alive():
                cls._instance = cls()
            return cls._instance

    def submit(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout=timeout)


class CommandManager(object):
    """One supervised command: spawn, wait, stream logs, kill."""

    def __init__(self, command, env=None, cwd=None):
        self.command = [str(c) for c in command]
        self.env = env
        self.cwd = cwd
        self.process = None
        self.returncode = None
        self.timeout_expired = False
        self.log_dir = tempfile.mkdtemp(prefix="tpuflow_cmd_")
        self.log_files = {
            "stdout": os.path.join(self.log_dir, "stdout.log"),
            "stderr": os.path.join(self.log_dir, "stderr.log"),
        }
        self._pumps = []

    # -- async core ---------------------------------------------------------

    async def start(self):
        if self.process is not None:
            raise RuntimeError("command already started")
        self.process = await asyncio.create_subprocess_exec(
            *self.command,
            env=self.env,
            cwd=self.cwd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            start_new_session=True,  # own process group: kill() reaps children
        )
        for name in ("stdout", "stderr"):
            self._pumps.append(
                asyncio.ensure_future(self._pump(name))
            )
        return self.process.pid

    async def _pump(self, name):
        stream = getattr(self.process, name)
        with open(self.log_files[name], "ab", buffering=0) as sink:
            while True:
                chunk = await stream.read(64 * 1024)
                if not chunk:
                    break
                sink.write(chunk)

    async def wait_async(self, timeout=None):
        """Wait for exit; on timeout, kill (TERM→KILL) and mark expired."""
        try:
            await asyncio.wait_for(self.process.wait(), timeout=timeout)
        except asyncio.TimeoutError:
            self.timeout_expired = True
            await self.kill_async()
        await asyncio.gather(*self._pumps, return_exceptions=True)
        self.returncode = self.process.returncode
        return self.returncode

    async def kill_async(self, termination_timeout=5):
        """SIGTERM the process group; escalate to SIGKILL after the grace."""
        if self.process is None or self.process.returncode is not None:
            return
        try:
            os.killpg(os.getpgid(self.process.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        try:
            await asyncio.wait_for(
                self.process.wait(), timeout=termination_timeout
            )
        except asyncio.TimeoutError:
            try:
                os.killpg(os.getpgid(self.process.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            await self.process.wait()

    async def stream_log_async(self, name="stdout", poll=0.1):
        """Async-iterate log lines live until the process exits and the
        file is fully drained (including a final unterminated line)."""
        path = self.log_files[name]
        pos = 0

        def read_from(pos, final):
            lines = []
            if os.path.exists(path):
                with open(path, "rb") as f:
                    f.seek(pos)
                    for line in f:
                        if line.endswith(b"\n") or final:
                            pos += len(line)
                            lines.append(
                                line.decode("utf-8", errors="replace")
                            )
                        else:
                            break  # partial line; re-read next poll
            return pos, lines

        while True:
            running = (
                self.process is not None
                and self.process.returncode is None
            )
            pos, lines = read_from(pos, final=False)
            for line in lines:
                yield line
            if not running:
                break
            await asyncio.sleep(poll)
        # the child exited, but the pump tasks may still be flushing the
        # last pipe chunks into the file — wait for them, then drain fully
        if self._pumps:
            await asyncio.gather(*self._pumps, return_exceptions=True)
        _pos, lines = read_from(pos, final=True)
        for line in lines:
            yield line

    # -- sync facade --------------------------------------------------------

    def run(self, timeout=None):
        """Start + wait synchronously; returns the exit code."""
        loop = _LoopThread.get()
        loop.submit(self.start())
        return loop.submit(self.wait_async(timeout=timeout))

    def spawn(self):
        """Start without waiting; returns the pid."""
        return _LoopThread.get().submit(self.start())

    def wait_future(self, timeout=None):
        """Begin waiting (with timeout-kill semantics) without blocking;
        returns a concurrent.futures.Future of the exit code — lets a
        caller stream logs while the deadline is enforced."""
        loop = _LoopThread.get()
        return asyncio.run_coroutine_threadsafe(
            self.wait_async(timeout=timeout), loop.loop
        )

    def wait(self, timeout=None):
        # wait_async owns timeout handling (incl. kill); no outer deadline
        return _LoopThread.get().submit(
            self.wait_async(timeout=timeout)
        )

    def kill(self, termination_timeout=5):
        return _LoopThread.get().submit(
            self.kill_async(termination_timeout=termination_timeout)
        )

    def stream_log(self, name="stdout", poll=0.1):
        """Synchronous generator over live log lines."""
        agen = self.stream_log_async(name, poll=poll)
        loop = _LoopThread.get()
        while True:
            try:
                yield loop.submit(agen.__anext__())
            except StopAsyncIteration:
                return

    def log_contents(self, name="stdout"):
        path = self.log_files[name]
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode("utf-8", errors="replace")

    @property
    def running(self):
        return (
            self.process is not None and self.process.returncode is None
        )

    def cleanup(self):
        shutil.rmtree(self.log_dir, ignore_errors=True)


class SubprocessManager(object):
    """Owns a set of CommandManagers; kills them all on exit/cleanup."""

    def __init__(self):
        self.commands = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cleanup()
        return False

    def run_command(self, command, env=None, cwd=None, timeout=None):
        cm = self.spawn_command(command, env=env, cwd=cwd)
        cm.wait(timeout=timeout)
        return cm

    def spawn_command(self, command, env=None, cwd=None):
        cm = CommandManager(command, env=env, cwd=cwd)
        pid = cm.spawn()
        self.commands[pid] = cm
        return cm

    def get(self, pid):
        return self.commands.get(pid)

    def cleanup(self, kill_running=True):
        for cm in list(self.commands.values()):
            if kill_running and cm.running:
                try:
                    cm.kill()
                except Exception:
                    pass
            cm.cleanup()
        self.commands.clear()
