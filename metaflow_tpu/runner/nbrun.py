"""Notebook ergonomics: run a flow defined in a notebook cell.

Reference behavior: metaflow/runner/nbrun.py (NBRunner) — the flow class is
defined interactively; we materialize it to a temp .py file and drive the
normal Runner machinery, so the notebook flow behaves exactly like a file
flow (subprocess tasks, datastore, client API).
"""

import inspect
import os
import tempfile

from ..exception import TpuFlowException
from . import Runner


DEFAULT_PRELUDE = "import metaflow_tpu\nfrom metaflow_tpu import *\n"


def materialize_flow(flow_cls, prelude=None):
    """Write a notebook-defined flow class to a runnable .py file; returns
    (tempdir, flow_file)."""
    try:
        source = inspect.getsource(flow_cls)
    except (OSError, TypeError):
        raise TpuFlowException(
            "Could not get the source of %r — define the flow class in "
            "its own cell." % flow_cls
        )
    tmpdir = tempfile.mkdtemp(prefix="tpuflow_nb_")
    flow_file = os.path.join(tmpdir, "%s.py" % flow_cls.__name__)
    with open(flow_file, "w") as f:
        f.write(prelude or DEFAULT_PRELUDE)
        f.write("\n")
        f.write(source)
        f.write(
            "\n\nif __name__ == '__main__':\n    %s()\n"
            % flow_cls.__name__
        )
    return tmpdir, flow_file


class NBRunner(object):
    def __init__(self, flow_cls, prelude=None, env=None, **top_level_kwargs):
        self._dir, flow_file = materialize_flow(flow_cls, prelude)
        self._runner = Runner(flow_file, env=env, **top_level_kwargs)

    def run(self, **params):
        return self._runner.run(**params)

    def async_run(self, **params):
        return self._runner.async_run(**params)

    def cleanup(self):
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)


class NBDeployer(object):
    """Deploy a notebook-defined flow to a production orchestrator
    (reference: metaflow/runner/nbdeploy.py):

        NBDeployer(MyFlow).argo_workflows(image=...).create()
    """

    def __init__(self, flow_cls, prelude=None, env=None, **kwargs):
        from .deployer import Deployer

        self._dir, flow_file = materialize_flow(flow_cls, prelude)
        self._deployer = Deployer(flow_file, env=env, **kwargs)

    def argo_workflows(self, **kwargs):
        return self._deployer.argo_workflows(**kwargs)

    def cleanup(self):
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)
