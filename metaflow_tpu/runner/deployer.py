"""Deployer: programmatic deployment to production orchestrators.

Reference behavior: metaflow/runner/deployer.py:99 —
`Deployer('flow.py').argo_workflows().create()` returns a DeployedFlow.
Compilation happens via the flow's own CLI (`argo-workflows create
--only-json`); applying to a cluster is the caller's `kubectl apply` (no
cluster access is assumed here).
"""

import os
import subprocess
import sys

from ..exception import TpuFlowException


class DeployedFlow(object):
    def __init__(self, name, manifests_yaml):
        self.name = name
        self.manifests = manifests_yaml

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.manifests)
        return path

    def trigger(self, **kwargs):
        raise TpuFlowException(
            "Triggering needs cluster access: kubectl apply the manifests "
            "and submit via 'argo submit --from workflowtemplate/%s'."
            % self.name
        )


class ArgoWorkflowsDeployer(object):
    def __init__(self, deployer, image=None, k8s_namespace="default",
                 datastore=None, datastore_root=None):
        self._deployer = deployer
        self._image = image
        self._namespace = k8s_namespace
        self._datastore = datastore
        self._datastore_root = datastore_root

    def create(self, do_package=False):
        top = []
        if self._datastore:
            top += ["--datastore", self._datastore]
        if self._datastore_root:
            top += ["--datastore-root", self._datastore_root]
        args = [
            sys.executable,
            self._deployer.flow_file,
        ] + top + [
            "argo-workflows",
            "create",
            "--only-json",
            "--k8s-namespace", self._namespace,
        ]
        if self._image:
            args += ["--image", self._image]
        if do_package:
            args += ["--package"]
        proc = subprocess.run(args, capture_output=True, text=True,
                              env=self._deployer.env_with_defaults())
        if proc.returncode != 0:
            raise TpuFlowException(
                "argo-workflows create failed:\n%s" % proc.stderr
            )
        name = None
        for line in proc.stdout.split("\n"):
            if line.strip().startswith("name:") and name is None:
                name = line.split(":", 1)[1].strip()
        return DeployedFlow(name or "unknown", proc.stdout)


class Deployer(object):
    def __init__(self, flow_file, env=None, **kwargs):
        self.flow_file = os.path.abspath(flow_file)
        if not os.path.exists(self.flow_file):
            raise TpuFlowException("Flow file %s not found" % flow_file)
        self.env = env or {}

    def env_with_defaults(self):
        merged = dict(os.environ)
        merged.update({k: str(v) for k, v in self.env.items()})
        return merged

    def argo_workflows(self, image=None, k8s_namespace="default",
                       datastore=None, datastore_root=None):
        return ArgoWorkflowsDeployer(self, image=image,
                                     k8s_namespace=k8s_namespace,
                                     datastore=datastore,
                                     datastore_root=datastore_root)
