"""Deployer: programmatic deployment to production orchestrators.

Reference behavior: metaflow/runner/deployer.py:99 —
`Deployer('flow.py').argo_workflows().create()` returns a DeployedFlow and
`.trigger()` a TriggeredRun. Compilation happens via the flow's own CLI
(`argo-workflows create --only-json`); cluster interaction goes through
kubectl (override the binary with TPUFLOW_KUBECTL — tests use a fake, the
same pattern as the gcloud TPU launcher).
"""

import json
import os
import subprocess
import sys

from .. import knobs
from ..exception import TpuFlowException


def _kubectl():
    return knobs.get_str("TPUFLOW_KUBECTL")


class TriggeredRun(object):
    """A workflow submitted from a deployed template."""

    def __init__(self, name, workflow_name, namespace):
        self.name = name
        self.workflow_name = workflow_name
        self.namespace = namespace
        # the Argo compiler derives every pod's run id this way (RUN_ID)
        self.run_id = "argo-%s" % workflow_name

    def status(self):
        proc = subprocess.run(
            [_kubectl(), "get", "workflow", self.workflow_name,
             "-n", self.namespace, "-o", "json"],
            capture_output=True, text=True, stdin=subprocess.DEVNULL,
        )
        if proc.returncode != 0:
            raise TpuFlowException(
                "kubectl get workflow failed:\n%s" % proc.stderr)
        return json.loads(proc.stdout).get("status", {}).get(
            "phase", "Unknown")


class DeployedFlow(object):
    def __init__(self, name, manifests_yaml, namespace="default",
                 parameters=None):
        self.name = name
        self.manifests = manifests_yaml
        self.namespace = namespace
        self._parameters = parameters or {}

    def save(self, path):
        with open(path, "w") as f:
            f.write(self.manifests)
        return path

    def apply(self):
        """kubectl-apply the compiled manifests to the cluster."""
        proc = subprocess.run(
            [_kubectl(), "apply", "-n", self.namespace, "-f", "-"],
            input=self.manifests, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise TpuFlowException("kubectl apply failed:\n%s" % proc.stderr)
        return self

    def trigger_manifest(self, **parameters):
        """The submittable Workflow referencing the deployed template —
        usable directly (`... | kubectl create -f -`) without this API."""
        params = dict(self._parameters)
        params.update(parameters)
        manifest = {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {"generateName": "%s-" % self.name,
                         "namespace": self.namespace},
            "spec": {"workflowTemplateRef": {"name": self.name}},
        }
        if params:
            manifest["spec"]["arguments"] = {"parameters": [
                {"name": k.replace("_", "-"), "value": json.dumps(v)}
                for k, v in params.items()
            ]}
        return manifest

    def trigger(self, **parameters):
        """Submit one run of the deployed template; returns a TriggeredRun.

        Needs kubectl + cluster access (point TPUFLOW_KUBECTL elsewhere to
        fake it); without them, use trigger_manifest() and submit however
        your cluster is reached."""
        manifest = self.trigger_manifest(**parameters)
        proc = subprocess.run(
            [_kubectl(), "create", "-n", self.namespace, "-f", "-",
             "-o", "json"],
            input=json.dumps(manifest), capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise TpuFlowException(
                "workflow submit failed (is kubectl configured? "
                "TPUFLOW_KUBECTL overrides the binary):\n%s" % proc.stderr
            )
        created = json.loads(proc.stdout)
        return TriggeredRun(
            self.name, created["metadata"]["name"], self.namespace
        )


class ArgoWorkflowsDeployer(object):
    def __init__(self, deployer, image=None, k8s_namespace="default",
                 datastore=None, datastore_root=None):
        self._deployer = deployer
        self._image = image
        self._namespace = k8s_namespace
        self._datastore = datastore
        self._datastore_root = datastore_root

    def create(self, do_package=False):
        top = []
        if self._datastore:
            top += ["--datastore", self._datastore]
        if self._datastore_root:
            top += ["--datastore-root", self._datastore_root]
        args = [
            sys.executable,
            self._deployer.flow_file,
        ] + top + [
            "argo-workflows",
            "create",
            "--only-json",
            "--k8s-namespace", self._namespace,
        ]
        if self._image:
            args += ["--image", self._image]
        if do_package:
            args += ["--package"]
        proc = subprocess.run(args, capture_output=True, text=True,
                              env=self._deployer.env_with_defaults())
        if proc.returncode != 0:
            raise TpuFlowException(
                "argo-workflows create failed:\n%s" % proc.stderr
            )
        name = None
        for line in proc.stdout.split("\n"):
            if line.strip().startswith("name:") and name is None:
                name = line.split(":", 1)[1].strip()
        return DeployedFlow(name or "unknown", proc.stdout,
                            namespace=self._namespace)


class Deployer(object):
    def __init__(self, flow_file, env=None, **kwargs):
        self.flow_file = os.path.abspath(flow_file)
        if not os.path.exists(self.flow_file):
            raise TpuFlowException("Flow file %s not found" % flow_file)
        self.env = env or {}

    def env_with_defaults(self):
        merged = dict(os.environ)
        merged.update({k: str(v) for k, v in self.env.items()})
        return merged

    def argo_workflows(self, image=None, k8s_namespace="default",
                       datastore=None, datastore_root=None):
        return ArgoWorkflowsDeployer(self, image=image,
                                     k8s_namespace=k8s_namespace,
                                     datastore=datastore,
                                     datastore_root=datastore_root)
