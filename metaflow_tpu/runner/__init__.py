"""Programmatic API: Runner shells out to the flow CLI and attaches a client
Run object (reference behavior: metaflow/runner/metaflow_runner.py:305)."""

import os
import subprocess
import sys
import tempfile
import time

from ..client import Run
from ..exception import TpuFlowException


class ExecutingRun(object):
    """Result of Runner.run(): the subprocess + the client Run object."""

    def __init__(self, command, returncode, run, stdout, stderr):
        self.command = command
        self.returncode = returncode
        self.run = run
        self.stdout = stdout
        self.stderr = stderr

    @property
    def status(self):
        return "successful" if self.returncode == 0 else "failed"


class Runner(object):
    """Run a flow file programmatically:

        with Runner('flow.py') as runner:
            result = runner.run(alpha=0.5)
            print(result.run.data.x)
    """

    def __init__(self, flow_file, show_output=False, env=None, cwd=None,
                 **top_level_kwargs):
        self.flow_file = os.path.abspath(flow_file)
        if not os.path.exists(self.flow_file):
            raise TpuFlowException("Flow file %s not found" % flow_file)
        self.show_output = show_output
        self.env = env or {}
        self.cwd = cwd
        self.top_level_kwargs = top_level_kwargs

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _top_level_args(self):
        args = []
        for k, v in self.top_level_kwargs.items():
            key = "--" + k.replace("_", "-")
            if isinstance(v, bool):
                if v:
                    args.append(key)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    args.extend([key, str(item)])
            else:
                args.extend([key, str(v)])
        return args

    def _execute(self, command_args, timeout=None):
        with tempfile.TemporaryDirectory() as tmp:
            run_id_file = os.path.join(tmp, "run_id")
            argv = (
                [sys.executable, self.flow_file]
                + self._top_level_args()
                + command_args
                + ["--run-id-file", run_id_file]
            )
            env = dict(os.environ)
            env.update({k: str(v) for k, v in self.env.items()})
            proc = subprocess.run(
                argv,
                env=env,
                cwd=self.cwd,
                capture_output=not self.show_output,
                timeout=timeout,
            )
            stdout = (proc.stdout or b"").decode("utf-8", errors="replace")
            stderr = (proc.stderr or b"").decode("utf-8", errors="replace")
            run = None
            if os.path.exists(run_id_file):
                with open(run_id_file) as f:
                    run_id = f.read().strip()
                flow_name = self._flow_name()
                for _attempt in range(3):
                    try:
                        run = Run("%s/%s" % (flow_name, run_id),
                                  _namespace_check=False)
                        break
                    except Exception:
                        time.sleep(0.2)
            return ExecutingRun(argv, proc.returncode, run, stdout, stderr)

    def _flow_name(self):
        # flow class name == the click group name; derive by asking the file
        out = subprocess.run(
            [sys.executable, self.flow_file, "--help"],
            capture_output=True,
        )
        first = (out.stdout or b"").decode().split("\n", 1)[0]
        # "Usage: FlowName [OPTIONS] ..."
        parts = first.split()
        if len(parts) >= 2 and parts[0] == "Usage:":
            return parts[1]
        # fallback: scan the file for the class definition
        import re

        with open(self.flow_file) as f:
            m = re.search(r"class\s+(\w+)\s*\(.*FlowSpec", f.read())
        if m:
            return m.group(1)
        raise TpuFlowException("Could not determine flow name")

    def run(self, timeout=None, **params):
        args = ["run"]
        for k, v in params.items():
            if k in ("max_workers", "max_num_splits", "tags", "namespace"):
                key = "--" + k.replace("_", "-").rstrip("s" if k == "tags" else "")
                if isinstance(v, (list, tuple)):
                    for item in v:
                        args.extend(["--tag", str(item)])
                else:
                    args.extend([key, str(v)])
            else:
                args.extend(["--" + k.replace("_", "-"), str(v)])
        return self._execute(args, timeout=timeout)

    def resume(self, step_to_rerun=None, origin_run_id=None, timeout=None):
        args = ["resume"]
        if step_to_rerun:
            args.append(step_to_rerun)
        if origin_run_id:
            args.extend(["--origin-run-id", str(origin_run_id)])
        return self._execute(args, timeout=timeout)
