"""Programmatic API: Runner shells out to the flow CLI and attaches a client
Run object (reference behavior: metaflow/runner/metaflow_runner.py:305).

Kwarg handling reflects the flow's actual click command tree
(runner/click_api.py), so any option the CLI grows is immediately a valid
Runner kwarg and typos fail fast. Subprocesses run under an asyncio
supervisor (runner/subprocess_manager.py): timeouts kill the whole process
group (TERM→KILL), and logs stream live from files that outlive the
process.
"""

import os
import sys
import tempfile
import time

from ..client import Run
from ..exception import TpuFlowException
from .click_api import FlowCLIReflection
from .deployer import Deployer  # noqa: F401  (public API re-export)
from .subprocess_manager import SubprocessManager


def __getattr__(name):
    # notebook helpers import lazily: they pull in IPython-adjacent
    # machinery that isn't needed for the common CLI path
    if name in ("NBRunner", "NBDeployer"):
        from . import nbrun

        return getattr(nbrun, name)
    raise AttributeError(name)


class ExecutingRun(object):
    """Result of Runner.run(): the finished subprocess + the client Run."""

    def __init__(self, command, returncode, run, stdout, stderr):
        self.command = command
        self.returncode = returncode
        self.run = run
        self.stdout = stdout
        self.stderr = stderr

    @property
    def status(self):
        return "successful" if self.returncode == 0 else "failed"


class Runner(object):
    """Run a flow file programmatically:

        with Runner('flow.py') as runner:
            result = runner.run(alpha=0.5)
            print(result.run.data.x)

    Top-level CLI options (datastore, metadata, decospecs/--with, configs)
    are Runner kwargs; command options are method kwargs. Both are
    validated against the flow's real CLI.
    """

    def __init__(self, flow_file, show_output=False, env=None, cwd=None,
                 **top_level_kwargs):
        self.flow_file = os.path.abspath(flow_file)
        if not os.path.exists(self.flow_file):
            raise TpuFlowException("Flow file %s not found" % flow_file)
        self.show_output = show_output
        self.env = env or {}
        self.cwd = cwd
        self.top_level_kwargs = top_level_kwargs
        self.api = FlowCLIReflection(self.flow_file)
        self._manager = SubprocessManager()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._manager.cleanup()
        return False

    def command_names(self):
        """Commands the flow's CLI exposes (reflection view)."""
        return self.api.command_names()

    def _subprocess_env(self):
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.env.items()})
        return env

    def _argv(self, command, kwargs, positional=(), run_id_file=None):
        argv = (
            [sys.executable, self.flow_file]
            + self.api.build_top_level_argv(self.top_level_kwargs)
            + self.api.build_command_argv(command, kwargs, positional)
        )
        if run_id_file:
            argv += ["--run-id-file", run_id_file]
        return argv

    def _attach_run(self, run_id_file):
        if not os.path.exists(run_id_file):
            return None
        with open(run_id_file) as f:
            run_id = f.read().strip()
        flow_name = self._flow_name()
        for _attempt in range(3):
            try:
                return Run("%s/%s" % (flow_name, run_id),
                           _namespace_check=False)
            except Exception:
                time.sleep(0.2)
        return None

    def _execute(self, command, kwargs, positional=(), timeout=None):
        with tempfile.TemporaryDirectory() as tmp:
            run_id_file = os.path.join(tmp, "run_id")
            argv = self._argv(command, kwargs, positional, run_id_file)
            cm = self._manager.spawn_command(
                argv, env=self._subprocess_env(), cwd=self.cwd
            )
            # the deadline is enforced on the loop thread while (optionally)
            # streaming output live — a 2h run with show_output must show
            # progress as it happens, not a dump at exit
            wait_fut = cm.wait_future(timeout=timeout)
            if self.show_output:
                for line in cm.stream_log("stdout"):
                    sys.stdout.write(line)
            wait_fut.result()
            stdout = cm.log_contents("stdout")
            stderr = cm.log_contents("stderr")
            if self.show_output:
                sys.stderr.write(stderr)
            self._manager.commands.pop(cm.process.pid, None)
            cm.cleanup()
            if cm.timeout_expired:
                raise TpuFlowException(
                    "Command timed out after %ss: %s"
                    % (timeout, " ".join(argv))
                )
            return ExecutingRun(
                argv, cm.returncode, self._attach_run(run_id_file),
                stdout, stderr,
            )

    def _flow_name(self):
        # the flow name is the FlowSpec subclass name in the file
        import re

        with open(self.flow_file) as f:
            m = re.search(r"class\s+(\w+)\s*\([^)]*FlowSpec", f.read())
        if m:
            return m.group(1)
        raise TpuFlowException(
            "Could not determine the flow name from %s" % self.flow_file
        )

    def run(self, timeout=None, **params):
        return self._execute("run", params, timeout=timeout)

    def resume(self, step_to_rerun=None, timeout=None, **params):
        positional = (step_to_rerun,) if step_to_rerun else ()
        return self._execute("resume", params, positional, timeout=timeout)

    def _spawn_async(self, command, params, positional=()):
        tmpdir = tempfile.mkdtemp(prefix="tpuflow_run_")
        run_id_file = os.path.join(tmpdir, "run_id")
        argv = self._argv(command, params, positional,
                          run_id_file=run_id_file)
        cm = self._manager.spawn_command(
            argv, env=self._subprocess_env(), cwd=self.cwd
        )
        # the AsyncRun owns its process from here: leaving the Runner
        # context must not kill a deliberately backgrounded run (callers
        # wait()/terminate() through the handle)
        self._manager.commands.pop(cm.process.pid, None)
        return AsyncRun(self, cm, run_id_file, argv)

    def async_run(self, **params):
        """Start the run without blocking; returns an AsyncRun handle
        that owns the subprocess (it survives Runner.__exit__)."""
        return self._spawn_async("run", params)

    def async_resume(self, step_to_rerun=None, **params):
        positional = (step_to_rerun,) if step_to_rerun else ()
        return self._spawn_async("resume", params, positional)


class AsyncRun(object):
    """Handle on a live run: id/client access, live log streaming,
    wait-with-timeout, and kill (TERM→KILL on the process group)."""

    def __init__(self, runner, cm, run_id_file, command):
        self._runner = runner
        self._cm = cm
        self._run_id_file = run_id_file
        self.command = command

    @property
    def proc(self):
        # back-compat shim over the asyncio Process: Popen-style
        # .pid/.returncode/.poll() (asyncio's Process has no poll())
        cm = self._cm

        class _ProcShim(object):
            pid = cm.process.pid if cm.process else None

            @property
            def returncode(self):
                return cm.process.returncode if cm.process else None

            def poll(self):
                return self.returncode

        return _ProcShim()

    @property
    def run_id(self):
        # be patient: flow-file import can take tens of seconds on a TPU VM
        deadline = time.time() + 600
        while time.time() < deadline:
            if os.path.exists(self._run_id_file):
                with open(self._run_id_file) as f:
                    return f.read().strip()
            if not self._cm.running:
                break
            time.sleep(0.1)
        # final re-check: a fast run may exit between poll and file write
        if os.path.exists(self._run_id_file):
            with open(self._run_id_file) as f:
                return f.read().strip()
        return None

    @property
    def run(self):
        run_id = self.run_id
        if run_id is None:
            return None
        try:
            return Run("%s/%s" % (self._runner._flow_name(), run_id),
                       _namespace_check=False)
        except Exception:
            return None

    def stream_log(self, name="stdout"):
        """Yield log lines live while the run executes."""
        return self._cm.stream_log(name)

    def wait(self, timeout=None):
        """Wait for the run; on timeout the process group is killed and a
        TpuFlowException raised (same contract as Runner.run(timeout=...))."""
        self._cm.wait(timeout=timeout)
        if self._cm.timeout_expired:
            self._cleanup()
            raise TpuFlowException(
                "Run timed out after %ss (process killed): %s"
                % (timeout, " ".join(self.command))
            )
        result = ExecutingRun(
            self.command,
            self._cm.returncode,
            self.run,
            self._cm.log_contents("stdout"),
            self._cm.log_contents("stderr"),
        )
        self._cleanup()
        return result

    def terminate(self):
        self._cm.kill()
        self._cleanup()

    def _cleanup(self):
        import shutil

        shutil.rmtree(os.path.dirname(self._run_id_file),
                      ignore_errors=True)
        self._cm.cleanup()
