"""Programmatic API: Runner shells out to the flow CLI and attaches a client
Run object (reference behavior: metaflow/runner/metaflow_runner.py:305)."""

import os
import subprocess
import sys
import tempfile
import time

from ..client import Run
from ..exception import TpuFlowException
from .deployer import Deployer  # noqa: F401  (public API re-export)


def __getattr__(name):
    # NBRunner imports lazily: nbrun pulls in Runner machinery that isn't
    # needed for the common CLI path
    if name == "NBRunner":
        from .nbrun import NBRunner

        return NBRunner
    raise AttributeError(name)


class ExecutingRun(object):
    """Result of Runner.run(): the subprocess + the client Run object."""

    def __init__(self, command, returncode, run, stdout, stderr):
        self.command = command
        self.returncode = returncode
        self.run = run
        self.stdout = stdout
        self.stderr = stderr

    @property
    def status(self):
        return "successful" if self.returncode == 0 else "failed"


class Runner(object):
    """Run a flow file programmatically:

        with Runner('flow.py') as runner:
            result = runner.run(alpha=0.5)
            print(result.run.data.x)
    """

    def __init__(self, flow_file, show_output=False, env=None, cwd=None,
                 **top_level_kwargs):
        self.flow_file = os.path.abspath(flow_file)
        if not os.path.exists(self.flow_file):
            raise TpuFlowException("Flow file %s not found" % flow_file)
        self.show_output = show_output
        self.env = env or {}
        self.cwd = cwd
        self.top_level_kwargs = top_level_kwargs

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _top_level_args(self):
        args = []
        for k, v in self.top_level_kwargs.items():
            key = "--" + k.replace("_", "-")
            if isinstance(v, bool):
                if v:
                    args.append(key)
            elif isinstance(v, (list, tuple)):
                for item in v:
                    args.extend([key, str(item)])
            else:
                args.extend([key, str(v)])
        return args

    def _execute(self, command_args, timeout=None):
        with tempfile.TemporaryDirectory() as tmp:
            run_id_file = os.path.join(tmp, "run_id")
            argv = (
                [sys.executable, self.flow_file]
                + self._top_level_args()
                + command_args
                + ["--run-id-file", run_id_file]
            )
            env = dict(os.environ)
            env.update({k: str(v) for k, v in self.env.items()})
            proc = subprocess.run(
                argv,
                env=env,
                cwd=self.cwd,
                capture_output=not self.show_output,
                timeout=timeout,
            )
            stdout = (proc.stdout or b"").decode("utf-8", errors="replace")
            stderr = (proc.stderr or b"").decode("utf-8", errors="replace")
            run = None
            if os.path.exists(run_id_file):
                with open(run_id_file) as f:
                    run_id = f.read().strip()
                flow_name = self._flow_name()
                for _attempt in range(3):
                    try:
                        run = Run("%s/%s" % (flow_name, run_id),
                                  _namespace_check=False)
                        break
                    except Exception:
                        time.sleep(0.2)
            return ExecutingRun(argv, proc.returncode, run, stdout, stderr)

    def _flow_name(self):
        # the flow name is the FlowSpec subclass name in the file
        import re

        with open(self.flow_file) as f:
            m = re.search(r"class\s+(\w+)\s*\([^)]*FlowSpec", f.read())
        if m:
            return m.group(1)
        raise TpuFlowException(
            "Could not determine the flow name from %s" % self.flow_file
        )

    def run(self, timeout=None, **params):
        args = ["run"]
        for k, v in params.items():
            if k in ("max_workers", "max_num_splits", "tags", "namespace"):
                key = "--" + k.replace("_", "-").rstrip("s" if k == "tags" else "")
                if isinstance(v, (list, tuple)):
                    for item in v:
                        args.extend(["--tag", str(item)])
                else:
                    args.extend([key, str(v)])
            else:
                args.extend(["--" + k.replace("_", "-"), str(v)])
        return self._execute(args, timeout=timeout)

    def resume(self, step_to_rerun=None, origin_run_id=None, timeout=None):
        args = ["resume"]
        if step_to_rerun:
            args.append(step_to_rerun)
        if origin_run_id:
            args.extend(["--origin-run-id", str(origin_run_id)])
        return self._execute(args, timeout=timeout)

    def async_run(self, **params):
        """Start the run without blocking; returns an AsyncRun handle."""
        import tempfile

        tmpdir = tempfile.mkdtemp(prefix="tpuflow_run_")
        run_id_file = os.path.join(tmpdir, "run_id")
        argv = (
            [sys.executable, self.flow_file]
            + self._top_level_args()
            + ["run", "--run-id-file", run_id_file]
        )
        for k, v in params.items():
            argv.extend(["--" + k.replace("_", "-"), str(v)])
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.env.items()})
        proc = subprocess.Popen(
            argv, env=env, cwd=self.cwd,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        return AsyncRun(self, proc, run_id_file, argv)


class AsyncRun(object):
    def __init__(self, runner, proc, run_id_file, command):
        self._runner = runner
        self.proc = proc
        self._run_id_file = run_id_file
        self.command = command

    @property
    def run_id(self):
        # be patient: flow-file import can take tens of seconds on a TPU VM
        deadline = time.time() + 600
        while time.time() < deadline:
            if os.path.exists(self._run_id_file):
                with open(self._run_id_file) as f:
                    return f.read().strip()
            if self.proc.poll() is not None:
                break
            time.sleep(0.1)
        # final re-check: a fast run may exit between poll and file write
        if os.path.exists(self._run_id_file):
            with open(self._run_id_file) as f:
                return f.read().strip()
        return None

    @property
    def run(self):
        run_id = self.run_id
        if run_id is None:
            return None
        try:
            return Run("%s/%s" % (self._runner._flow_name(), run_id),
                       _namespace_check=False)
        except Exception:
            return None

    def wait(self, timeout=None):
        stdout, stderr = self.proc.communicate(timeout=timeout)
        result = ExecutingRun(
            self.command,
            self.proc.returncode,
            self.run,
            stdout.decode("utf-8", errors="replace"),
            stderr.decode("utf-8", errors="replace"),
        )
        self._cleanup()
        return result

    def terminate(self):
        self.proc.terminate()
        self._cleanup()

    def _cleanup(self):
        import shutil

        shutil.rmtree(os.path.dirname(self._run_id_file), ignore_errors=True)
