"""Paged-KV continuous-batching engine + speculative decoding.

The slot engine (engine.py) gives every slot a private
[max_seq] stripe of one static KV block, so max_seq bounds concurrency,
short requests strand HBM, and every radix prefix hit COPIES cached KV
into the slot. This module is the vLLM-lineage fix shaped for the same
TPU constraints: keep scheduling in Python, keep every device step one
of a FIXED set of jitted programs.

Layout: a global PagePool of fixed-size KV pages
({"k": [layers, n_pages, page_tokens, kv_heads, head_dim], "v": ...})
plus a per-slot BLOCK TABLE ([B, n_blocks] int32). Block tables are
TRACED arrays, so the compiled-program set stays fixed regardless of
which pages a slot happens to hold:

  - prefill: write one prompt chunk through one slot's block-table row
    (token position p lands in page table[p // page_tokens] at offset
    p % page_tokens — a batched scatter, the paged analogue of
    engine.py's dynamic_update_slice discipline)
  - decode: advance ALL slots one token in one fused call; attention
    gathers KV back through the tables (dense gathered view at small
    depth, page-streamed online softmax — decode._streamed_attention —
    beyond it)
  - spec: verify a K-token self-drafted proposal in ONE fused call
    ([B, K+1] tokens at per-slot offsets); the host keeps the longest
    prefix of drafts the target model's own argmax agrees with, so
    greedy output is token-identical to the non-speculative path

Page 0 is a reserved SCRATCH page: free and mid-prefill slots ride
through fused steps as masked lanes whose writes land in their own
table (all zeros for a free slot → scratch) and are overwritten before
they can become visible — the same invariant engine.py relies on.

Admission is RESERVATION-based and therefore deadlock-free: admit()
allocates every page the request could ever touch
(ceil((prompt + max_new + spec_k) / page_tokens)) up front, so decode
can never strand mid-request out of memory. The concurrency win over
the slot engine is the RAGGED reservation: a slot engine charges every
request max_seq tokens of HBM; this engine charges what the request
asked for, so at equal HBM the pool admits well past B short requests.
Page exhaustion surfaces at ADMISSION (scheduler backpressure +
serve.kv.exhausted), never mid-decode.

Zero-copy prefix sharing: prefix_cache.PagedPrefixIndex registers a
finished prompt's pages under a hash chain and holds its own pool ref
per page; a later hit POINTS the new slot's block table at the same
device pages (refcount++, no KV bytes move). Only a partially-filled
tail page is copied (copy-on-write) — a shared page that would be
appended to must be private first.
"""

import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

from .. import knobs
from ..inference.decode import (
    DECODE_CHUNK,
    _attn_qkv,
    _block_ffn,
    _cached_attention,
    _streamed_attention,
    bucket_length,
)
from ..models import llama
from ..ops import rms_norm
from ..ops.rope import rope_frequencies
from .engine import request_step_keys, sample_slots

DEFAULT_PAGE_TOKENS = 16


def page_tokens_from_env(default=DEFAULT_PAGE_TOKENS):
    """TPUFLOW_KV_PAGE_TOKENS: tokens per KV page (the paged engine's
    allocation granule)."""
    return max(1, knobs.get_int("TPUFLOW_KV_PAGE_TOKENS",
                                fallback=default))


def spec_k_from_env(default=0):
    """TPUFLOW_SPEC_K: speculative draft length (0 disables)."""
    return max(0, knobs.get_int("TPUFLOW_SPEC_K", fallback=default))


class PageExhaustedError(RuntimeError):
    """The page pool cannot satisfy an allocation right now. NOT a
    ValueError on purpose: the scheduler rejects ValueError admits as
    malformed, but exhaustion is backpressure — the request waits."""


class PagePool(object):
    """The global device KV page pool + host-side free list/refcounts.

    Pages are ref-counted, not owned: a slot refs every page in its
    block table, the prefix index refs every page it registers, and a
    page returns to the free list only when the LAST ref drops — which
    is exactly what makes prefix hits zero-copy-safe (eviction or slot
    release can never free a page another holder still reads).
    """

    def __init__(self, cfg, n_pages, page_tokens, dtype=None):
        if n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is scratch)")
        if page_tokens < 1:
            raise ValueError("page_tokens must be >= 1")
        dt = jnp.dtype(dtype) if dtype is not None else llama.param_dtype(cfg)
        shape = (cfg.n_layers, int(n_pages), int(page_tokens),
                 cfg.n_kv_heads, cfg.head_dim)
        self.kv = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        self.n_pages = int(n_pages)
        self.page_tokens = int(page_tokens)
        self._lock = threading.Lock()
        self.refs = np.zeros(self.n_pages, np.int32)
        self.refs[0] = 1  # scratch: permanently held, never allocated
        self._free = list(range(self.n_pages - 1, 0, -1))
        self.alloc_count = 0       # cumulative pages handed out
        self.freed_count = 0       # cumulative pages returned

    @property
    def usable_pages(self):
        return self.n_pages - 1    # minus the scratch page

    def page_bytes(self):
        k = self.kv["k"]
        layers, _, ptok, kv_heads, head_dim = k.shape
        return 2 * layers * ptok * kv_heads * head_dim * k.dtype.itemsize

    def free_pages(self):
        with self._lock:
            return len(self._free)

    def pages_in_use(self):
        with self._lock:
            return self.usable_pages - len(self._free)

    def shared_pages(self):
        """Pages currently held by more than one owner (scratch excluded)."""
        with self._lock:
            return int((self.refs[1:] > 1).sum())

    def can_alloc(self, n):
        with self._lock:
            return len(self._free) >= n

    def alloc(self, n):
        """Take n pages (each with one ref). Raises PageExhaustedError —
        callers gate on can_alloc/can_admit, so this raising is the
        backstop, not the control flow."""
        with self._lock:
            if len(self._free) < n:
                raise PageExhaustedError(
                    "need %d pages, %d free" % (n, len(self._free)))
            pids = [self._free.pop() for _ in range(n)]
            for p in pids:
                self.refs[p] = 1
            self.alloc_count += n
            return pids

    def ref(self, pids):
        with self._lock:
            for p in pids:
                p = int(p)
                if p == 0:
                    continue
                if self.refs[p] <= 0:
                    raise RuntimeError("ref of free page %d" % p)
                self.refs[p] += 1

    def unref(self, pids):
        """Drop one ref per page; pages reaching zero return to the free
        list. Returns how many were actually freed."""
        freed = 0
        with self._lock:
            for p in pids:
                p = int(p)
                if p == 0:
                    continue
                if self.refs[p] <= 0:
                    raise RuntimeError("unref of free page %d" % p)
                self.refs[p] -= 1
                if self.refs[p] == 0:
                    self._free.append(p)
                    freed += 1
            self.freed_count += freed
        return freed

    def stats(self):
        with self._lock:
            free = len(self._free)
            shared = int((self.refs[1:] > 1).sum())
        total = self.usable_pages
        return {
            "page_tokens": self.page_tokens,
            "page_bytes": self.page_bytes(),
            "pages_total": total,
            "pages_free": free,
            "pages_in_use": total - free,
            "occupancy": round((total - free) / max(1, total), 4),
            "shared_pages": shared,
            "page_allocs": self.alloc_count,
            "page_frees": self.freed_count,
        }


def ngram_draft(context, k, max_ngram=3):
    """Prompt-lookup self-drafting (the default draft policy): find the
    most recent earlier occurrence of the longest trailing n-gram of the
    context and propose its continuation. Free — no draft model — and
    effective exactly when decode revisits earlier phrasing (templated
    output, code, retrieval-grounded answers)."""
    ctx = [int(t) for t in context]
    for ng in range(min(max_ngram, max(0, len(ctx) - 1)), 0, -1):
        tail = ctx[-ng:]
        for i in range(len(ctx) - ng - 1, -1, -1):
            if ctx[i:i + ng] == tail:
                cont = ctx[i + ng:i + ng + k]
                if cont:
                    while len(cont) < k:
                        cont.append(cont[-1])
                    return cont
    last = ctx[-1] if ctx else 0
    return [last] * k


def _paged_forward(params, tokens, pool_kv, tables, pos, cfg,
                   page_tokens, mesh=None, attn_impl="dense"):
    """decode_forward through a block table: forward T new tokens per
    row at per-row offsets `pos` [B], writing their KV into the pages
    `tables` [B, n_blocks] names and attending back through them.

    Numerics match the contiguous path: the qkv/rope and FFN halves are
    the SAME functions (decode._attn_qkv/_block_ffn), 'dense' gathers
    the table into a contiguous [B, S] view and runs the SAME
    _cached_attention, and 'chunked' streams pages through the SAME
    online-softmax accumulation (_streamed_attention)."""
    dt = llama.param_dtype(cfg)
    B, T = tokens.shape
    n_blocks = tables.shape[1]
    S = n_blocks * page_tokens
    KV, Hd = cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][tokens].astype(dt)
    cos, sin = rope_frequencies(
        cfg.head_dim, S, cfg.rope_theta, dtype=dt,
        llama3_scaling=getattr(cfg, "rope_llama3_scaling", False),
    )
    abs_pos = pos[:, None] + jnp.arange(T)[None, :]          # [B, T]
    page_idx = abs_pos // page_tokens
    offs = abs_pos % page_tokens
    pids = jnp.take_along_axis(tables, page_idx, axis=1)     # [B, T]

    def layer_fn(carry, inp):
        lp, pk, pv = inp          # pk/pv: [n_pages, page_tokens, KV, Hd]
        q, k, v = _attn_qkv(cfg, cos, sin, pos, carry, lp)
        # paged cache write: token t of row b lands in page pids[b, t]
        # at offset offs[b, t] — one batched scatter per layer, the
        # block-table analogue of the vector-pos dynamic_update_slice
        pk = pk.at[pids, offs].set(k.astype(pk.dtype))
        pv = pv.at[pids, offs].set(v.astype(pv.dtype))
        if attn_impl == "chunked":
            n_chunks = (jnp.max(pos) + T + page_tokens - 1) // page_tokens

            def fetch(i):
                blk = tables[:, i]                           # [B]
                return (pk[blk], pv[blk],
                        i * page_tokens + jnp.arange(page_tokens))

            attn = _streamed_attention(q, pos, page_tokens, n_chunks,
                                       fetch)
        else:
            view_k = pk[tables].reshape(B, S, KV, Hd)
            view_v = pv[tables].reshape(B, S, KV, Hd)
            attn = _cached_attention(q, view_k, view_v, pos)
        out = _block_ffn(cfg, carry, attn, lp, mesh=mesh)
        return out, (pk, pv)

    x, (new_k, new_v) = jax.lax.scan(
        layer_fn, x, (params["layers"], pool_kv["k"], pool_kv["v"])
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}


class PagedEngine(object):
    """SlotEngine-compatible engine over a paged KV pool.

    Same API surface the scheduler drives (admit/prefill_step/
    decode_step/release/seed_prefix/extract_kv/admit_prefilled), plus
    the paged extensions: can_admit/fits (reservation capacity),
    seed_pages (zero-copy prefix attach), slot_prefix_pages (prefix
    registration read path), kv_stats/spec_stats.

    NOT thread-safe — exactly one scheduler loop drives it.
    """

    def __init__(self, params, cfg, max_slots=8, max_seq_len=None,
                 prefill_chunk=64, mesh=None, attn_impl="auto",
                 cache_dtype=None, pad_id=0, min_bucket=16,
                 page_tokens=None, total_pages=None, spec_k=None,
                 draft_fn=None):
        if attn_impl not in ("auto", "dense", "chunked"):
            raise ValueError("attn_impl must be 'auto', 'dense' or "
                             "'chunked', got %r" % (attn_impl,))
        self.params = params
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1, got %d"
                             % self.prefill_chunk)
        self.pad_id = int(pad_id)
        self.min_bucket = min(int(min_bucket), self.prefill_chunk)
        self.mesh = mesh
        self._vocab = cfg.vocab_size
        self.page_tokens = int(page_tokens or page_tokens_from_env())
        self.spec_k = int(spec_k_from_env() if spec_k is None else spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        self.draft_fn = draft_fn or ngram_draft
        ptok = self.page_tokens
        # table width covers max_seq PLUS the spec margin: a verify step
        # writes up to K positions past the last accepted token, and an
        # out-of-range table index would be clamped into a LIVE page
        self.n_blocks = -(-(self.max_seq_len + self.spec_k) // ptok)
        if total_pages is None:
            # default: the slot engine's HBM shape — every slot can hold
            # a full max_seq sequence (+1 scratch page)
            total_pages = self.max_slots * self.n_blocks + 1
        self.pool = PagePool(cfg, total_pages, ptok, dtype=cache_dtype)
        if attn_impl == "auto":
            attn_impl = ("chunked"
                         if self.n_blocks * ptok > 2 * DECODE_CHUNK
                         else "dense")
        self.attn_impl = attn_impl

        B = self.max_slots
        # host-side per-slot state (mirrors engine.py)
        self.pos = np.zeros(B, np.int32)
        self.active = np.zeros(B, bool)
        self.decoding = np.zeros(B, bool)
        self.block_tables = np.zeros((B, self.n_blocks), np.int32)
        self._n_pages = np.zeros(B, np.int32)
        self._tok = np.zeros(B, np.int32)
        self._temp = np.zeros(B, np.float32)
        self._top_k = np.full(B, self._vocab, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._keys = np.zeros((B, 2), np.uint32)
        self._step_keys = [None] * B
        self._slot_ctx = [None] * B
        self._key_cursor = np.zeros(B, np.int32)
        self._prompt = [None] * B
        self._prefill_cursor = np.zeros(B, np.int32)
        self._max_new = np.zeros(B, np.int32)
        self._emitted = np.zeros(B, np.int32)
        self._context = [None] * B       # prompt+generated (draft source)
        self._dirty = True
        self._d_tok = self._d_pos = self._d_mask = self._d_tables = None
        self._d_temp = self._d_top_k = self._d_top_p = None
        # counters
        self.kv_bytes_copied = 0   # host<->page copies (0 on zero-copy hits)
        self.cow_pages = 0         # partial tail pages privatized
        self.cow_bytes = 0
        self.shared_pages_attached = 0  # zero-copy pages attached to slots
        self.shared_tokens = 0     # tokens those pages carried
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

        fwd = _paged_forward

        def _prefill(params, pool_kv, chunk_tokens, table_row, start):
            logits, pool_kv = fwd(
                params, chunk_tokens, pool_kv, table_row[None],
                start[None], cfg, ptok, mesh=mesh,
                attn_impl=self.attn_impl)
            return logits, pool_kv

        def _advance(nxt, tok, pos, mask):
            tok = jnp.where(mask, nxt, tok)
            pos = pos + mask.astype(jnp.int32)
            return tok, pos

        def _decode_sampled(params, pool_kv, tok, pos, mask, tables,
                            keys, temp, top_k, top_p):
            logits, pool_kv = fwd(
                params, tok[:, None], pool_kv, tables, pos, cfg, ptok,
                mesh=mesh, attn_impl=self.attn_impl)
            nxt = sample_slots(logits[:, 0], keys, temp, top_k, top_p)
            tok, pos = _advance(nxt, tok, pos, mask)
            return nxt, tok, pos, pool_kv

        def _decode_greedy(params, pool_kv, tok, pos, mask, tables):
            logits, pool_kv = fwd(
                params, tok[:, None], pool_kv, tables, pos, cfg, ptok,
                mesh=mesh, attn_impl=self.attn_impl)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            tok, pos = _advance(nxt, tok, pos, mask)
            return nxt, tok, pos, pool_kv

        def _spec_verify(params, pool_kv, toks, pos, tables):
            # toks: [B, K+1] = last emitted token + K drafts; the target
            # model scores ALL K+1 positions in one fused call and the
            # host keeps the agreeing prefix (greedy: argmax == the
            # token sequential decode would emit, so acceptance
            # preserves token identity)
            logits, pool_kv = fwd(
                params, toks, pool_kv, tables, pos, cfg, ptok,
                mesh=mesh, attn_impl=self.attn_impl)
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return out, pool_kv

        def _first_token(logits, idx, key, temp, top_k, top_p):
            last = jax.lax.dynamic_index_in_dim(logits, idx, axis=1,
                                                keepdims=False)
            return sample_slots(last, key[None], temp[None], top_k[None],
                                top_p[None])[0]

        def _seed_host(pool_kv, k, v, table_row):
            # scatter a host KV range ([layers, T, kv, hd]) into the
            # slot's pages at positions [0, T) — the radix-cache /
            # disagg-handoff COPY path (zero-copy goes via seed_pages)
            T = k.shape[1]
            p_idx = jnp.arange(T) // ptok
            pids = table_row[p_idx]
            offs = jnp.arange(T) % ptok
            pk = pool_kv["k"].at[:, pids, offs].set(
                k.astype(pool_kv["k"].dtype))
            pv = pool_kv["v"].at[:, pids, offs].set(
                v.astype(pool_kv["v"].dtype))
            return {"k": pk, "v": pv}

        def _extract(pool_kv, table_row, T):
            # gather the first T positions back out (static T bucket)
            n = -(-T // ptok)
            k = pool_kv["k"][:, table_row[:n]]
            v = pool_kv["v"][:, table_row[:n]]
            L = k.shape[0]
            KV, Hd = k.shape[3], k.shape[4]
            return (k.reshape(L, n * ptok, KV, Hd)[:, :T],
                    v.reshape(L, n * ptok, KV, Hd)[:, :T])

        def _copy_page(pool_kv, src, dst):
            # copy-on-write: privatize one page before it is appended to
            L, _, T, KV, Hd = pool_kv["k"].shape
            out = {}
            for name in ("k", "v"):
                blk = jax.lax.dynamic_slice(
                    pool_kv[name], (0, src, 0, 0, 0), (L, 1, T, KV, Hd))
                out[name] = jax.lax.dynamic_update_slice(
                    pool_kv[name], blk, (0, dst, 0, 0, 0))
            return out

        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_sampled_fn = jax.jit(_decode_sampled,
                                          donate_argnums=(1,))
        self._decode_greedy_fn = jax.jit(_decode_greedy,
                                         donate_argnums=(1,))
        self._spec_fn = jax.jit(_spec_verify, donate_argnums=(1,))
        self._first_fn = jax.jit(_first_token)
        self._seed_fn = jax.jit(_seed_host, donate_argnums=(0,))
        self._extract_fn = jax.jit(_extract, static_argnums=(2,))
        self._copy_page_fn = jax.jit(_copy_page, donate_argnums=(0,))

    # ---------- pool / capacity state ----------

    def free_slots(self):
        return [i for i in range(self.max_slots) if not self.active[i]]

    def occupancy(self):
        return float(self.active.sum()) / self.max_slots

    def compile_counts(self):
        return {
            "prefill": self._prefill_fn._cache_size(),
            "decode_greedy": self._decode_greedy_fn._cache_size(),
            "decode_sampled": self._decode_sampled_fn._cache_size(),
            "spec_verify": self._spec_fn._cache_size(),
            "first_token": self._first_fn._cache_size(),
            "seed_prefix": self._seed_fn._cache_size(),
            "extract_kv": self._extract_fn._cache_size(),
            "copy_page": self._copy_page_fn._cache_size(),
        }

    def kv_token_bytes(self):
        k = self.pool.kv["k"]
        layers, _, _, kv_heads, head_dim = k.shape
        return 2 * layers * kv_heads * head_dim * k.dtype.itemsize

    def _pages_needed(self, prompt_len, max_new_tokens):
        need = prompt_len + max_new_tokens + self.spec_k
        return -(-need // self.page_tokens)

    def fits(self, prompt_len, max_new_tokens):
        """Could this request EVER be admitted (empty pool)? The
        admission-time capacity check — a False here is a permanent 413,
        not backpressure."""
        if prompt_len + max_new_tokens > self.max_seq_len:
            return False
        return (self._pages_needed(prompt_len, max_new_tokens)
                <= self.pool.usable_pages)

    def can_admit(self, prompt_len, max_new_tokens):
        """Can this request be admitted NOW (enough free pages for its
        full reservation)? A False is backpressure: the scheduler keeps
        it queued and emits serve.kv.exhausted."""
        return self.pool.can_alloc(
            self._pages_needed(prompt_len, max_new_tokens))

    def max_context_tokens(self):
        """The largest prompt+max_new any request may carry — the
        scalar the fleet router sheds oversized dispatches against."""
        return min(self.max_seq_len,
                   self.pool.usable_pages * self.page_tokens - self.spec_k)

    def kv_stats(self):
        out = {"enabled": True}
        out.update(self.pool.stats())
        out.update({
            "cow_pages": self.cow_pages,
            "cow_bytes": self.cow_bytes,
            "kv_bytes_copied": self.kv_bytes_copied,
            "shared_pages_attached": self.shared_pages_attached,
            "shared_tokens": self.shared_tokens,
            "spec_k": self.spec_k,
        })
        return out

    def spec_stats(self):
        return {
            "enabled": self.spec_k > 0,
            "k": self.spec_k,
            "steps": self.spec_steps,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "accept_rate": round(
                self.spec_accepted / max(1, self.spec_proposed), 4),
        }

    # ---------- slot lifecycle ----------

    def admit(self, slot, prompt_tokens, max_new_tokens, temperature=0.0,
              top_k=None, top_p=None, rng=0):
        """Bind a request to a free slot and RESERVE its full page
        budget. Raises ValueError for malformed/never-fits requests and
        PageExhaustedError when the pool is momentarily out of pages
        (callers gate on can_admit)."""
        if self.active[slot]:
            raise ValueError("slot %d is busy" % slot)
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size + max_new_tokens > self.max_seq_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the engine's "
                "max_seq_len (%d)" % (prompt.size, max_new_tokens,
                                      self.max_seq_len))
        n_pages = self._pages_needed(prompt.size, max_new_tokens)
        if n_pages > self.pool.usable_pages:
            raise ValueError(
                "request needs %d KV pages but the pool only has %d"
                % (n_pages, self.pool.usable_pages))
        pids = self.pool.alloc(n_pages)   # may raise PageExhaustedError
        self.active[slot] = True
        self.decoding[slot] = False
        self.pos[slot] = 0
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :n_pages] = pids
        self._n_pages[slot] = n_pages
        self._prompt[slot] = prompt
        self._prefill_cursor[slot] = 0
        self._temp[slot] = float(temperature)
        self._top_k[slot] = (self._vocab if top_k is None
                             else min(int(top_k), self._vocab))
        self._top_p[slot] = 1.0 if top_p is None else float(top_p)
        self._step_keys[slot] = request_step_keys(rng, max_new_tokens)
        self._key_cursor[slot] = 0
        self._max_new[slot] = int(max_new_tokens)
        self._emitted[slot] = 0
        self._context[slot] = [int(t) for t in prompt]
        self._dirty = True

    def bind_slot_context(self, slot, ctx):
        self._slot_ctx[slot] = dict(ctx) if ctx else None

    def slot_context(self, slot):
        return self._slot_ctx[slot]

    def release(self, slot):
        """Reclaim the slot and drop its page refs. Pages the prefix
        index (or another holder) still refs survive; everything else
        returns to the free list — so every terminal path (finish,
        cancel, deadline, drain, shutdown) releases the full
        reservation."""
        n = int(self._n_pages[slot])
        if n:
            self.pool.unref(self.block_tables[slot, :n])
        self.block_tables[slot, :] = 0
        self._n_pages[slot] = 0
        self.active[slot] = False
        self._slot_ctx[slot] = None
        self.decoding[slot] = False
        self.pos[slot] = 0
        self._prompt[slot] = None
        self._step_keys[slot] = None
        self._context[slot] = None
        self._temp[slot] = 0.0
        self._top_k[slot] = self._vocab
        self._top_p[slot] = 1.0
        self._dirty = True

    # ---------- prefix seeding ----------

    def seed_pages(self, slot, handle):
        """ZERO-COPY prefix attach: point the slot's block table at the
        shared pages a PagedPrefixIndex match pinned. The slot's own
        pages for those positions go straight back to the pool (the net
        reservation SHRINKS on a hit). A partially-filled tail page is
        privatized with one device page copy (copy-on-write) — the only
        KV bytes that ever move on a hit."""
        if not self.active[slot] or self.decoding[slot]:
            raise ValueError("slot %d is not prefilling" % slot)
        if int(self._prefill_cursor[slot]) != 0:
            raise ValueError("slot %d already started prefill" % slot)
        prompt = self._prompt[slot]
        if not (0 < handle.length < prompt.size):
            raise ValueError(
                "seed length %d must be in [1, prompt %d)"
                % (handle.length, prompt.size))
        n_full = len(handle.pages)
        if n_full:
            own = self.block_tables[slot, :n_full]
            self.pool.ref(handle.pages)
            self.pool.unref(own)
            self.block_tables[slot, :n_full] = handle.pages
            self.shared_pages_attached += n_full
            self.shared_tokens += n_full * self.page_tokens
        if handle.partial is not None:
            src, _ntok = handle.partial
            dst = int(self.block_tables[slot, n_full])
            self.pool.kv = self._copy_page_fn(
                self.pool.kv, jnp.int32(src), jnp.int32(dst))
            self.cow_pages += 1
            self.cow_bytes += self.pool.page_bytes()
        self._prefill_cursor[slot] = handle.length
        self.pos[slot] = handle.length
        self._dirty = True

    def slot_prefix_pages(self, slot, prompt_len):
        """The pages holding the first prompt_len cached tokens of a
        slot: (full_page_ids, tail_page_id_or_None) — what the prefix
        index registers after a finished prefill."""
        ptok = self.page_tokens
        n_full = prompt_len // ptok
        full = [int(p) for p in self.block_tables[slot, :n_full]]
        tail = None
        if prompt_len % ptok:
            tail = int(self.block_tables[slot, n_full])
        return full, tail

    def seed_prefix(self, slot, kv):
        """Host-KV copy seeding (radix-cache / compat path): upload a
        cached [layers, T, kv, hd] range into the slot's pages at
        positions [0, T). The zero-copy path is seed_pages."""
        if not self.active[slot] or self.decoding[slot]:
            raise ValueError("slot %d is not prefilling" % slot)
        if int(self._prefill_cursor[slot]) != 0:
            raise ValueError("slot %d already started prefill" % slot)
        k, v = np.asarray(kv["k"]), np.asarray(kv["v"])
        T = k.shape[1]
        prompt = self._prompt[slot]
        if not (0 < T < prompt.size):
            raise ValueError(
                "seed length %d must be in [1, prompt %d)"
                % (T, prompt.size))
        self._upload_kv(slot, k, v, T)
        self._prefill_cursor[slot] = T
        self.pos[slot] = T
        self._dirty = True

    def _upload_kv(self, slot, k, v, T):
        bucket = bucket_length(T, minimum=self.min_bucket,
                               maximum=self.n_blocks * self.page_tokens)
        if bucket > T:
            pad = [(0, 0), (0, bucket - T), (0, 0), (0, 0)]
            k, v = np.pad(k, pad), np.pad(v, pad)
        dtype = self.pool.kv["k"].dtype
        self.pool.kv = self._seed_fn(
            self.pool.kv, jnp.asarray(k, dtype), jnp.asarray(v, dtype),
            jnp.asarray(self.block_tables[slot]))
        self.kv_bytes_copied += int(k.nbytes) + int(v.nbytes)

    def extract_kv(self, slot, length):
        """The first `length` cached positions of a slot as host arrays
        — the disaggregation-handoff read path (a paged prefix cache
        never needs this: it shares pages in place)."""
        if length < 1 or length > self.max_seq_len:
            raise ValueError("length %d out of range" % length)
        bucket = bucket_length(length, minimum=self.min_bucket,
                               maximum=self.n_blocks * self.page_tokens)
        k, v = self._extract_fn(
            self.pool.kv, jnp.asarray(self.block_tables[slot]), bucket)
        return {"k": np.asarray(k)[:, :length],
                "v": np.asarray(v)[:, :length]}

    def admit_prefilled(self, slot, prompt_tokens, first_token, kv,
                        max_new_tokens, temperature=0.0, top_k=None,
                        top_p=None, rng=0):
        """Bind a request prefilled ELSEWHERE (disaggregation): seed the
        full prompt KV into fresh pages and enter decode directly."""
        self.admit(slot, prompt_tokens, max_new_tokens,
                   temperature=temperature, top_k=top_k, top_p=top_p,
                   rng=rng)
        prompt = self._prompt[slot]
        k = np.asarray(kv["k"])
        if k.shape[1] != prompt.size:
            self.release(slot)
            raise ValueError("handoff kv length %d != prompt %d"
                             % (k.shape[1], prompt.size))
        self._upload_kv(slot, k, np.asarray(kv["v"]), prompt.size)
        self._prefill_cursor[slot] = prompt.size
        self.decoding[slot] = True
        self.pos[slot] = prompt.size
        self._tok[slot] = int(first_token)
        self._key_cursor[slot] = 1
        self._emitted[slot] = 1
        self._context[slot].append(int(first_token))
        self._dirty = True

    # ---------- device work ----------

    def prefill_step(self, slot):
        """Write the next prompt chunk of `slot` through its block
        table. Same contract as SlotEngine.prefill_step: returns
        (tokens_consumed, first_token_or_None)."""
        if not self.active[slot] or self.decoding[slot]:
            raise ValueError("slot %d is not prefilling" % slot)
        prompt = self._prompt[slot]
        start = int(self._prefill_cursor[slot])
        end = min(start + self.prefill_chunk, prompt.size)
        chunk = prompt[start:end]
        # the pad bucket must stay inside the slot's RESERVED pages: a
        # write through a table index past n_pages would be clamped into
        # the last page and silently corrupt live positions
        bucket = bucket_length(
            chunk.size, minimum=self.min_bucket,
            maximum=min(self.prefill_chunk,
                        int(self._n_pages[slot]) * self.page_tokens
                        - start))
        if bucket > chunk.size:
            chunk = np.concatenate([
                chunk, np.full(bucket - chunk.size, self.pad_id, np.int32)])
        logits, self.pool.kv = self._prefill_fn(
            self.params, self.pool.kv, jnp.asarray(chunk)[None],
            jnp.asarray(self.block_tables[slot]), jnp.int32(start))
        self._prefill_cursor[slot] = end
        self.pos[slot] = end
        self._dirty = True
        consumed = end - start
        if end < prompt.size:
            return consumed, None
        first = self._first_fn(
            logits, jnp.int32(prompt.size - 1 - start),
            jnp.asarray(self._keys_for(slot)),
            jnp.float32(self._temp[slot]), jnp.int32(self._top_k[slot]),
            jnp.float32(self._top_p[slot]))
        first = int(first)
        self.decoding[slot] = True
        self.pos[slot] = prompt.size
        self._tok[slot] = first
        self._key_cursor[slot] += 1
        self._emitted[slot] = 1
        self._context[slot].append(first)
        self._dirty = True
        return consumed, first

    def _keys_for(self, slot):
        keys = self._step_keys[slot]
        cursor = int(self._key_cursor[slot])
        if cursor >= len(keys):
            raise ValueError("slot %d ran past its key schedule" % slot)
        return keys[cursor]

    def _stage(self):
        if self._dirty:
            self._d_tok = jnp.asarray(self._tok)
            self._d_pos = jnp.asarray(self.pos)
            self._d_mask = jnp.asarray(self.decoding)
            self._d_tables = jnp.asarray(self.block_tables)
            self._d_temp = jnp.asarray(self._temp)
            self._d_top_k = jnp.asarray(self._top_k)
            self._d_top_p = jnp.asarray(self._top_p)
            self._dirty = False

    def decode_step(self):
        """One fused step over the whole pool. Returns {slot: token}
        (plain path) or {slot: [tokens]} (speculative path — up to
        spec_k+1 tokens per slot per step). The scheduler treats both
        shapes uniformly."""
        decoding = [i for i in range(self.max_slots) if self.decoding[i]]
        if not decoding:
            return {}
        sampled = any(self._temp[i] > 0.0 for i in decoding)
        if self.spec_k > 0 and not sampled:
            return self._spec_decode_step(decoding)
        self._stage()
        if sampled:
            for i in decoding:
                self._keys[i] = self._keys_for(i)
            out, self._d_tok, self._d_pos, self.pool.kv = \
                self._decode_sampled_fn(
                    self.params, self.pool.kv, self._d_tok, self._d_pos,
                    self._d_mask, self._d_tables, jnp.asarray(self._keys),
                    self._d_temp, self._d_top_k, self._d_top_p)
        else:
            out, self._d_tok, self._d_pos, self.pool.kv = \
                self._decode_greedy_fn(
                    self.params, self.pool.kv, self._d_tok, self._d_pos,
                    self._d_mask, self._d_tables)
        out = np.asarray(out)
        tokens = {}
        for i in decoding:
            tokens[i] = int(out[i])
            self._tok[i] = out[i]
            self.pos[i] += 1
            self._key_cursor[i] += 1
            self._emitted[i] += 1
            self._context[i].append(int(out[i]))
        return tokens

    def _spec_decode_step(self, decoding):
        """Speculative decode: draft K tokens per decoding slot
        (self-drafting — prompt-lookup by default, draft_fn pluggable),
        verify all K+1 positions in ONE fused call, keep the prefix the
        target model agrees with. Greedy-only (sampled slots fall back
        to the plain step before reaching here), so acceptance is exact
        token identity: out[j] IS the token sequential greedy decode
        would emit after toks[:j+1]."""
        K = self.spec_k
        B = self.max_slots
        drafts = np.zeros((B, K), np.int32)
        for i in decoding:
            d = self.draft_fn(self._context[i], K)
            drafts[i] = np.asarray(d[:K], np.int32)
        toks = np.concatenate([self._tok[:, None], drafts], axis=1)
        self._stage()
        out, self.pool.kv = self._spec_fn(
            self.params, self.pool.kv, jnp.asarray(toks), self._d_pos,
            self._d_tables)
        out = np.asarray(out)
        tokens = {}
        for i in decoding:
            remaining = int(self._max_new[i] - self._emitted[i])
            n_acc = 0
            while n_acc < K and drafts[i, n_acc] == out[i, n_acc]:
                n_acc += 1
            n_emit = max(1, min(n_acc + 1, remaining))
            emitted = [int(t) for t in out[i, :n_emit]]
            tokens[i] = emitted
            self._tok[i] = emitted[-1]
            self.pos[i] += n_emit
            self._key_cursor[i] += n_emit
            self._emitted[i] += n_emit
            self._context[i].extend(emitted)
            self.spec_proposed += K
            self.spec_accepted += n_acc
        self.spec_steps += 1
        # pos/tok advanced HOST-side (acceptance is data-dependent):
        # restage before the next fused call
        self._dirty = True
        return tokens
