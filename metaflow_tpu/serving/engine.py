"""Slot-based continuous-batching decode engine.

The lockstep generate() path compiles ONE program per (batch, prompt
bucket) and forces every sequence in a batch to start and finish
together — real traffic with mixed prompt/output lengths leaves most of
the MXU idle padding to the slowest request. This engine is the
Orca/vLLM-lineage fix, shaped for TPUs: scheduling happens in Python,
but every device step is one of a FIXED set of jitted programs, so the
compiled-program residency that TPUs reward is preserved.

Layout: a pool of B slots shares one static
[layers, B, max_seq, kv_heads, head_dim] KV cache. Each slot holds at
most one in-flight request and carries host-side state (pos, sampling
knobs, per-token rng keys). Three compiled programs cover everything:

  - prefill: write one PROMPT CHUNK of one slot into the cache
    (single-slot cache view via dynamic_slice on the batch axis; chunk
    padded to a power-of-two bucket, so compiles are bounded by
    log2(prefill_chunk) regardless of prompt-length diversity)
  - decode: advance ALL slots one token in one fused call — per-slot
    positions (vector-pos decode_forward), per-slot dynamic_update_slice
    cache writes, per-slot slot-masked sampling (greedy/temperature/
    top-k/top-p as traced per-slot arrays, so one program serves every
    sampling-config mix)
  - first-token: sample the token the final prefill chunk's logits imply

Slots never wait for each other: a finished slot is released and can be
refilled while its neighbors keep decoding. Free/prefilling slots ride
through the fused decode step as masked lanes — their writes land at
their own cursor and are overwritten (prefill rewrites the range, decode
overwrites pad garbage exactly one position before it would become
visible), so no flag tensor is needed inside the compiled program.

Token identity with generate(): same forward, same sampling ops (the
per-slot sampler reproduces decode._sample row-for-row), same rng policy
(request_step_keys mirrors generate's split sequence), so a request
served through the engine emits exactly the tokens the lockstep path
would give it alone — greedy case bit-exact (pinned by
tests/test_serving.py).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..inference.decode import (
    DECODE_CHUNK,
    bucket_length,
    decode_forward,
    init_kv_cache,
)
from ..ops.attention import NEG_INF


def request_step_keys(rng, max_new_tokens):
    """The per-token rng keys generate() would use: the first token
    samples with split(rng)[1], tokens 1..n-1 with
    split(split(rng)[0], n-1). Returns [max_new_tokens, 2] uint32."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    rng, first = jax.random.split(rng)
    if max_new_tokens > 1:
        rest = jax.random.split(rng, max_new_tokens - 1)
        return np.concatenate(
            [np.asarray(first)[None], np.asarray(rest)], axis=0)
    return np.asarray(first)[None]


def sample_slots(logits, keys, temperature, top_k, top_p):
    """Per-slot sampling: [B, vocab] fp32 logits -> [B] int32, with
    TRACED per-slot knobs (temperature[B], top_k[B] int32 — vocab size
    disables, top_p[B] — 1.0 disables, keys[B, 2] uint32).

    Row-for-row identical to decode._sample with the same scalar knobs:
    same filter order (temperature scale, top_k, exclusive-mass top_p),
    same tie handling, and vmap'd categorical over per-slot keys matches
    the single-key batch-of-one call bit-for-bit."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    is_greedy = temperature <= 0.0
    safe_t = jnp.where(is_greedy, 1.0, temperature)
    lt = logits / safe_t[:, None]
    k = jnp.clip(top_k, 1, V)
    sorted_desc = -jnp.sort(-lt, axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    lt = jnp.where((k < V)[:, None] & (lt < kth), NEG_INF, lt)
    order = jnp.argsort(-lt, axis=-1)
    sorted_logits = jnp.take_along_axis(lt, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # EXCLUSIVE cumulative mass (decode._sample): the top token survives
    before = jnp.cumsum(probs, axis=-1) - probs
    drop_sorted = before >= top_p[:, None]
    drop = jnp.zeros_like(drop_sorted).at[
        jnp.arange(B)[:, None], order].set(drop_sorted)
    lt = jnp.where((top_p < 1.0)[:, None] & drop, NEG_INF, lt)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row))(keys, lt)
    return jnp.where(is_greedy, greedy, sampled.astype(jnp.int32))


class SlotEngine(object):
    """Fixed pool of decode slots over one shared static KV cache.

    Host-side bookkeeping (which slot holds which request, positions,
    sampling knobs) lives in numpy arrays; device work goes through the
    three jitted programs described in the module docstring. The engine
    is NOT thread-safe — exactly one scheduler loop drives it.
    """

    def __init__(self, params, cfg, max_slots=8, max_seq_len=None,
                 prefill_chunk=64, mesh=None, attn_impl="auto",
                 cache_dtype=None, pad_id=0, min_bucket=16):
        if attn_impl not in ("auto", "dense", "chunked"):
            raise ValueError("attn_impl must be 'auto', 'dense' or "
                             "'chunked', got %r" % (attn_impl,))
        self.params = params
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        self.prefill_chunk = int(prefill_chunk)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if self.prefill_chunk < 1:
            # a 0-chunk engine would admit requests and never prefill
            # them: the scheduler loop idles forever with full slots
            raise ValueError("prefill_chunk must be >= 1, got %d"
                             % self.prefill_chunk)
        self.pad_id = int(pad_id)
        self.min_bucket = min(int(min_bucket), self.prefill_chunk)
        self.mesh = mesh
        if attn_impl == "auto":
            attn_impl = ("chunked" if self.max_seq_len > 2 * DECODE_CHUNK
                         else "dense")
        self.attn_impl = attn_impl
        self._vocab = cfg.vocab_size

        self._cache = init_kv_cache(cfg, self.max_slots, self.max_seq_len,
                                    dtype=cache_dtype)
        B = self.max_slots
        # host-side per-slot state
        self.pos = np.zeros(B, np.int32)          # next cache write index
        self.active = np.zeros(B, bool)           # slot holds a request
        self.decoding = np.zeros(B, bool)         # past prefill
        self._tok = np.zeros(B, np.int32)         # last emitted token
        self._temp = np.zeros(B, np.float32)
        self._top_k = np.full(B, self._vocab, np.int32)
        self._top_p = np.ones(B, np.float32)
        self._keys = np.zeros((B, 2), np.uint32)  # current step key
        self._step_keys = [None] * B              # [max_new, 2] per slot
        self._slot_ctx = [None] * B               # request trace context
        self._key_cursor = np.zeros(B, np.int32)
        self._prompt = [None] * B                 # remaining host prompt
        self._prefill_cursor = np.zeros(B, np.int32)
        # device mirrors of the decode-step inputs: steady-state decode
        # re-uploads NOTHING (the jitted step advances tok/pos on device);
        # slot membership or sampling-knob changes set _dirty and the
        # next step re-stages from the host arrays above
        self._dirty = True
        self._d_tok = self._d_pos = self._d_mask = None
        self._d_temp = self._d_top_k = self._d_top_p = None

        def _prefill(params, cache, chunk_tokens, slot, start):
            sub = {
                name: jax.lax.dynamic_slice_in_dim(arr, slot, 1, axis=1)
                for name, arr in cache.items()
            }
            logits, sub = decode_forward(
                params, chunk_tokens, sub, start, cfg, mesh=mesh,
                attn_impl=self.attn_impl)
            cache = {
                name: jax.lax.dynamic_update_slice_in_dim(
                    cache[name], sub[name], slot, axis=1)
                for name in cache
            }
            return logits, cache

        def _advance(nxt, tok, pos, mask):
            # decoding lanes take the new token and move their cursor;
            # masked lanes (free / mid-prefill) hold still — the SAME
            # update runs on the host mirrors, so no download is needed
            tok = jnp.where(mask, nxt, tok)
            pos = pos + mask.astype(jnp.int32)
            return tok, pos

        def _decode_sampled(params, cache, tok, pos, mask, keys, temp,
                            top_k, top_p):
            logits, cache = decode_forward(
                params, tok[:, None], cache, pos, cfg, mesh=mesh,
                attn_impl=self.attn_impl)
            nxt = sample_slots(logits[:, 0], keys, temp, top_k, top_p)
            tok, pos = _advance(nxt, tok, pos, mask)
            return nxt, tok, pos, cache

        def _decode_greedy(params, cache, tok, pos, mask):
            # static fast path when every active slot is greedy: the full
            # per-slot sampler (two sorts + scatter per step) costs ~2x a
            # tiny forward on CPU; greedy traffic must not pay it
            logits, cache = decode_forward(
                params, tok[:, None], cache, pos, cfg, mesh=mesh,
                attn_impl=self.attn_impl)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            tok, pos = _advance(nxt, tok, pos, mask)
            return nxt, tok, pos, cache

        def _first_token(logits, idx, key, temp, top_k, top_p):
            last = jax.lax.dynamic_index_in_dim(logits, idx, axis=1,
                                                keepdims=False)
            return sample_slots(last, key[None], temp[None], top_k[None],
                                top_p[None])[0]

        def _seed(cache, k, v, slot):
            # write a [layers, T, kv_heads, head_dim] KV range into one
            # slot's cache view starting at position 0; slot is TRACED
            # so compiles are bounded by the T bucket, not the pool size
            cache_k = jax.lax.dynamic_update_slice(
                cache["k"], k[:, None], (0, slot, 0, 0, 0))
            cache_v = jax.lax.dynamic_update_slice(
                cache["v"], v[:, None], (0, slot, 0, 0, 0))
            return {"k": cache_k, "v": cache_v}

        def _extract(cache, slot, T):
            # read the first T positions of one slot's view; T is STATIC
            # (callers pass a power-of-two bucket and trim on host)
            L = cache["k"].shape[0]
            KV, HD = cache["k"].shape[3], cache["k"].shape[4]
            k = jax.lax.dynamic_slice(
                cache["k"], (0, slot, 0, 0, 0), (L, 1, T, KV, HD))
            v = jax.lax.dynamic_slice(
                cache["v"], (0, slot, 0, 0, 0), (L, 1, T, KV, HD))
            return k[:, 0], v[:, 0]

        # the cache is donated: the pool's KV state is the single largest
        # buffer and every call replaces it wholesale
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_sampled_fn = jax.jit(_decode_sampled,
                                          donate_argnums=(1,))
        self._decode_greedy_fn = jax.jit(_decode_greedy,
                                         donate_argnums=(1,))
        self._first_fn = jax.jit(_first_token)
        self._seed_fn = jax.jit(_seed, donate_argnums=(0,))
        # no donation: the pool cache must survive an extraction
        self._extract_fn = jax.jit(_extract, static_argnums=(2,))

    # ---------- pool state ----------

    def free_slots(self):
        return [i for i in range(self.max_slots) if not self.active[i]]

    def occupancy(self):
        return float(self.active.sum()) / self.max_slots

    def fits(self, prompt_len, max_new_tokens):
        """Could this request EVER be admitted? False is a permanent
        413 at submit time (the scheduler's admission capacity check),
        not backpressure."""
        return prompt_len + max_new_tokens <= self.max_seq_len

    def max_context_tokens(self):
        """The largest prompt+max_new any request may carry — the
        scalar the fleet router sheds oversized dispatches against."""
        return self.max_seq_len

    def compile_counts(self):
        """jit cache entries per program — each decode variant must stay
        at <= 1, prefill at <= number of chunk buckets."""
        return {
            "prefill": self._prefill_fn._cache_size(),
            "decode_greedy": self._decode_greedy_fn._cache_size(),
            "decode_sampled": self._decode_sampled_fn._cache_size(),
            "first_token": self._first_fn._cache_size(),
            "seed_prefix": self._seed_fn._cache_size(),
            "extract_kv": self._extract_fn._cache_size(),
        }

    def kv_token_bytes(self):
        """Host bytes one cached token costs (k + v across layers) —
        the unit the prefix-cache byte budget is denominated in."""
        k = self._cache["k"]
        layers, _, _, kv_heads, head_dim = k.shape
        return 2 * layers * kv_heads * head_dim * k.dtype.itemsize

    # ---------- slot lifecycle ----------

    def admit(self, slot, prompt_tokens, max_new_tokens, temperature=0.0,
              top_k=None, top_p=None, rng=0):
        """Bind a request to a free slot; prefill starts on the next
        prefill_step calls. prompt_tokens: 1-D int sequence."""
        if self.active[slot]:
            raise ValueError("slot %d is busy" % slot)
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size + max_new_tokens > self.max_seq_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the engine's "
                "max_seq_len (%d)" % (prompt.size, max_new_tokens,
                                      self.max_seq_len))
        self.active[slot] = True
        self.decoding[slot] = False
        self.pos[slot] = 0
        self._prompt[slot] = prompt
        self._prefill_cursor[slot] = 0
        self._temp[slot] = float(temperature)
        self._top_k[slot] = (self._vocab if top_k is None
                             else min(int(top_k), self._vocab))
        self._top_p[slot] = 1.0 if top_p is None else float(top_p)
        self._step_keys[slot] = request_step_keys(rng, max_new_tokens)
        self._key_cursor[slot] = 0
        self._dirty = True

    def bind_slot_context(self, slot, ctx):
        """Attach the occupant's identity/trace context ({"request_id",
        "trace", "span"} from the scheduler) to a slot. The engine is
        the system of record for slot->request binding, so engine-level
        instrumentation (the serve.prefill_chunk device timer, future
        per-slot profiling hooks) attributes device work to the request
        that bought it."""
        self._slot_ctx[slot] = dict(ctx) if ctx else None

    def slot_context(self, slot):
        """The context bound at admit time, or None for a free slot."""
        return self._slot_ctx[slot]

    def seed_prefix(self, slot, kv):
        """Copy a cached KV range ({"k": [layers, T, kv_heads,
        head_dim], "v": ...}, host arrays) into the slot's cache view at
        positions [0, T) and move the prefill cursor to T, so chunked
        prefill resumes at the match boundary. Must run after admit(),
        before the first prefill_step; T must be < the slot's prompt
        length (at least one token has to prefill so final-chunk logits
        exist for first-token sampling).

        The upload pads T to a power-of-two bucket (compiles stay
        log2-bounded); pad positions hold garbage that is overwritten
        before it becomes visible — by the resumed prefill chunks up to
        the prompt end, and by the decode-step write at pos beyond it —
        the same invariant masked lanes already rely on."""
        if not self.active[slot] or self.decoding[slot]:
            raise ValueError("slot %d is not prefilling" % slot)
        if int(self._prefill_cursor[slot]) != 0:
            raise ValueError("slot %d already started prefill" % slot)
        k, v = np.asarray(kv["k"]), np.asarray(kv["v"])
        T = k.shape[1]
        prompt = self._prompt[slot]
        if not (0 < T < prompt.size):
            raise ValueError(
                "seed length %d must be in [1, prompt %d)"
                % (T, prompt.size))
        bucket = bucket_length(T, minimum=self.min_bucket,
                               maximum=self.max_seq_len)
        if bucket > T:
            pad = [(0, 0), (0, bucket - T), (0, 0), (0, 0)]
            k = np.pad(k, pad)
            v = np.pad(v, pad)
        dtype = self._cache["k"].dtype
        self._cache = self._seed_fn(
            self._cache, jnp.asarray(k, dtype), jnp.asarray(v, dtype),
            jnp.int32(slot))
        self._prefill_cursor[slot] = T
        self.pos[slot] = T
        self._dirty = True

    def extract_kv(self, slot, length):
        """The first `length` cache positions of a slot as host arrays
        ({"k": [layers, length, kv_heads, head_dim], "v": ...}) — the
        prefix-cache insert / disaggregation handoff read path. The
        device slice uses a power-of-two bucket (static shape, bounded
        compiles) and trims on host."""
        if length < 1 or length > self.max_seq_len:
            raise ValueError("length %d out of range" % length)
        bucket = bucket_length(length, minimum=self.min_bucket,
                               maximum=self.max_seq_len)
        k, v = self._extract_fn(self._cache, jnp.int32(slot), bucket)
        return {"k": np.asarray(k)[:, :length],
                "v": np.asarray(v)[:, :length]}

    def admit_prefilled(self, slot, prompt_tokens, first_token, kv,
                        max_new_tokens, temperature=0.0, top_k=None,
                        top_p=None, rng=0):
        """Bind a request whose prefill ALREADY happened elsewhere (a
        dedicated prefill worker): seed the full prompt's KV, accept the
        first sampled token, and enter the decode state directly. With
        the same (prompt, knobs, rng), the continued decode emits
        exactly the tokens a local prefill would — the key schedule
        resumes at cursor 1, mirroring prefill_step's final chunk."""
        self.admit(slot, prompt_tokens, max_new_tokens,
                   temperature=temperature, top_k=top_k, top_p=top_p,
                   rng=rng)
        prompt = self._prompt[slot]
        k = np.asarray(kv["k"])
        if k.shape[1] != prompt.size:
            self.release(slot)
            raise ValueError("handoff kv length %d != prompt %d"
                             % (k.shape[1], prompt.size))
        bucket = bucket_length(prompt.size, minimum=self.min_bucket,
                               maximum=self.max_seq_len)
        v = np.asarray(kv["v"])
        if bucket > prompt.size:
            pad = [(0, 0), (0, bucket - prompt.size), (0, 0), (0, 0)]
            k, v = np.pad(k, pad), np.pad(v, pad)
        dtype = self._cache["k"].dtype
        self._cache = self._seed_fn(
            self._cache, jnp.asarray(k, dtype), jnp.asarray(v, dtype),
            jnp.int32(slot))
        self._prefill_cursor[slot] = prompt.size
        self.decoding[slot] = True
        self.pos[slot] = prompt.size
        self._tok[slot] = int(first_token)
        self._key_cursor[slot] = 1
        self._dirty = True

    def release(self, slot):
        """Reclaim a slot immediately; the stale cache contents stay and
        are overwritten by the next occupant's prefill."""
        self.active[slot] = False
        self._slot_ctx[slot] = None
        self.decoding[slot] = False
        self.pos[slot] = 0  # park the masked-lane write cursor
        self._prompt[slot] = None
        self._step_keys[slot] = None
        self._temp[slot] = 0.0
        self._top_k[slot] = self._vocab
        self._top_p[slot] = 1.0
        self._dirty = True

    # ---------- device work ----------

    def prefill_step(self, slot):
        """Write the next prompt chunk of `slot` into the cache.

        Returns (tokens_consumed, first_token_or_None): first_token is
        the request's first sampled token, produced when the final chunk
        lands (chunked prefill — long prompts spread over several calls
        so decode steps for other slots interleave between them)."""
        if not self.active[slot] or self.decoding[slot]:
            raise ValueError("slot %d is not prefilling" % slot)
        prompt = self._prompt[slot]
        start = int(self._prefill_cursor[slot])
        end = min(start + self.prefill_chunk, prompt.size)
        chunk = prompt[start:end]
        # cap the pad bucket at the cache edge: a bucketed write spilling
        # past max_seq would be CLAMPED by dynamic_update_slice and
        # silently rewrite earlier live positions
        bucket = bucket_length(
            chunk.size, minimum=self.min_bucket,
            maximum=min(self.prefill_chunk, self.max_seq_len - start))
        if bucket > chunk.size:
            chunk = np.concatenate([
                chunk, np.full(bucket - chunk.size, self.pad_id, np.int32)])
        logits, self._cache = self._prefill_fn(
            self.params, self._cache, jnp.asarray(chunk)[None],
            jnp.int32(slot), jnp.int32(start))
        self._prefill_cursor[slot] = end
        # keep pos at the prefill cursor: a mid-prefill slot rides
        # through fused decode steps as a masked lane whose write lands
        # at pos — it must fall where the NEXT chunk overwrites it, not
        # on already-written positions
        self.pos[slot] = end
        self._dirty = True
        consumed = end - start
        if end < prompt.size:
            return consumed, None
        # final chunk: the first generated token comes off these logits
        first = self._first_fn(
            logits, jnp.int32(prompt.size - 1 - start),
            jnp.asarray(self._keys_for(slot)),
            jnp.float32(self._temp[slot]), jnp.int32(self._top_k[slot]),
            jnp.float32(self._top_p[slot]))
        first = int(first)
        self.decoding[slot] = True
        self.pos[slot] = prompt.size
        self._tok[slot] = first
        self._key_cursor[slot] += 1
        self._dirty = True
        return consumed, first

    def _keys_for(self, slot):
        keys = self._step_keys[slot]
        cursor = int(self._key_cursor[slot])
        if cursor >= len(keys):
            raise ValueError("slot %d ran past its key schedule" % slot)
        return keys[cursor]

    def decode_step(self):
        """One fused decode step over the WHOLE pool. Returns a dict
        {slot: token} for slots in the decode state; other slots ride
        through as masked lanes (their writes are overwritten before
        becoming visible). Advances pos/key cursors for decoding slots
        only.

        Steady state stays on device: tok/pos flow out of one jitted call
        and back into the next; only the per-step sampling keys upload
        (and only when a sampled slot is active). Host mirrors replay the
        same masked advance, so they stay exact without a download."""
        decoding = [i for i in range(self.max_slots) if self.decoding[i]]
        if not decoding:
            return {}
        if self._dirty:
            self._d_tok = jnp.asarray(self._tok)
            self._d_pos = jnp.asarray(self.pos)
            self._d_mask = jnp.asarray(self.decoding)
            self._d_temp = jnp.asarray(self._temp)
            self._d_top_k = jnp.asarray(self._top_k)
            self._d_top_p = jnp.asarray(self._top_p)
            self._dirty = False
        if any(self._temp[i] > 0.0 for i in decoding):
            for i in decoding:
                self._keys[i] = self._keys_for(i)
            out, self._d_tok, self._d_pos, self._cache = \
                self._decode_sampled_fn(
                    self.params, self._cache, self._d_tok, self._d_pos,
                    self._d_mask, jnp.asarray(self._keys), self._d_temp,
                    self._d_top_k, self._d_top_p)
        else:
            out, self._d_tok, self._d_pos, self._cache = \
                self._decode_greedy_fn(
                    self.params, self._cache, self._d_tok, self._d_pos,
                    self._d_mask)
        out = np.asarray(out)
        tokens = {}
        for i in decoding:
            tokens[i] = int(out[i])
            self._tok[i] = out[i]
            self.pos[i] += 1
            self._key_cursor[i] += 1
        return tokens
