"""Radix prefix cache: reusable KV ranges keyed by prompt token prefixes.

Serving traffic is dominated by shared prefixes — a fleet-wide system
prompt, few-shot templates, multi-turn histories that re-send the whole
conversation. Cold prefill recomputes the KV projections for every one
of those tokens on every request even though, for a causal model, the
KV state of a prefix depends ONLY on the prefix tokens themselves.
This module is the SGLang/vLLM-lineage fix: a compressed radix tree
over token sequences whose nodes carry the host-side KV arrays for
their edge tokens. On admit the scheduler looks up the longest cached
prefix, seeds the slot's KV-cache view with it (SlotEngine.seed_prefix)
and starts chunked prefill at the match boundary; after a finished
prefill it inserts the slot's KV back (SlotEngine.extract_kv) so the
next request sharing the prefix hits.

Identity guarantee: the cached arrays are bitwise what cold prefill
wrote for those positions, and resuming chunked prefill at a different
boundary preserves numerics (the same property the chunked-prefill
identity tests already pin), so a cache-hit request emits exactly the
tokens a cold one would — greedy and sampled alike, since sampling only
consumes logits and the request's own rng schedule.

Concurrency/safety model: match() returns a PIN — every node on the
matched path is ref-counted until release(), so LRU eviction (byte
budget, leaf-first) can never free KV that an in-flight request still
depends on. The scheduler releases the pin when the request finishes
prefill or dies (cancel/deadline/shutdown); a leaked pin would show up
as pinned_nodes() > 0 with an idle engine, which tests assert against.

Node splits keep handles valid: the matched node OBJECT stays the
deeper (suffix) node and handles capture numpy views of the KV at match
time, so a later split neither moves a pin nor invalidates captured
arrays.
"""

import os
import threading

import numpy as np

from .. import telemetry


def _as_tokens(tokens):
    return np.asarray(tokens, np.int32).reshape(-1)


def _common_prefix(a, b):
    n = min(a.size, b.size)
    if n == 0:
        return 0
    eq = a[:n] == b[:n]
    if eq.all():
        return n
    return int(np.argmin(eq))


class _Node(object):
    __slots__ = ("tokens", "k", "v", "children", "parent", "refs",
                 "last_use")

    def __init__(self, tokens, k, v, parent):
        self.tokens = tokens          # np.int32 [T] edge labels
        self.k = k                    # np [layers, T, kv_heads, head_dim]
        self.v = v
        self.children = {}            # first token -> _Node
        self.parent = parent
        self.refs = 0
        self.last_use = 0

    def nbytes(self):
        if self.k is None:
            return 0
        return int(self.k.nbytes) + int(self.v.nbytes)


class PrefixHandle(object):
    """A pinned match: `length` cached tokens and the KV that backs
    them. Hold it until the request is past prefill (or dead), then
    release() exactly once."""

    __slots__ = ("_nodes", "_parts", "length", "_released")

    def __init__(self, nodes, parts, length):
        self._nodes = nodes           # pinned path, root-exclusive
        self._parts = parts           # [(k_view, v_view), ...] in order
        self.length = length
        self._released = False

    def kv(self):
        """{"k": [layers, length, kv_heads, head_dim], "v": ...} — the
        cached KV for the matched prefix, concatenated host-side."""
        ks = [p[0] for p in self._parts]
        vs = [p[1] for p in self._parts]
        if len(ks) == 1:
            return {"k": ks[0], "v": vs[0]}
        return {"k": np.concatenate(ks, axis=1),
                "v": np.concatenate(vs, axis=1)}


class RadixPrefixCache(object):
    """Compressed radix tree over prompt tokens with per-node KV ranges,
    ref-count pinning and LRU leaf eviction under a byte budget."""

    def __init__(self, max_bytes):
        self.max_bytes = int(max_bytes)
        if self.max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self._root = _Node(np.zeros(0, np.int32), None, None, None)
        self._lock = threading.Lock()
        self._clock = 0
        self._bytes = 0
        self._nodes = 0
        self._tokens = 0
        self._evicted_nodes = 0
        self._evicted_tokens = 0
        self._evictions = 0           # evict() sweeps that freed memory

    @classmethod
    def from_env(cls, default_mb=0):
        """Build from TPUFLOW_PREFIX_CACHE_MB, or None when the budget
        is 0 (the cache is opt-in: no budget, no cache)."""
        mb = float(os.environ.get("TPUFLOW_PREFIX_CACHE_MB", default_mb))
        if mb <= 0:
            return None
        return cls(int(mb * 1024 * 1024))

    # ---------- lookup ----------

    def match(self, tokens):
        """Longest cached prefix of `tokens`: a pinned PrefixHandle, or
        None on a zero-length match. Callers cap reuse themselves (the
        scheduler matches prompt[:-1] so at least one token prefills and
        final-chunk logits exist for first-token sampling)."""
        tokens = _as_tokens(tokens)
        with self._lock:
            self._clock += 1
            node = self._root
            i = 0
            nodes, parts = [], []
            while i < tokens.size:
                child = node.children.get(int(tokens[i]))
                if child is None:
                    break
                common = _common_prefix(child.tokens, tokens[i:])
                if common == 0:
                    break
                child.last_use = self._clock
                nodes.append(child)
                parts.append((child.k[:, :common], child.v[:, :common]))
                i += common
                if common < child.tokens.size:
                    break
                node = child
            if i == 0:
                return None
            for n in nodes:
                n.refs += 1
            return PrefixHandle(nodes, parts, i)

    def release(self, handle):
        """Drop a match's pins. Idempotent per handle."""
        if handle is None or handle._released:
            return
        handle._released = True
        with self._lock:
            for n in handle._nodes:
                n.refs -= 1

    # ---------- insert / evict ----------

    def insert(self, tokens, kv):
        """Cache the KV for `tokens` (kv: {"k": [layers, T, kv_heads,
        head_dim], "v": ...}, T == len(tokens)). Shared prefixes with
        existing entries are deduplicated via node splits; only the
        novel suffix adds bytes. Evicts LRU leaves if over budget."""
        tokens = _as_tokens(tokens)
        k, v = kv["k"], kv["v"]
        if k.shape[1] != tokens.size:
            raise ValueError("kv length %d != token count %d"
                             % (k.shape[1], tokens.size))
        with self._lock:
            self._clock += 1
            node = self._root
            i = 0
            while i < tokens.size:
                child = node.children.get(int(tokens[i]))
                if child is None:
                    # copy the suffix: a view would pin the caller's FULL
                    # prompt-KV buffer, breaking the byte-budget accounting
                    leaf = _Node(tokens[i:].copy(), k[:, i:].copy(),
                                 v[:, i:].copy(), node)
                    leaf.last_use = self._clock
                    node.children[int(tokens[i])] = leaf
                    self._bytes += leaf.nbytes()
                    self._nodes += 1
                    self._tokens += int(leaf.tokens.size)
                    break
                child.last_use = self._clock
                common = _common_prefix(child.tokens, tokens[i:])
                if common < child.tokens.size:
                    # split the edge: a NEW prefix node takes the head;
                    # `child` (possibly pinned) keeps its object identity
                    # and becomes the suffix below it
                    mid = _Node(child.tokens[:common], child.k[:, :common],
                                child.v[:, :common], node)
                    mid.last_use = self._clock
                    node.children[int(child.tokens[0])] = mid
                    child.tokens = child.tokens[common:]
                    child.k = child.k[:, common:]
                    child.v = child.v[:, common:]
                    child.parent = mid
                    mid.children[int(child.tokens[0])] = child
                    self._nodes += 1
                    node = mid
                    i += common
                    continue
                node = child
                i += common
            self._evict_locked()

    def _evict_locked(self):
        freed_nodes = freed_tokens = freed_bytes = 0
        while self._bytes > self.max_bytes:
            victim = None
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n is self._root or n.children or n.refs > 0:
                    continue
                if victim is None or n.last_use < victim.last_use:
                    victim = n
            if victim is None:
                break  # everything left is pinned or interior
            victim.parent.children.pop(int(victim.tokens[0]))
            nb = victim.nbytes()
            self._bytes -= nb
            self._nodes -= 1
            self._tokens -= int(victim.tokens.size)
            freed_nodes += 1
            freed_tokens += int(victim.tokens.size)
            freed_bytes += nb
        if freed_nodes:
            self._evictions += 1
            self._evicted_nodes += freed_nodes
            self._evicted_tokens += freed_tokens
            telemetry.event("serve.prefix.evict", data={
                "nodes": freed_nodes, "tokens": freed_tokens,
                "bytes": freed_bytes})

    # ---------- introspection ----------

    def pinned_nodes(self):
        with self._lock:
            count = 0
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n is not self._root and n.refs > 0:
                    count += 1
            return count

    def stats(self):
        with self._lock:
            return {
                "nodes": self._nodes,
                "cached_tokens": self._tokens,
                "cached_bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "evictions": self._evictions,
                "evicted_nodes": self._evicted_nodes,
                "evicted_tokens": self._evicted_tokens,
            }
