"""Radix prefix cache: reusable KV ranges keyed by prompt token prefixes.

Serving traffic is dominated by shared prefixes — a fleet-wide system
prompt, few-shot templates, multi-turn histories that re-send the whole
conversation. Cold prefill recomputes the KV projections for every one
of those tokens on every request even though, for a causal model, the
KV state of a prefix depends ONLY on the prefix tokens themselves.
This module is the SGLang/vLLM-lineage fix: a compressed radix tree
over token sequences whose nodes carry the host-side KV arrays for
their edge tokens. On admit the scheduler looks up the longest cached
prefix, seeds the slot's KV-cache view with it (SlotEngine.seed_prefix)
and starts chunked prefill at the match boundary; after a finished
prefill it inserts the slot's KV back (SlotEngine.extract_kv) so the
next request sharing the prefix hits.

Identity guarantee: the cached arrays are bitwise what cold prefill
wrote for those positions, and resuming chunked prefill at a different
boundary preserves numerics (the same property the chunked-prefill
identity tests already pin), so a cache-hit request emits exactly the
tokens a cold one would — greedy and sampled alike, since sampling only
consumes logits and the request's own rng schedule.

Concurrency/safety model: match() returns a PIN — every node on the
matched path is ref-counted until release(), so LRU eviction (byte
budget, leaf-first) can never free KV that an in-flight request still
depends on. The scheduler releases the pin when the request finishes
prefill or dies (cancel/deadline/shutdown); a leaked pin would show up
as pinned_nodes() > 0 with an idle engine, which tests assert against.

Node splits keep handles valid: the matched node OBJECT stays the
deeper (suffix) node and handles capture numpy views of the KV at match
time, so a later split neither moves a pin nor invalidates captured
arrays.
"""

import hashlib
import os
import threading

import numpy as np

from .. import knobs, telemetry


def _as_tokens(tokens):
    return np.asarray(tokens, np.int32).reshape(-1)


def _common_prefix(a, b):
    n = min(a.size, b.size)
    if n == 0:
        return 0
    eq = a[:n] == b[:n]
    if eq.all():
        return n
    return int(np.argmin(eq))


# ---------------------------------------------------------------------------
# Routing digests: the compact prefix summary replicas publish through
# /healthz so the fleet router can score "who already holds this prompt's
# longest prefix" without shipping token sequences over the wire. The
# vocabulary is a rolling sha1 chain over BLOCK-aligned token blocks —
# identical to the paged index's page-key chain, so for a paged replica
# the published digests ARE its cached page keys. A digest identifies
# both content and position (the chain folds in everything before it),
# so set-membership of the request's chain against a replica's digest
# set is exactly "this block-aligned prefix is cached there".
# ---------------------------------------------------------------------------

ROUTE_DIGEST_HEX = 16     # published hex chars per digest (64-bit)


def _chain_key(prev_key, tokens):
    h = hashlib.sha1(prev_key)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


def route_digest_chain(tokens, block):
    """The rolling block-digest chain of a token sequence: one hex
    digest per complete `block`-token prefix, in prefix order. The
    router computes this for a request's prompt; replicas publish the
    same chains for their cached prefixes."""
    tokens = _as_tokens(tokens)
    block = int(block)
    if block <= 0:
        return []
    out = []
    key = b"root"
    for i in range(tokens.size // block):
        key = _chain_key(key, tokens[i * block:(i + 1) * block])
        out.append(key.hex()[:ROUTE_DIGEST_HEX])
    return out


class _Node(object):
    __slots__ = ("tokens", "k", "v", "children", "parent", "refs",
                 "last_use")

    def __init__(self, tokens, k, v, parent):
        self.tokens = tokens          # np.int32 [T] edge labels
        self.k = k                    # np [layers, T, kv_heads, head_dim]
        self.v = v
        self.children = {}            # first token -> _Node
        self.parent = parent
        self.refs = 0
        self.last_use = 0

    def nbytes(self):
        if self.k is None:
            return 0
        return int(self.k.nbytes) + int(self.v.nbytes)


class PrefixHandle(object):
    """A pinned match: `length` cached tokens and the KV that backs
    them. Hold it until the request is past prefill (or dead), then
    release() exactly once."""

    __slots__ = ("_nodes", "_parts", "length", "_released")

    def __init__(self, nodes, parts, length):
        self._nodes = nodes           # pinned path, root-exclusive
        self._parts = parts           # [(k_view, v_view), ...] in order
        self.length = length
        self._released = False

    def kv(self):
        """{"k": [layers, length, kv_heads, head_dim], "v": ...} — the
        cached KV for the matched prefix, concatenated host-side."""
        ks = [p[0] for p in self._parts]
        vs = [p[1] for p in self._parts]
        if len(ks) == 1:
            return {"k": ks[0], "v": vs[0]}
        return {"k": np.concatenate(ks, axis=1),
                "v": np.concatenate(vs, axis=1)}


class RadixPrefixCache(object):
    """Compressed radix tree over prompt tokens with per-node KV ranges,
    ref-count pinning and LRU leaf eviction under a byte budget."""

    def __init__(self, max_bytes):
        self.max_bytes = int(max_bytes)
        if self.max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self._root = _Node(np.zeros(0, np.int32), None, None, None)
        self._lock = threading.Lock()
        self._clock = 0
        self._bytes = 0
        self._nodes = 0
        self._tokens = 0
        self._evicted_nodes = 0
        self._evicted_tokens = 0
        self._evictions = 0           # evict() sweeps that freed memory

    @classmethod
    def from_env(cls, default_mb=0):
        """Build from TPUFLOW_PREFIX_CACHE_MB, or None when the budget
        is 0 (the cache is opt-in: no budget, no cache)."""
        mb = knobs.get_float("TPUFLOW_PREFIX_CACHE_MB",
                             fallback=default_mb)
        if mb <= 0:
            return None
        return cls(int(mb * 1024 * 1024))

    # ---------- lookup ----------

    def match(self, tokens):
        """Longest cached prefix of `tokens`: a pinned PrefixHandle, or
        None on a zero-length match. Callers cap reuse themselves (the
        scheduler matches prompt[:-1] so at least one token prefills and
        final-chunk logits exist for first-token sampling)."""
        tokens = _as_tokens(tokens)
        with self._lock:
            self._clock += 1
            node = self._root
            i = 0
            nodes, parts = [], []
            while i < tokens.size:
                child = node.children.get(int(tokens[i]))
                if child is None:
                    break
                common = _common_prefix(child.tokens, tokens[i:])
                if common == 0:
                    break
                child.last_use = self._clock
                nodes.append(child)
                parts.append((child.k[:, :common], child.v[:, :common]))
                i += common
                if common < child.tokens.size:
                    break
                node = child
            if i == 0:
                return None
            for n in nodes:
                n.refs += 1
            return PrefixHandle(nodes, parts, i)

    def release(self, handle):
        """Drop a match's pins. Idempotent per handle."""
        if handle is None or handle._released:
            return
        handle._released = True
        with self._lock:
            for n in handle._nodes:
                n.refs -= 1

    # ---------- insert / evict ----------

    def insert(self, tokens, kv):
        """Cache the KV for `tokens` (kv: {"k": [layers, T, kv_heads,
        head_dim], "v": ...}, T == len(tokens)). Shared prefixes with
        existing entries are deduplicated via node splits; only the
        novel suffix adds bytes. Evicts LRU leaves if over budget."""
        tokens = _as_tokens(tokens)
        k, v = kv["k"], kv["v"]
        if k.shape[1] != tokens.size:
            raise ValueError("kv length %d != token count %d"
                             % (k.shape[1], tokens.size))
        with self._lock:
            self._clock += 1
            node = self._root
            i = 0
            while i < tokens.size:
                child = node.children.get(int(tokens[i]))
                if child is None:
                    # copy the suffix: a view would pin the caller's FULL
                    # prompt-KV buffer, breaking the byte-budget accounting
                    leaf = _Node(tokens[i:].copy(), k[:, i:].copy(),
                                 v[:, i:].copy(), node)
                    leaf.last_use = self._clock
                    node.children[int(tokens[i])] = leaf
                    self._bytes += leaf.nbytes()
                    self._nodes += 1
                    self._tokens += int(leaf.tokens.size)
                    break
                child.last_use = self._clock
                common = _common_prefix(child.tokens, tokens[i:])
                if common < child.tokens.size:
                    # split the edge: a NEW prefix node takes the head;
                    # `child` (possibly pinned) keeps its object identity
                    # and becomes the suffix below it
                    mid = _Node(child.tokens[:common], child.k[:, :common],
                                child.v[:, :common], node)
                    mid.last_use = self._clock
                    node.children[int(child.tokens[0])] = mid
                    child.tokens = child.tokens[common:]
                    child.k = child.k[:, common:]
                    child.v = child.v[:, common:]
                    child.parent = mid
                    mid.children[int(child.tokens[0])] = child
                    self._nodes += 1
                    node = mid
                    i += common
                    continue
                node = child
                i += common
            self._evict_locked()

    def _evict_locked(self):
        freed_nodes = freed_tokens = freed_bytes = 0
        while self._bytes > self.max_bytes:
            victim = None
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n is self._root or n.children or n.refs > 0:
                    continue
                if victim is None or n.last_use < victim.last_use:
                    victim = n
            if victim is None:
                break  # everything left is pinned or interior
            victim.parent.children.pop(int(victim.tokens[0]))
            nb = victim.nbytes()
            self._bytes -= nb
            self._nodes -= 1
            self._tokens -= int(victim.tokens.size)
            freed_nodes += 1
            freed_tokens += int(victim.tokens.size)
            freed_bytes += nb
        if freed_nodes:
            self._evictions += 1
            self._evicted_nodes += freed_nodes
            self._evicted_tokens += freed_tokens
            telemetry.event("serve.prefix.evict", data={
                "nodes": freed_nodes, "tokens": freed_tokens,
                "bytes": freed_bytes})

    # ---------- introspection ----------

    def pinned_nodes(self):
        with self._lock:
            count = 0
            stack = [self._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n is not self._root and n.refs > 0:
                    count += 1
            return count

    def stats(self):
        with self._lock:
            return {
                "nodes": self._nodes,
                "cached_tokens": self._tokens,
                "cached_bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "evictions": self._evictions,
                "evicted_nodes": self._evicted_nodes,
                "evicted_tokens": self._evicted_tokens,
            }

    def route_digests(self, block, limit=512):
        """Block-digest summary of every cached prefix (newest-capped):
        the compact routing vocabulary published through /healthz. A
        radix edge can end mid-block; the partial remainder rides down
        to the children, so only block-aligned prefixes produce
        digests — the same alignment the router's request chain uses."""
        block = int(block)
        if block <= 0:
            return []
        out = []
        with self._lock:
            empty = np.zeros(0, np.int32)
            stack = [(self._root, b"root", empty)]
            while stack and len(out) < limit:
                node, key, rem = stack.pop()
                if node is self._root:
                    toks = rem
                else:
                    toks = np.concatenate([rem, node.tokens])
                n_full = toks.size // block
                for i in range(n_full):
                    key = _chain_key(key,
                                     toks[i * block:(i + 1) * block])
                    out.append(key.hex()[:ROUTE_DIGEST_HEX])
                    if len(out) >= limit:
                        break
                rem = toks[n_full * block:]
                for child in node.children.values():
                    stack.append((child, key, rem))
        return out


# ---------------------------------------------------------------------------
# Page-granular prefix index (the paged engine's zero-copy counterpart)
# ---------------------------------------------------------------------------


class _PageEntry(object):
    __slots__ = ("pid", "key", "prev", "last_use")

    def __init__(self, pid, key, prev, last_use):
        self.pid = pid          # device page id (index-owned pool ref)
        self.key = key
        self.prev = prev        # parent chain key (eviction bookkeeping)
        self.last_use = last_use


class _TailEntry(object):
    __slots__ = ("pid", "tokens", "last_use")

    def __init__(self, pid, tokens, last_use):
        self.pid = pid
        self.tokens = tokens    # np.int32 [<page_tokens] valid prefix
        self.last_use = last_use


class PagedPrefixHandle(object):
    """A pinned page-granular match: `pages` full device pages holding
    the first len(pages)*page_tokens prompt tokens verbatim, plus an
    optional `partial` (page_id, n_tokens) tail the engine privatizes
    with one copy-on-write page copy. `length` is the total matched
    token count. The handle holds one pool ref per referenced page
    until release()."""

    __slots__ = ("pages", "length", "partial", "_pool", "_released")

    def __init__(self, pool, pages, length, partial):
        self.pages = pages
        self.length = length
        self.partial = partial
        self._pool = pool
        self._released = False


class PagedPrefixIndex(object):
    """Prefix reuse at PAGE granularity over the paged engine's pool —
    the zero-copy successor of the radix tree above (vLLM hash-chain
    lineage). A FULL page of prompt tokens is keyed by the digest chain
    of every page before it plus its own tokens, so a key identifies
    both content and position; a hit points the new slot's block table
    at the SAME device pages (PagedEngine.seed_pages) and no KV bytes
    move. A partially-filled tail page is indexed with its token prefix
    and shared via copy-on-write (the one copy a hit can cost).

    Ownership: the index holds ONE pool ref per registered page, so
    "eviction" is simply dropping that ref — a page a live slot still
    reads survives until its last ref drains, which is what makes
    eviction always safe (no pinned_nodes() dance needed). match()
    additionally refs every returned page for the handle's lifetime so
    an eviction between match and seed cannot free them.
    """

    MAX_TAILS_PER_CHAIN = 4   # bounded CoW candidates per chain point

    def __init__(self, pool, max_pages=None):
        self.pool = pool
        self.page_tokens = pool.page_tokens
        # default budget: the whole pool — the refcounts already keep
        # live pages safe, and unreferenced cached pages are exactly
        # what a KV cache is for
        self.max_pages = int(max_pages) if max_pages else pool.usable_pages
        if self.max_pages < 1:
            raise ValueError("max_pages must be >= 1")
        self._lock = threading.Lock()
        self._full = {}       # chain key -> _PageEntry
        self._tails = {}      # chain key -> [_TailEntry, ...]
        self._clock = 0
        self._evictions = 0
        self._evicted_pages = 0

    @classmethod
    def from_env(cls, pool, default_mb=0):
        """Budget from TPUFLOW_PREFIX_CACHE_MB (page-rounded); 0/unset
        disables — the same opt-in contract as RadixPrefixCache."""
        mb = knobs.get_float("TPUFLOW_PREFIX_CACHE_MB",
                             fallback=default_mb)
        if mb <= 0:
            return None
        pages = max(1, int(mb * 1024 * 1024) // max(1, pool.page_bytes()))
        return cls(pool, max_pages=min(pages, pool.usable_pages))

    @staticmethod
    def _chain(prev_key, tokens):
        # shared with route_digest_chain: a paged replica's published
        # routing digests are literally its cached page keys
        return _chain_key(prev_key, tokens)

    # ---------- lookup ----------

    def match(self, tokens):
        """Longest page-aligned cached prefix of `tokens` (plus at most
        one partial tail page): a pinned PagedPrefixHandle, or None."""
        tokens = _as_tokens(tokens)
        ptok = self.page_tokens
        with self._lock:
            self._clock += 1
            key = b"root"
            pages = []
            n_full = tokens.size // ptok
            for i in range(n_full):
                page = tokens[i * ptok:(i + 1) * ptok]
                key = self._chain(key, page)
                entry = self._full.get(key)
                if entry is None:
                    break
                entry.last_use = self._clock
                pages.append(entry.pid)
            partial = None
            # a tail can only extend a FULLY matched page chain: tail
            # entries hang off the chain key of everything before them
            if len(pages) == n_full:
                rem = tokens[n_full * ptok:]
                if rem.size > 0:
                    best, best_m = None, 0
                    for t in self._tails.get(key, []):
                        m = _common_prefix(t.tokens, rem)
                        if m > best_m:
                            best, best_m = t, m
                    if best is not None:
                        best.last_use = self._clock
                        partial = (best.pid, best_m)
            length = len(pages) * ptok + (partial[1] if partial else 0)
            if length == 0:
                return None
            pinned = list(pages) + ([partial[0]] if partial else [])
            self.pool.ref(pinned)
            return PagedPrefixHandle(self.pool, list(pages), length,
                                     partial)

    def release(self, handle):
        """Drop a match's pins. Idempotent per handle."""
        if handle is None or handle._released:
            return
        handle._released = True
        pinned = list(handle.pages)
        if handle.partial is not None:
            pinned.append(handle.partial[0])
        self.pool.unref(pinned)

    # ---------- insert / evict ----------

    def insert_pages(self, tokens, full_pids, tail_pid=None):
        """Register a finished prompt's pages: full_pids cover the
        len(tokens) // page_tokens complete pages IN ORDER, tail_pid
        (optional) holds the remainder. The index refs every NEWLY
        registered page (dedup: an already-cached chain point keeps its
        existing page — the new slot's copy stays private and drains
        with the slot)."""
        tokens = _as_tokens(tokens)
        ptok = self.page_tokens
        n_full = tokens.size // ptok
        if len(full_pids) < n_full:
            raise ValueError("need %d full pages, got %d"
                             % (n_full, len(full_pids)))
        with self._lock:
            self._clock += 1
            key = b"root"
            for i in range(n_full):
                page = tokens[i * ptok:(i + 1) * ptok]
                prev = key
                key = self._chain(key, page)
                entry = self._full.get(key)
                if entry is not None:
                    entry.last_use = self._clock
                    continue
                pid = int(full_pids[i])
                self.pool.ref([pid])
                self._full[key] = _PageEntry(pid, key, prev, self._clock)
            rem = tokens[n_full * ptok:]
            if rem.size and tail_pid is not None:
                bucket = self._tails.setdefault(key, [])
                covered = any(
                    t.tokens.size >= rem.size
                    and _common_prefix(t.tokens, rem) == rem.size
                    for t in bucket)
                if not covered:
                    self.pool.ref([int(tail_pid)])
                    bucket.append(_TailEntry(int(tail_pid), rem.copy(),
                                             self._clock))
                    if len(bucket) > self.MAX_TAILS_PER_CHAIN:
                        bucket.sort(key=lambda t: t.last_use)
                        old = bucket.pop(0)
                        self.pool.unref([old.pid])
            self._evict_locked()

    # scheduler duck-typing: the radix cache's insert(tokens, kv) has no
    # page-sharing analogue — the scheduler calls insert_pages instead

    def _evict_locked(self):
        over = self._registered_locked() - self.max_pages
        if over <= 0:
            return
        victims = sorted(
            [("full", k, e) for k, e in self._full.items()]
            + [("tail", k, t) for k, ts in self._tails.items()
               for t in ts],
            key=lambda item: item[2].last_use)
        freed = 0
        for kind, key, entry in victims:
            if freed >= over:
                break
            if kind == "full":
                del self._full[key]
            else:
                bucket = self._tails.get(key, [])
                if entry in bucket:
                    bucket.remove(entry)
                    if not bucket:
                        del self._tails[key]
            self.pool.unref([entry.pid])
            freed += 1
        if freed:
            self._evictions += 1
            self._evicted_pages += freed
            telemetry.event("serve.prefix.evict", data={
                "nodes": freed,
                "tokens": freed * self.page_tokens,
                "bytes": freed * self.pool.page_bytes()})

    def _registered_locked(self):
        return len(self._full) + sum(len(ts)
                                     for ts in self._tails.values())

    def clear(self):
        """Drop every registered page ref (drain/shutdown teardown; a
        leak assert after clear() expects the pool fully free)."""
        with self._lock:
            entries = list(self._full.values()) + [
                t for ts in self._tails.values() for t in ts]
            self._full.clear()
            self._tails.clear()
        self.pool.unref([e.pid for e in entries])

    # ---------- introspection ----------

    def registered_pages(self):
        with self._lock:
            return self._registered_locked()

    def stats(self):
        with self._lock:
            full = len(self._full)
            tails = sum(len(ts) for ts in self._tails.values())
            tail_tokens = sum(int(t.tokens.size)
                              for ts in self._tails.values() for t in ts)
        return {
            "pages": full + tails,
            "cached_tokens": full * self.page_tokens + tail_tokens,
            "cached_bytes": (full + tails) * self.pool.page_bytes(),
            "max_bytes": self.max_pages * self.pool.page_bytes(),
            "evictions": self._evictions,
            "evicted_pages": self._evicted_pages,
        }

    def route_digests(self, block=None, limit=512):
        """Routing summary for the fleet router: the cached full-page
        chain keys, most-recently-used first. `block` is ignored — a
        paged index's digest block IS its page size (publish
        page_tokens as route_block alongside these)."""
        with self._lock:
            entries = sorted(self._full.values(),
                             key=lambda e: e.last_use, reverse=True)
        return [e.key.hex()[:ROUTE_DIGEST_HEX]
                for e in entries[:int(limit)]]
