"""Fault-tolerant serving fleet: replica supervisor + health-checked
router with failover re-dispatch and load shedding.

PR 4's single SlotEngine process is one SIGKILL away from an outage.
This tier gives serving the contract the elastic trainer already has
(elastic/supervisor.py): a replica kill costs a retry, not the endpoint.

Topology — one router process fronting N replica workers:

    client ──► FleetRouter (HTTP, this process)
                  │ least-loaded + session-affine dispatch
                  ├──► replica 0  (serving/replica.py subprocess)
                  ├──► replica 1
                  │      ▲ health: /healthz poll + proc liveness
                  └── FleetSupervisor: restart dead replicas with
                      elastic.policy.BackoffPolicy delays

Failover correctness rides the engine's determinism: a request's token
stream is a pure function of (prompt, sampling knobs, seed) via
`request_step_keys`, so when a replica dies mid-request the router
re-issues the SAME request to a survivor and gets the SAME tokens —
already-streamed prefixes are skipped, the client sees one seamless
stream. Requests the dead replica had finished streaming are NOT
re-issued (at-most-once for completed work; re-dispatch until complete
for in-flight work — docs/serving.md#fleet spells out the guarantee).

Load shedding keeps the fleet stable under overload: a bounded fleet
in-flight budget (429 before any replica sees the request), expired
deadlines are rejected before prefill (429), and a draining fleet 503s
new work while in-flight requests finish (SIGTERM drains the router,
then SIGTERMs each replica, which drain their own schedulers).

Env knobs (all optional, read by FleetConfig.from_env):

    TPUFLOW_FLEET_MAX_INFLIGHT      fleet-wide in-flight bound
                                    (default 4x total slots)
    TPUFLOW_FLEET_FAILOVER=0        disable re-dispatch (bench baseline)
    TPUFLOW_FLEET_RESTART=0         disable replica restart
    TPUFLOW_FLEET_MAX_RESTARTS      per-replica restart budget (def 16)
    TPUFLOW_FLEET_HEALTH_INTERVAL_S health poll period (default 1.0)
    TPUFLOW_FLEET_HEALTH_FAILS      consecutive probe failures that
                                    declare a replica dead (default 3)
    TPUFLOW_FLEET_SPAWN_TIMEOUT_S   replica boot budget (default 180)
    TPUFLOW_FLEET_REDISPATCH_MAX    failovers per request (default 3)
    TPUFLOW_FLEET_WAIT_S            max wait for a ready replica before
                                    503 (default 15)

Restart delays come from the shared elastic.policy.BackoffPolicy
(TPUFLOW_RETRY_BACKOFF_*), so a seeded chaos run replays the exact
restart timeline. Telemetry: the fleet.* event set is pinned in
tests/schema_validate.py::FLEET_EVENT_DATA_SCHEMAS.
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import slo as slo_rules_mod
from .. import telemetry
from .. import tracing
from ..elastic.policy import BackoffPolicy


def _env_num(env, name, default, cast=float):
    try:
        return cast(env.get(name, default))
    except (TypeError, ValueError):
        return cast(default)


class FleetConfig(object):
    """Router/supervisor knobs; see the module docstring for the env
    contract."""

    def __init__(self, max_inflight=None, failover=True, restart=True,
                 max_restarts=16, health_interval_s=1.0, health_fails=3,
                 spawn_timeout_s=180.0, redispatch_max=3, wait_s=15.0,
                 backoff=None):
        self.max_inflight = max_inflight  # None: 4x total slots at start
        self.failover = bool(failover)
        self.restart = bool(restart)
        self.max_restarts = int(max_restarts)
        self.health_interval_s = float(health_interval_s)
        self.health_fails = int(health_fails)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.redispatch_max = int(redispatch_max)
        self.wait_s = float(wait_s)
        self.backoff = backoff or BackoffPolicy.from_env()

    @classmethod
    def from_env(cls, env=None):
        env = env if env is not None else os.environ
        max_inflight = env.get("TPUFLOW_FLEET_MAX_INFLIGHT")
        try:
            max_inflight = int(max_inflight) if max_inflight else None
        except ValueError:
            max_inflight = None
        return cls(
            max_inflight=max_inflight,
            failover=env.get("TPUFLOW_FLEET_FAILOVER", "1") != "0",
            restart=env.get("TPUFLOW_FLEET_RESTART", "1") != "0",
            max_restarts=_env_num(env, "TPUFLOW_FLEET_MAX_RESTARTS",
                                  16, int),
            health_interval_s=_env_num(
                env, "TPUFLOW_FLEET_HEALTH_INTERVAL_S", 1.0),
            health_fails=_env_num(env, "TPUFLOW_FLEET_HEALTH_FAILS",
                                  3, int),
            spawn_timeout_s=_env_num(env, "TPUFLOW_FLEET_SPAWN_TIMEOUT_S",
                                     180.0),
            redispatch_max=_env_num(env, "TPUFLOW_FLEET_REDISPATCH_MAX",
                                    3, int),
            wait_s=_env_num(env, "TPUFLOW_FLEET_WAIT_S", 15.0),
        )


class ReplicaHandle(object):
    """Router-side view of one replica worker."""

    def __init__(self, index):
        self.index = index
        self.proc = None        # Popen-like: poll/terminate/kill/wait
        self.host = None
        self.port = None
        self.state = "starting"  # starting|ready|backoff|dead|stopped
        self.generation = 0      # bumps on every (re)spawn
        self.restarts = 0        # restart attempts consumed
        self.inflight = 0        # router-dispatched, not yet returned
        self.dispatched = 0
        self.health_fails = 0
        self.last_stats = {}
        self.restart_at = None   # backoff deadline (monotonic)
        self.t_spawn = None

    @property
    def pid(self):
        return getattr(self.proc, "pid", None)

    def describe(self):
        return {
            "index": self.index, "state": self.state, "pid": self.pid,
            "port": self.port, "inflight": self.inflight,
            "dispatched": self.dispatched, "restarts": self.restarts,
            "generation": self.generation,
            "queue_depth": self.last_stats.get("queue_depth"),
            "occupancy": self.last_stats.get("occupancy"),
        }


class SubprocessReplicaSpawner(object):
    """Default spawner: fork `python -m metaflow_tpu.serving.replica`
    and wait for its port-file (the ready protocol)."""

    def __init__(self, replica_args, workdir=None, env=None,
                 spawn_timeout_s=180.0):
        self.replica_args = list(replica_args)  # sans --port-file/--index
        self.workdir = workdir or tempfile.mkdtemp(prefix="tpuflow-fleet-")
        self.env = env
        self.spawn_timeout_s = float(spawn_timeout_s)

    def __call__(self, index, generation):
        port_file = os.path.join(
            self.workdir, "replica-%d-gen%d.port" % (index, generation))
        log_path = os.path.join(
            self.workdir, "replica-%d-gen%d.log" % (index, generation))
        argv = [sys.executable, "-m", "metaflow_tpu.serving.replica",
                "--port-file", port_file,
                "--replica-index", str(index)] + self.replica_args
        log = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                env=self.env, start_new_session=True)
        finally:
            log.close()
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(port_file):
                try:
                    with open(port_file) as f:
                        info = json.load(f)
                    return proc, info["host"], int(info["port"])
                except (ValueError, KeyError, OSError):
                    pass  # partially visible write; retry
            if proc.poll() is not None:
                raise RuntimeError(
                    "replica %d exited rc=%s during boot (log: %s)"
                    % (index, proc.returncode, log_path))
            time.sleep(0.05)
        proc.kill()
        raise RuntimeError("replica %d did not come up in %.0fs (log: %s)"
                           % (index, self.spawn_timeout_s, log_path))


class _ReplicaBackendError(Exception):
    """The replica connection died or answered garbage mid-request —
    the trigger for failover re-dispatch. Carries the streaming progress
    the relay had made so the re-issue can skip what the client already
    has."""

    def __init__(self, delivered=0, started=False):
        super(_ReplicaBackendError, self).__init__("replica backend lost")
        self.delivered = delivered
        self.started = started


class _ReplicaBusyError(Exception):
    """The replica shed the request (429/503) — try a sibling."""

    def __init__(self, code, body):
        super(_ReplicaBusyError, self).__init__("replica returned %d"
                                                % code)
        self.code = code
        self.body = body


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "tpuflow-fleet/1"

    def log_message(self, fmt, *args):
        pass

    @property
    def fleet(self):
        return self.server.fleet

    def _json(self, code, obj):
        body = json.dumps(obj).encode("utf-8")
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # client gave up (health probes with short timeouts do this
            # routinely while replicas boot) — nothing to answer
            self.close_connection = True

    def do_GET(self):
        if self.path == "/healthz":
            self._json(200, self.fleet.healthz())
            return
        if self.path == "/v1/stats":
            self._json(200, self.fleet.stats())
            return
        self._json(404, {"error": "not found"})

    def do_POST(self):
        if self.path != "/v1/generate":
            self._json(404, {"error": "not found"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, TypeError) as ex:
            self._json(400, {"error": str(ex)})
            return
        self.fleet.handle_generate(self, payload)

    def _chunk(self, data):
        self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))


class ServingFleet(object):
    """N replicas + the router + the supervisor, one object.

    `spawner(index, generation) -> (proc, host, port)` must block until
    the replica's HTTP listener is up; the supervisor then health-checks
    /healthz before marking it ready. The default production spawner is
    SubprocessReplicaSpawner; tests inject in-process fakes.
    """

    def __init__(self, spawner, n_replicas, config=None, host="127.0.0.1",
                 port=0, chaos=None, echo=None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.spawner = spawner
        self.config = config or FleetConfig.from_env()
        self.chaos = chaos
        self.echo = echo or (lambda *_a, **_k: None)
        self.handles = [ReplicaHandle(i) for i in range(n_replicas)]
        self._lock = threading.Lock()
        self._sessions = {}      # session id -> ReplicaHandle
        self._draining = False
        self._stopped = False
        self._done = threading.Event()
        # fleet counters (under _lock)
        self.dispatch_count = 0
        self.failover_count = 0
        self.shed_count = 0
        self.restart_count = 0
        self.completed = 0
        # SLO monitoring: rules come from TPUFLOW_SLO_* / TPUFLOW_SLO_FILE
        # and are re-evaluated by the health loop against replica-reported
        # tail latency + the supervisor's own restart history
        self.slo_rules = slo_rules_mod.load_rules()
        self._slo_breaches = {}       # rule name -> latest breach dict
        self._restart_times = []      # monotonic stamps (under _lock)
        self._httpd = ThreadingHTTPServer((host, port), _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd.fleet = self
        self._threads = []

    # ---------- lifecycle ----------

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def draining(self):
        return self._draining

    def start(self):
        """Spawn every replica (concurrently — boot cost is import +
        warmup), then start the monitor/health/HTTP threads."""
        boot_errors = []

        def _boot(h):
            try:
                self._spawn(h)
            except Exception as ex:
                boot_errors.append((h.index, ex))
                h.state = "dead"

        boots = [threading.Thread(target=_boot, args=(h,), daemon=True)
                 for h in self.handles]
        for t in boots:
            t.start()
        for t in boots:
            t.join()
        if not any(h.state == "ready" for h in self.handles):
            raise RuntimeError("no replica came up: %s"
                               % "; ".join("replica %d: %s" % (i, e)
                                           for i, e in boot_errors))
        for i, ex in boot_errors:
            self.echo("fleet: replica %d failed to boot (%s); the "
                      "supervisor will retry" % (i, ex))
            self._schedule_restart(self.handles[i])
        if self.config.max_inflight is None:
            slots = sum(h.last_stats.get("slots") or 8
                        for h in self.handles if h.state == "ready")
            self.config.max_inflight = max(8, 4 * slots)
        for name, target in (("fleet-monitor", self._monitor_loop),
                             ("fleet-health", self._health_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="fleet-http", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _spawn(self, h):
        h.generation += 1
        h.state = "starting"
        h.t_spawn = time.monotonic()
        telemetry.event("fleet.replica.spawn", data={
            "replica": h.index, "generation": h.generation,
            "restarts": h.restarts})
        proc, host, port = self.spawner(h.index, h.generation)
        h.proc, h.host, h.port = proc, host, port
        # the listener is up; confirm the scheduler answers before
        # taking traffic
        deadline = time.monotonic() + self.config.spawn_timeout_s
        while time.monotonic() < deadline:
            stats = self._probe(h)
            if stats is not None and stats.get("ok"):
                h.last_stats = stats
                h.health_fails = 0
                h.state = "ready"
                telemetry.event("fleet.replica.ready", data={
                    "replica": h.index, "pid": h.pid or 0,
                    "port": h.port,
                    "spawn_ms": round(
                        (time.monotonic() - h.t_spawn) * 1000, 3)})
                self._gauge_ready()
                self.echo("fleet: replica %d ready on %s:%d (pid %s)"
                          % (h.index, h.host, h.port, h.pid))
                return
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        raise RuntimeError("replica %d never answered /healthz" % h.index)

    def _probe(self, h):
        try:
            conn = http.client.HTTPConnection(h.host, h.port, timeout=5)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                if resp.status != 200:
                    return None
                return json.loads(resp.read().decode("utf-8"))
            finally:
                conn.close()
        except (OSError, ValueError):
            return None

    def _gauge_ready(self):
        telemetry.gauge("fleet.replicas_ready",
                        sum(1 for h in self.handles
                            if h.state == "ready"))

    # ---------- supervision ----------

    def _monitor_loop(self):
        while not self._stopped:
            now = time.monotonic()
            for h in self.handles:
                if self._stopped:
                    return
                if h.state == "ready" and h.proc is not None \
                        and h.proc.poll() is not None:
                    self._on_death(h)
                elif h.state == "backoff" and h.restart_at is not None \
                        and now >= h.restart_at:
                    h.restart_at = None
                    try:
                        self._spawn(h)
                    except Exception as ex:
                        self.echo("fleet: replica %d restart failed: %s"
                                  % (h.index, ex))
                        self._schedule_restart(h)
            time.sleep(0.05)

    def slo_metrics(self):
        """Live values for the SLO rule vocabulary (slo.ENV_RULES). The
        fleet tail is the WORST ready replica's rolling percentile — an
        SLO holds only if every replica holds it. A percentile of 0.0
        means the replica's window is empty (no samples yet): such
        replicas do not contribute, and with no samples anywhere the
        metric is absent so its rules are not evaluated."""
        now = time.monotonic()
        with self._lock:
            restarts = [t for t in self._restart_times if now - t <= 60.0]
        metrics = {"replica_restart_rate_per_min": float(len(restarts))}
        for key in ("p99_ttft_ms", "p99_itl_ms", "p50_ttft_ms",
                    "p50_itl_ms"):
            vals = [h.last_stats.get(key) for h in self.handles]
            vals = [float(v) for v in vals
                    if isinstance(v, (int, float)) and v > 0]
            if vals:
                metrics[key] = max(vals)
        return metrics

    def _check_slo(self):
        if not self.slo_rules:
            return
        breaches = slo_rules_mod.evaluate(self.slo_rules,
                                          self.slo_metrics())
        current = {b["rule"]: b for b in breaches}
        for name, breach in current.items():
            if name not in self._slo_breaches:
                # rising edge only: a sustained breach is ONE event, not
                # one per probe interval
                telemetry.event("slo.breach",
                                data=dict(breach, source="fleet"))
                self.echo("fleet: SLO breach: %s %s=%s > %s"
                          % (breach["rule"], breach["metric"],
                             breach["value"], breach["threshold"]))
        self._slo_breaches = current

    def _health_loop(self):
        while not self._stopped:
            time.sleep(self.config.health_interval_s)
            self._check_slo()
            for h in self.handles:
                if self._stopped or self._draining:
                    return
                if h.state != "ready":
                    continue
                stats = self._probe(h)
                if stats is not None and stats.get("ok"):
                    h.last_stats = stats
                    h.health_fails = 0
                elif h.state == "ready":
                    h.health_fails += 1
                    if h.health_fails >= self.config.health_fails:
                        # unresponsive but the process lives: a wedged
                        # replica is dead to the router — take it out
                        # through the same death path
                        self.echo("fleet: replica %d failed %d health "
                                  "probes; killing it"
                                  % (h.index, h.health_fails))
                        try:
                            h.proc.kill()
                        except OSError:
                            pass
                        self._on_death(h)

    def _on_death(self, h):
        with self._lock:
            if h.state in ("dead", "backoff", "stopped"):
                return
            h.state = "dead"
            inflight = h.inflight
            # sticky sessions to a dead replica re-pin on next dispatch
            for sid in [s for s, hh in self._sessions.items() if hh is h]:
                del self._sessions[sid]
        telemetry.event("fleet.replica.dead", data={
            "replica": h.index, "pid": h.pid or 0, "inflight": inflight})
        self._gauge_ready()
        self.echo("fleet: replica %d died (pid %s, %d in flight)"
                  % (h.index, h.pid, inflight))
        if not self._draining:
            self._schedule_restart(h)

    def _schedule_restart(self, h):
        if not self.config.restart:
            return
        if h.restarts >= self.config.max_restarts:
            self.echo("fleet: replica %d out of restart budget (%d)"
                      % (h.index, h.restarts))
            return
        delay = self.config.backoff.delay(h.restarts,
                                          key="replica-%d" % h.index)
        h.restarts += 1
        h.state = "backoff"
        h.restart_at = time.monotonic() + delay
        with self._lock:
            self.restart_count += 1
            self._restart_times.append(time.monotonic())
            del self._restart_times[:-256]
        telemetry.event("fleet.replica.restart", data={
            "replica": h.index, "attempt": h.restarts,
            "delay_s": round(delay, 4)})
        self.echo("fleet: replica %d restarting in %.2fs (attempt %d)"
                  % (h.index, delay, h.restarts))

    def kill_replica(self, index, sig=signal.SIGKILL):
        """Chaos hook: deliver a REAL process kill to replica `index`.
        The monitor observes the death exactly as it would a prod
        reclaim; relay threads fail over organically."""
        h = self.handles[index]
        proc = h.proc
        if proc is None:
            return False
        if hasattr(proc, "send_signal"):
            try:
                proc.send_signal(sig)
                return True
            except OSError:
                return False
        proc.kill()
        return True

    # ---------- dispatch ----------

    def _pick(self, session, exclude):
        with self._lock:
            ready = [h for h in self.handles
                     if h.state == "ready" and h not in exclude]
            if not ready:
                return None
            if session is not None:
                pinned = self._sessions.get(session)
                if pinned is not None and pinned in ready:
                    pinned.inflight += 1
                    return pinned
            h = min(ready, key=lambda r: (
                r.inflight, r.last_stats.get("queue_depth") or 0,
                r.index))
            if session is not None:
                self._sessions[session] = h
            h.inflight += 1
            return h

    def _wait_for_ready(self, deadline_s, exclude):
        """Block (bounded) for a ready replica: a fleet mid-restart
        should queue briefly, not 503 the world."""
        end = time.monotonic() + deadline_s
        while time.monotonic() < end and not self._draining \
                and not self._stopped:
            with self._lock:
                if any(h.state == "ready" and h not in exclude
                       for h in self.handles):
                    return True
                if not any(h.state in ("starting", "backoff")
                           for h in self.handles):
                    return False  # nothing will ever become ready
            time.sleep(0.05)
        return False

    def _shed(self, handler, request_id, reason, code, message):
        with self._lock:
            self.shed_count += 1
        telemetry.event("fleet.request.shed", data={
            "request_id": str(request_id), "reason": reason})
        handler._json(code, {"error": message, "reason": reason})

    def handle_generate(self, handler, payload):
        request_id = payload.get("request_id") or \
            "fleet-%d" % (id(payload) & 0xFFFFFF)
        session = payload.get("session")
        stream = bool(payload.get("stream", False))
        # the router is where a request's trace begins: mint the root
        # traceparent here (or adopt the client's) so every dispatch
        # attempt — including failover re-dispatch — carries a child
        # span of the same trace to its replica
        root_tp = handler.headers.get("Traceparent") or None
        if root_tp is None and tracing.trace_requests_enabled():
            root_tp = tracing.request_traceparent(str(request_id))
        trace_id, root_span = tracing.traceparent_ids(root_tp)
        attempt_span = ""
        deadline = None
        if payload.get("deadline_ms") is not None:
            try:
                deadline = time.monotonic() \
                    + float(payload["deadline_ms"]) / 1000.0
            except (TypeError, ValueError):
                handler._json(400, {"error": "bad deadline_ms"})
                return
        # ---- admission: shed before any replica spends prefill ----
        if self._draining or self._stopped:
            self._shed(handler, request_id, "draining", 503,
                       "fleet is draining")
            return
        if deadline is not None and deadline <= time.monotonic():
            self._shed(handler, request_id, "deadline", 429,
                       "deadline already expired")
            return
        with self._lock:
            total_inflight = sum(h.inflight for h in self.handles)
            if self.config.max_inflight is not None \
                    and total_inflight >= self.config.max_inflight:
                full = True
            else:
                full = False
        if full:
            self._shed(handler, request_id, "queue_full", 429,
                       "fleet in-flight budget exhausted")
            return

        delivered = 0      # tokens already streamed to the client
        started = False    # status line sent (streaming path)
        attempts = 0
        tried_busy = set()
        exclude = set()
        while True:
            if deadline is not None and deadline <= time.monotonic() \
                    and delivered == 0:
                self._shed(handler, request_id, "deadline", 429,
                           "deadline expired before dispatch")
                return
            h = self._pick(session, exclude | tried_busy)
            if h is None:
                wait = self.config.wait_s
                if deadline is not None:
                    wait = min(wait, max(0.0,
                                         deadline - time.monotonic()))
                if self._wait_for_ready(wait, exclude | tried_busy):
                    continue
                if started:
                    handler.close_connection = True
                    return
                self._shed(handler, request_id, "no_replica", 503,
                           "no ready replica")
                return
            with self._lock:
                self.dispatch_count += 1
                n_dispatch = self.dispatch_count
                h.dispatched += 1
            attempt_tp = None
            dispatch_data = {
                "request_id": str(request_id), "replica": h.index,
                "dispatch": n_dispatch}
            if trace_id:
                attempt_tp = tracing.child_traceparent(
                    root_tp, "dispatch-%d" % n_dispatch)
                attempt_span = tracing.traceparent_ids(attempt_tp)[1]
                dispatch_data["trace"] = trace_id
                dispatch_data["span"] = attempt_span
                dispatch_data["parent_span"] = root_span
            telemetry.event("fleet.request.dispatch", data=dispatch_data)
            if self.chaos is not None:
                victim = self.chaos.on_dispatch(n_dispatch,
                                                len(self.handles))
                if victim is not None:
                    self.kill_replica(victim)
            try:
                done, delivered, started = self._relay(
                    handler, h, payload, request_id, stream, delivered,
                    traceparent=attempt_tp)
                with self._lock:
                    h.inflight = max(0, h.inflight - 1)
                    if done:
                        self.completed += 1
                return
            except _ReplicaBusyError as ex:
                with self._lock:
                    h.inflight = max(0, h.inflight - 1)
                tried_busy.add(h)
                if len(tried_busy) >= len(self.handles):
                    self._shed(handler, request_id, "queue_full",
                               ex.code, "every replica shed the request")
                    return
                continue
            except _ReplicaBackendError as ex:
                with self._lock:
                    h.inflight = max(0, h.inflight - 1)
                delivered, started = ex.delivered, ex.started
                exclude = {h}
                if not self.config.failover:
                    if started:
                        handler.close_connection = True
                    else:
                        self._shed(handler, request_id, "replica_lost",
                                   502, "replica died (failover "
                                   "disabled)")
                    return
                attempts += 1
                if attempts > self.config.redispatch_max:
                    if started:
                        handler.close_connection = True
                    else:
                        self._shed(handler, request_id,
                                   "failover_exhausted", 502,
                                   "re-dispatch budget exhausted")
                    return
                with self._lock:
                    self.failover_count += 1
                failover_data = {
                    "request_id": str(request_id),
                    "from_replica": h.index, "attempt": attempts,
                    "delivered": delivered}
                if trace_id:
                    # span = the attempt that died, so the assembler can
                    # close the victim's delivered-prefix span and parent
                    # the successor under the same request
                    failover_data["trace"] = trace_id
                    failover_data["span"] = attempt_span
                telemetry.event("fleet.request.failover",
                                data=failover_data)
                continue
            except (BrokenPipeError, ConnectionResetError):
                # the CLIENT went away: nothing to re-dispatch
                with self._lock:
                    h.inflight = max(0, h.inflight - 1)
                handler.close_connection = True
                return

    def _relay(self, handler, h, payload, request_id, stream, delivered,
               traceparent=None):
        """Forward one dispatch attempt; returns (done, delivered,
        started). Raises _ReplicaBackendError (carrying progress) on
        replica death."""
        # always ask the replica to stream: the router must observe
        # token-by-token progress to resume a partially-streamed request
        # on a survivor without duplicating output
        fwd = dict(payload)
        fwd["stream"] = True
        fwd["request_id"] = str(request_id)
        fwd.pop("session", None)
        body = json.dumps(fwd).encode("utf-8")
        started = delivered > 0

        def backend(fn):
            # replica-side I/O only: a socket reset HERE is a replica
            # loss (failover), never a client disconnect — client-side
            # wfile errors propagate to handle_generate unwrapped
            try:
                return fn()
            except (http.client.HTTPException, OSError, ValueError):
                raise _ReplicaBackendError(delivered, started)

        headers = {"Content-Type": "application/json"}
        if traceparent:
            # per-attempt trace context: the replica stamps this span
            # into its serve.request.* records
            headers["Traceparent"] = traceparent
        conn = http.client.HTTPConnection(h.host, h.port, timeout=300)
        try:
            backend(lambda: conn.request(
                "POST", "/v1/generate", body=body, headers=headers))
            resp = backend(conn.getresponse)
            if resp.status in (429, 503):
                raise _ReplicaBusyError(
                    resp.status,
                    backend(resp.read).decode("utf-8", "replace"))
            if resp.status != 200:
                # non-retryable replica verdict (400 oversized etc):
                # relay it verbatim
                data = backend(resp.read)
                handler.send_response(resp.status)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Content-Length", str(len(data)))
                handler.end_headers()
                handler.wfile.write(data)
                return (False, delivered, started)
            tokens = []
            terminal = None
            index = delivered
            skip = delivered
            while True:
                line = backend(resp.readline)
                if not line:
                    raise _ReplicaBackendError(delivered, started)
                line = line.strip()
                if not line:
                    continue
                item = backend(
                    lambda: json.loads(line.decode("utf-8")))
                if item.get("done"):
                    if item.get("reason") == "shutdown":
                        # the replica hard-stopped mid-request: its
                        # scheduler flushed in-flight work as 'shutdown'
                        # before the process died — incomplete output,
                        # a replica loss, not a result
                        raise _ReplicaBackendError(delivered, started)
                    terminal = item
                    break
                if skip > 0:
                    # token-identical re-issue: the survivor
                    # regenerates the prefix the client already has
                    skip -= 1
                    continue
                tokens.append(item["token"])
                if stream:
                    if not started:
                        handler.send_response(200)
                        handler.send_header("Content-Type",
                                            "application/jsonl")
                        handler.send_header("Transfer-Encoding",
                                            "chunked")
                        handler.end_headers()
                        started = True
                    handler._chunk(json.dumps(
                        {"token": item["token"],
                         "index": index}).encode() + b"\n")
                    handler.wfile.flush()
                    index += 1
                    delivered += 1
        finally:
            try:
                conn.close()
            except OSError:
                pass
        # terminal reached: close out the client response
        new_tokens = terminal.get("new_tokens", tokens)
        if stream:
            if not started:
                handler.send_response(200)
                handler.send_header("Content-Type", "application/jsonl")
                handler.send_header("Transfer-Encoding", "chunked")
                handler.end_headers()
                started = True
            handler._chunk(json.dumps(
                {"done": True, "reason": terminal.get("reason"),
                 "new_tokens": new_tokens}).encode() + b"\n")
            handler._chunk(b"")
            handler.wfile.flush()
        else:
            prompt = payload.get("tokens") or []
            handler._json(200, {
                "id": str(request_id),
                "tokens": list(prompt) + list(new_tokens),
                "new_tokens": new_tokens,
                "reason": terminal.get("reason"),
                "usage": {"prompt_tokens": len(prompt),
                          "new_tokens": len(new_tokens)},
                "replica": h.index,
            })
        return (True, delivered, started)

    # ---------- introspection ----------

    def healthz(self):
        ready = sum(1 for h in self.handles if h.state == "ready")
        with self._lock:
            inflight = sum(h.inflight for h in self.handles)
        metrics = self.slo_metrics()
        breaches = list(self._slo_breaches.values())
        return {
            "ok": ready > 0 and not self._draining,
            "draining": self._draining,
            "replicas": [h.describe() for h in self.handles],
            "ready": ready,
            "inflight": inflight,
            # fleet tail latency (worst ready replica; null = no samples)
            "p99_ttft_ms": metrics.get("p99_ttft_ms"),
            "p99_itl_ms": metrics.get("p99_itl_ms"),
            # SLO breach state: what `tpuflow watch --check` and external
            # monitors gate on without reading telemetry
            "slo": {"breached": bool(breaches), "breaches": breaches},
        }

    def stats(self):
        with self._lock:
            return {
                "replicas": [h.describe() for h in self.handles],
                "dispatched": self.dispatch_count,
                "completed": self.completed,
                "failovers": self.failover_count,
                "shed": self.shed_count,
                "restarts": self.restart_count,
                "inflight": sum(h.inflight for h in self.handles),
                "max_inflight": self.config.max_inflight,
                "draining": self._draining,
            }

    # ---------- shutdown ----------

    def install_signal_handlers(self):
        def _on_signal(_sig, _frame):
            threading.Thread(target=self.shutdown, daemon=True).start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, _on_signal)
            except ValueError:
                pass  # not the main thread (tests)

    def serve_forever(self):
        self.install_signal_handlers()
        try:
            self._done.wait()
        except KeyboardInterrupt:
            self.shutdown()

    def shutdown(self, timeout=60.0):
        """Graceful fleet drain: 503 new work, let in-flight relays
        finish, then SIGTERM each replica (they drain their own
        schedulers) and reap the processes."""
        self._draining = True
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._lock:
                if all(h.inflight == 0 for h in self.handles):
                    break
            time.sleep(0.05)
        for h in self.handles:
            h.state = "stopped"
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass
        for h in self.handles:
            if h.proc is not None:
                try:
                    h.proc.wait(timeout=max(1.0,
                                            end - time.monotonic()))
                except Exception:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
        self._stopped = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._done.set()
        return True

    def close(self):
        """Hard stop (tests): kill everything now."""
        self._draining = True
        self._stopped = True
        for h in self.handles:
            h.state = "stopped"
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.kill()
                except OSError:
                    pass
        self._httpd.shutdown()
        self._httpd.server_close()
        self._done.set()
